"""TLS + bearer auth on the replica serving endpoint (ISSUE 12
satellite): the data plane hardened for exposure beyond loopback.

Same discipline as the extender (scheduler/server.py): TLS wraps the
listening socket with the handshake deferred to the handler thread,
bearer auth gates the privileged verbs — here that is ALL of ``/v1/*``
(submit/cancel/export/import/state move KV bytes and cancel sequences)
while ``/healthz`` and ``/metrics`` stay open for probes and scrapes.
``importorskip("cryptography")``-guarded: tier-1 stays clean without
the dep (the TLS material comes from testing/tlsutil).
"""

import pytest

cryptography = pytest.importorskip("cryptography")

import http.client  # noqa: E402
import json  # noqa: E402
import types  # noqa: E402

from kubegpu_tpu.gateway import (  # noqa: E402
    HttpReplicaClient,
    ReplicaServer,
    SimBatcher,
)
from kubegpu_tpu.gateway.client import sim_stream_seed  # noqa: E402
from kubegpu_tpu.testing.tlsutil import make_self_signed  # noqa: E402

TOKEN = "replica-secret-token"


def _req(rid, prompt, budget, sink=None):
    return types.SimpleNamespace(
        request_id=rid, prompt=prompt, max_new_tokens=budget,
        temperature=0.0, session=None, on_tokens=sink,
    )


@pytest.fixture
def tls_server(tmp_path):
    cert, key = make_self_signed(str(tmp_path))
    srv = ReplicaServer(
        SimBatcher(slots=4), step_delay_s=0.001,
        tls_cert=cert, tls_key=key, auth_token=TOKEN,
    ).start()
    yield srv, cert
    srv.stop()


def test_tls_auth_stream_token_identical(tls_server):
    """The happy path over HTTPS + bearer: a stream serves exactly the
    mill's deterministic tokens, and the registry probe (open /healthz)
    works through the same TLS transport."""
    srv, cert = tls_server
    client = HttpReplicaClient(
        endpoints={"r": srv.endpoint}, tls_ca=cert, auth_token=TOKEN,
    )
    try:
        deltas = []
        a = client.submit(
            "r", _req("t1", [1, 2, 3], 8,
                      sink=lambda at, d: deltas.append(d))
        )
        assert a.wait(20) and a.result().ok, a.result()
        seed = sim_stream_seed([1, 2, 3])
        expect = [(seed * 31 + i) % 256 for i in range(8)]
        assert a.result().tokens == expect
        assert sum(deltas, []) == expect
        # /v1/state is gated but this client carries the token
        state = client._get_state("r")
        assert state is not None and state["tp"] == 1
        # probe: /healthz over TLS, no auth required
        ok, why = client.probe(
            types.SimpleNamespace(key="r", addr=None)
        )
        assert ok, why
    finally:
        client.stop()


def test_missing_or_wrong_token_is_unauthorized(tls_server):
    srv, cert = tls_server
    bad = HttpReplicaClient(
        endpoints={"r": srv.endpoint}, tls_ca=cert,
        auth_token="not-the-token",
    )
    tokenless = HttpReplicaClient(
        endpoints={"r": srv.endpoint}, tls_ca=cert,
    )
    try:
        for client in (bad, tokenless):
            a = client.submit("r", _req("t2", [1], 4))
            assert a.wait(20), "attempt hung on 401"
            res = a.result()
            assert not res.ok and "401" in res.error, res
            # the gated read surface refuses too
            assert client._get_state("r") is None
            # but liveness stays open: a token-skewed prober must not
            # drain the replica
            ok, why = client.probe(
                types.SimpleNamespace(key="r", addr=None)
            )
            assert ok, why
        # nothing above admitted work
        assert srv.loop.active_streams() == 0
    finally:
        bad.stop()
        tokenless.stop()


def test_plain_http_client_against_tls_server_fails_cleanly(tls_server):
    """A cleartext client meeting the TLS socket is a RESULT (refused
    attempt), never a hang — the gateway's failover treats it like any
    unreachable replica."""
    srv, _ = tls_server
    client = HttpReplicaClient(endpoints={"r": srv.endpoint})
    try:
        a = client.submit("r", _req("t3", [2], 4))
        assert a.wait(20), "cleartext-vs-TLS attempt hung"
        assert not a.result().ok
    finally:
        client.stop()


def test_plain_server_still_works_without_tls_knobs(tmp_path):
    """Regression guard: the default (no cert/key/token) stays plain
    HTTP with open verbs — loopback soaks and single-tenant pods keep
    their zero-config path."""
    srv = ReplicaServer(SimBatcher(slots=2), step_delay_s=0.001).start()
    try:
        host, port = srv.address
        conn = http.client.HTTPConnection(host, port, timeout=5.0)
        conn.request("GET", "/v1/state")
        resp = conn.getresponse()
        assert resp.status == 200
        assert json.loads(resp.read())["tp"] == 1
        conn.close()
    finally:
        srv.stop()


def test_auth_gates_migration_verbs(tls_server):
    """/v1/export and /v1/import move KV pages — the verbs a stolen
    podIP must not reach: 401 without the bearer, normal verb-level
    errors (not auth errors) with it."""
    srv, cert = tls_server
    host_port = srv.endpoint
    with_token = HttpReplicaClient(
        endpoints={"r": host_port}, tls_ca=cert, auth_token=TOKEN,
    )
    without = HttpReplicaClient(
        endpoints={"r": host_port}, tls_ca=cert,
    )
    try:
        # tokenless export: refused at the door
        assert without._wire_export(host_port, {"stream": [1, 2]}) is None
        # authorized export of a never-seen stream: the verb RUNS (the
        # SimBatcher has no sealed chains, so the payload is null — an
        # answer, not a 401)
        conn = with_token._connect(host_port, timeout=5.0)
        conn.request(
            "POST", "/v1/export", json.dumps({"stream": [1, 2]}),
            with_token._headers({"Content-Type": "application/json"}),
        )
        resp = conn.getresponse()
        assert resp.status == 200
        assert json.loads(resp.read())["payload"] is None
        conn.close()
    finally:
        with_token.stop()
        without.stop()
