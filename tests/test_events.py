"""v1 Event emission: the operator-facing record of scheduler decisions
(kubectl-describe parity with kube-scheduler's Scheduled/FailedScheduling/
Preempted convention)."""

import pytest

from kubegpu_tpu.plugins import Advertiser, FakeSlice
from kubegpu_tpu.scheduler import Scheduler
from kubegpu_tpu.types import RES_TPU, annotations
from kubegpu_tpu.utils import InMemoryApiServer
from kubegpu_tpu.utils.events import EventRecorder
from kubegpu_tpu.utils.metrics import Metrics


def fake_cluster(mesh=(4, 4)):
    api = InMemoryApiServer()
    fs = FakeSlice(slice_id="s0", mesh_shape=mesh, host_block=(2, 2))
    advs = {h: Advertiser(p, api) for h, p in fs.providers().items()}
    for a in advs.values():
        a.advertise_once()
    sched = Scheduler(api, metrics=Metrics())
    sched.cache.refresh()
    return api, fs, advs, sched


def pod_obj(name, chips, group=None, size=1, priority=0):
    ann = {}
    if group:
        ann[annotations.POD_GROUP] = group
        ann[annotations.POD_GROUP_SIZE] = str(size)
    if priority:
        ann[annotations.POD_PRIORITY] = str(priority)
    return {
        "metadata": {"name": name, "namespace": "default",
                     "uid": f"uid-{name}", "annotations": ann},
        "spec": {"containers": [
            {"name": "m", "resources": {"limits": {RES_TPU: str(chips)}}}]},
    }


def reasons(api, name=None):
    return [
        e["reason"]
        for e in api.list_events()
        if name is None or e["involvedObject"]["name"] == name
    ]


def schedule(api, sched, obj):
    nodes = sorted(n["metadata"]["name"] for n in api.list_nodes())
    r = sched.filter(obj, nodes)
    assert r.nodes, r.failed
    assert sched.bind("default", obj["metadata"]["name"], r.nodes[0]) is None


def test_gang_schedule_emits_planned_and_assigned():
    api, _, _, sched = fake_cluster()
    for i in range(2):
        api.create_pod(pod_obj(f"g{i}", 4, group="ring", size=2))
    for i in range(2):
        schedule(api, sched, api.get_pod("default", f"g{i}"))
    assert "GangPlanned" in reasons(api, "g0")  # first member planned it
    for i in range(2):
        assert "DeviceAssigned" in reasons(api, f"g{i}")
    assigned = [e for e in api.list_events() if e["reason"] == "DeviceAssigned"]
    assert all(e["type"] == "Normal" for e in assigned)
    assert "4 TPU chip(s)" in assigned[0]["message"]
    assert assigned[0]["involvedObject"]["uid"] == "uid-g0"
    assert assigned[0]["source"]["component"] == "kubegpu-tpu-scheduler"


def test_unschedulable_gang_emits_warning_once():
    api, _, _, sched = fake_cluster()
    obj = pod_obj("w0", 4, group="big", size=9)  # member count can't arrive
    api.create_pod(obj)
    nodes = sorted(n["metadata"]["name"] for n in api.list_nodes())
    for _ in range(5):  # kube-scheduler retries; dedup must absorb them
        assert not sched.filter(obj, nodes).nodes
    warnings = [e for e in api.list_events() if e["reason"] == "GangUnschedulable"]
    assert len(warnings) == 1
    assert warnings[0]["type"] == "Warning"
    assert "waiting for members" in warnings[0]["message"]


def test_preemption_and_chip_failure_emit_warnings():
    api, fs, advs, sched = fake_cluster()
    victim = pod_obj("victim", 4, priority=1)
    api.create_pod(victim)
    schedule(api, sched, victim)
    # fill the rest so the vip needs a preemption
    for i in range(3):
        filler = pod_obj(f"f{i}", 4, priority=1)
        api.create_pod(filler)
        schedule(api, sched, filler)
    vip = pod_obj("vip", 4, priority=9)
    api.create_pod(vip)
    schedule(api, sched, vip)
    pre = [e for e in api.list_events() if e["reason"] == "Preempted"]
    assert len(pre) == 1 and pre[0]["type"] == "Warning"
    assert "default/vip" in pre[0]["message"]

    # now kill a chip under the vip and resync: ChipFailure eviction event
    a = annotations.assignment_from_pod(api.get_pod("default", "vip"))
    fs.kill_chip(a.all_chips()[0].coords)
    for adv in advs.values():
        adv.advertise_once()
    sched.resync()
    chip = [e for e in api.list_events() if e["reason"] == "ChipFailure"]
    assert len(chip) == 1
    assert chip[0]["involvedObject"]["name"] == "vip"


def test_recorder_swallows_api_failures():
    class ExplodingApi:
        def create_event(self, obj):
            raise OSError("api down")

    rec = EventRecorder(ExplodingApi())
    rec.pod_event("default", "p", "Reason", "msg")  # must not raise

    class NoEventsApi:
        def create_event(self, obj):
            raise NotImplementedError

    EventRecorder(NoEventsApi()).pod_event("default", "p", "Reason", "msg")


def test_dedup_expires_and_reemits():
    api = InMemoryApiServer()
    rec = EventRecorder(api, dedup_s=0.0)
    rec.pod_event("default", "p", "R", "m")
    rec.pod_event("default", "p", "R", "m")
    assert len(api.list_events()) == 2  # zero window: every emission lands
    rec2 = EventRecorder(api, dedup_s=300.0)
    rec2.pod_event("default", "q", "R", "m")
    rec2.pod_event("default", "q", "R", "m")
    assert len([e for e in api.list_events()
                if e["involvedObject"]["name"] == "q"]) == 1


def test_long_pod_name_event_stays_within_dns1123():
    """ADVICE r3 low: event names are pod name + nanosecond suffix; for
    pod names near the 253-char DNS-1123 limit the suffix pushed the name
    over and a real API server 422s — silently dropping the record
    exactly for long-named pods.  The prefix is truncated instead."""
    api = InMemoryApiServer()
    rec = EventRecorder(api)
    long_name = "p" * 253  # at the subdomain limit already
    rec.pod_event("default", long_name, "Tested", "msg", uid="u1")
    events = api.list_events()
    assert len(events) == 1
    ev_name = events[0]["metadata"]["name"]
    assert len(ev_name) <= 253
    # still unique-suffixed and still attributable to the pod
    assert "." in ev_name and ev_name.startswith("p" * 100)
    assert events[0]["involvedObject"]["name"] == long_name
