"""Prefill/decode disaggregation (ISSUE 17): role-split replicas with
post-prefill KV handoff over the migration verbs.

Layers under test:

- the batcher parking contract — ``prefill_only`` parks a sequence the
  moment its prompt pages seal (zero tokens emitted), ``drain_sealed``
  announces it exactly once, ``set_prefill_only(False)`` unparks
  locally, and imported sequences DECODE even in prefill-only mode (the
  fallback resume path);
- phase-aware routing — new admissions prefer prefill-role replicas,
  fall back to flex, and never strand on an all-decode candidate list;
- the gateway handoff — a sealed signal triggers an export→import
  transfer to a decode-side replica, the caller's stream is
  UNINTERRUPTED, and fp32 token identity holds disaggregated ≡
  co-located across page sizes × {fp32, int8} pools × speculation
  on/off, at exact page-boundary and sub-page prompt lengths;
- the fallback contract — a refused or dead importer resumes decode ON
  the prefill replica (counted ``fallback``, never a request error),
  and collapse (``set_disaggregation(False)``) unparks locally;
- the controller's ratio actuator — TTFT pressure converts flex →
  prefill, ITL pressure converts back, a failing handoff path collapses
  the fleet to co-located and re-arms after clean ticks;
- the role surfaces — worker ``/v1/state`` advertises the role, POST
  ``/v1/role`` flips it live, the registry reads POD_ROLE;
- GatewaySoak ``disaggregation=True`` — the kill/refuse/
  kill-mid-migration schedule lands on both ends of the handoff path
  with I5 and both-end page accounting intact;
- the streamed seal-time pipeline (ISSUE 18) — ``export_sealed_delta``
  ships sealed prompt pages DURING prefill compute, the decode side
  stages them content-addressed (a refused delta rolls back to the last
  consistent prefix, atomically), the final handoff exports only layers
  ≥ the acked cursor, acked pages are reclaimed on the prefill replica
  at seal time (raising admission concurrency mid-schedule), parked
  sequences leave the token-budget denominator, and streamed ≡ one-shot
  ≡ co-located token identity holds across the same page-size × dtype ×
  speculation grid.
"""

import json
import time
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubegpu_tpu.models import TransformerLM
from kubegpu_tpu.models.paging import PagedContinuousBatcher

CFG = dict(vocab_size=64, num_layers=2, num_heads=8, hidden=32, max_seq=64)


@pytest.fixture(scope="module")
def params():
    model = TransformerLM(dtype=jnp.float32, **CFG)
    return model.init(
        jax.random.PRNGKey(0), jnp.ones((2, 8), jnp.int32)
    )["params"]


def make_paged(params, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("prompt_pad", 16)
    kw.setdefault("page_size", 4)
    kw.setdefault("pool_pages", 48)
    kw.setdefault("decode_page_cache", "fp32")
    return PagedContinuousBatcher(
        params, dtype=jnp.float32, **CFG, **kw
    )


def spec_kw(params, k=2):
    return dict(
        draft_params=params, speculate_k=k,
        draft_num_layers=CFG["num_layers"],
        draft_num_heads=CFG["num_heads"], draft_hidden=CFG["hidden"],
    )


PROMPT = [3, 1, 4, 1, 5, 9, 2, 6]        # 2 exact pages at page_size=4
SUBPAGE_PROMPT = [3, 1, 4]               # under one page


# ---------------------------------------------------------------------------
# batcher parking contract (no jax: SimBatcher twins)
# ---------------------------------------------------------------------------

def test_simbatcher_prefill_only_parks_and_announces_once():
    from kubegpu_tpu.gateway import SimBatcher

    b = SimBatcher(slots=4, vocab=97, prefill_only=True)
    b.submit(5, [1, 2, 3], 10)
    b.serve_step()
    assert b.drain_sealed() == [5]
    assert b.drain_sealed() == []            # announced exactly once
    for _ in range(3):
        b.serve_step()
    assert b.live_tokens() == {5: []}        # parked: zero tokens emitted
    # unpark locally: the collapse rung must never strand a stream
    assert b.set_prefill_only(False)
    out = {}
    while b.has_work():
        out.update(b.serve_step())
    assert out[5] == [(5 * 31 + i) % 97 for i in range(10)]


def test_simbatcher_imported_sequence_decodes_in_prefill_mode():
    from kubegpu_tpu.gateway import SimBatcher

    src = SimBatcher(slots=2, vocab=97, prefill_only=True)
    src.submit(1, [1, 2], 8)
    src.serve_step()
    assert src.drain_sealed() == [1]
    payload = src.export_pages(1)
    src.cancel(1)
    # the fallback contract: re-import into the SAME prefill-only
    # batcher — the sequence must decode, not re-park
    src.import_pages(9, payload)
    out = {}
    while src.has_work():
        out.update(src.serve_step())
    assert out[9] == [(1 * 31 + i) % 97 for i in range(8)]


def test_paged_prefill_only_parks_at_seal(params):
    """The real batcher: a prefill-only admission chunk-prefills, seals
    its prompt pages, and PARKS with zero tokens emitted; exporting and
    importing into a decode twin finishes token-identical; flipping the
    mode off unparks locally instead."""
    ref = make_paged(params).run([np.asarray(PROMPT, np.int32)], [10])[0]
    src = make_paged(params, prefill_only=True)
    dst = make_paged(params)
    src.submit(1, np.asarray(PROMPT, np.int32), 10)
    deadline = time.monotonic() + 30
    sealed = []
    while not sealed and time.monotonic() < deadline:
        src.serve_step()
        sealed = src.drain_sealed()
    assert sealed == [1]
    s = next(s for s in src._seqs if s.seq_id == 1)
    assert s.parked and len(s.tokens) == 0   # zero tokens emitted
    payload = src.export_pages(1)
    src.cancel(1)
    src.assert_page_accounting()
    dst.import_pages(11, payload)
    out = {}
    while dst.has_work():
        out.update(dst.serve_step())
    assert out[11] == ref
    dst.assert_page_accounting()

    # the collapse leg: park, then flip the mode off — local unpark
    src.submit(2, np.asarray(PROMPT, np.int32), 10)
    while not src.drain_sealed():
        src.serve_step()
    assert src.set_prefill_only(False)
    out = {}
    while src.has_work():
        out.update(src.serve_step())
    assert out[2] == ref
    src.assert_page_accounting()


# ---------------------------------------------------------------------------
# phase-aware routing
# ---------------------------------------------------------------------------

def test_router_prefers_prefill_candidates():
    from types import SimpleNamespace

    from kubegpu_tpu.gateway.router import _phase_candidates

    def rep(key, role):
        return SimpleNamespace(key=key, role=role)

    pre, dec, flex = rep("a", "prefill"), rep("b", "decode"), rep("c", "flex")
    # prefill replicas win the prefill phase outright
    assert _phase_candidates([dec, flex, pre]) == [pre]
    # no prefill: flex serves both phases, decode stays decode-side
    assert _phase_candidates([dec, flex]) == [flex]
    # all-decode fleet: availability beats purity
    assert _phase_candidates([dec]) == [dec]
    # uniform flex fleet passes through unchanged
    assert _phase_candidates([flex, flex]) == [flex, flex]


# ---------------------------------------------------------------------------
# gateway stack helpers
# ---------------------------------------------------------------------------

def _disagg_stack(n_replicas, batcher_factory, roles, policy=None,
                  dispatchers=2):
    from kubegpu_tpu.gateway import (
        AdmissionQueue, FailoverPolicy, Gateway, InMemoryReplicaClient,
    )
    from kubegpu_tpu.testing.fake_serving import build_fake_serving_stack
    from kubegpu_tpu.utils.metrics import Metrics

    stack = build_fake_serving_stack(
        n_replicas, metrics=Metrics(), roles=roles,
    )
    client = InMemoryReplicaClient(
        batcher_factory=batcher_factory, step_delay_s=0.0,
    )
    stack.registry.subscribe(client.sync_live)
    gw = Gateway(
        stack.registry, client, queue=AdmissionQueue(capacity=32),
        policy=policy or FailoverPolicy(
            deadline_s=120.0, hedge_after_s=60.0, max_attempts=4,
        ),
        metrics=Metrics(), dispatchers=dispatchers,
    )
    stack.registry.refresh()
    for rep in stack.registry.live():
        if rep.role == "prefill":
            client.set_role(rep.key, "prefill")
    gw.start()
    return stack, client, gw


def _pools_balanced(client):
    with client._lock:
        batchers = [w.batcher for w in client._workers.values()]
    for b in batchers:
        check = getattr(b, "assert_page_accounting", None)
        if check is not None:
            check()
    return batchers


# ---------------------------------------------------------------------------
# fp32 token identity: disaggregated == co-located
# ---------------------------------------------------------------------------

def _identity_case(params, prompt, budget, streamed=True,
                   expect_streamed=None, **paged_kw):
    """``streamed`` flips the seal-watch knob; ``expect_streamed``
    (default: follows the knob) is what the handoff should have DONE —
    a sub-page prompt seals zero full pages before parking, so it
    legitimately degrades to one-shot even with streaming on."""
    from kubegpu_tpu.gateway import GatewayRequest

    if expect_streamed is None:
        expect_streamed = streamed
    ref = make_paged(params, **paged_kw).run(
        [np.asarray(prompt, np.int32)], [budget]
    )[0]
    stack, client, gw = _disagg_stack(
        2, lambda key: make_paged(params, **paged_kw),
        roles=("prefill", "flex"),
    )
    try:
        if not streamed:
            # the one-shot comparison lane: the seal-watch never ships
            # deltas, the whole payload rides the critical-path hop
            gw.dispatcher.stream_handoff = False
        p = gw.submit(GatewayRequest(
            prompt=list(prompt), max_new_tokens=budget, request_id="d0",
        ))
        assert p.wait(180), "disaggregated request timed out"
        r = p.result()
        assert r.status == "ok", (r.status, r.error)
        assert list(r.tokens) == ref, (r.tokens, ref)
        assert gw.metrics.get(
            "gateway_phase_handoff_total", outcome="ok"
        ) == 1
        mode = "streamed" if expect_streamed else "oneshot"
        assert gw.metrics.get(
            "gateway_phase_handoff_wire_bytes_total", mode=mode
        ) > 0
        if expect_streamed:
            # at least the sealed full pages shipped as deltas before
            # the final hop, and the source reclaimed them at seal
            assert gw.metrics.get(
                "gateway_phase_handoff_deltas_total"
            ) >= 1
            assert sum(
                b.stats.get("pages_reclaimed", 0)
                for b in _pools_balanced(client)
            ) >= 1
        else:
            assert gw.metrics.get(
                "gateway_phase_handoff_deltas_total"
            ) == 0
        # the caller's stream is attributed to the disaggregated path
        assert gw.metrics.histogram_count(
            "gateway_ttft_seconds", role="disaggregated"
        ) == 1
        assert gw.metrics.histogram_count(
            "gateway_itl_seconds", role="disaggregated"
        ) == 1
        assert gw.drain(60)
        _pools_balanced(client)              # BOTH replicas at quiescence
    finally:
        gw.stop()
        client.stop()


def test_disaggregated_identity_fp32(params):
    _identity_case(params, PROMPT, 10)


def test_disaggregated_identity_fp32_oneshot(params):
    # streamed ≡ one-shot ≡ co-located: the same case with the
    # seal-watch forced off must emit the same tokens
    _identity_case(params, PROMPT, 10, streamed=False)


def test_disaggregated_identity_subpage_prompt(params):
    _identity_case(params, SUBPAGE_PROMPT, 8, expect_streamed=False)


def test_disaggregated_identity_int8_pool(params):
    _identity_case(params, PROMPT, 10, kv_dtype="int8",
                   decode_page_cache="quantized")


def test_disaggregated_identity_int8_oneshot(params):
    _identity_case(params, PROMPT, 10, streamed=False, kv_dtype="int8",
                   decode_page_cache="quantized")


def test_disaggregated_identity_speculative(params):
    _identity_case(params, PROMPT, 10, **spec_kw(params))


@pytest.mark.slow
def test_disaggregated_identity_page8(params):
    # a 12-token prompt at page 8 seals one full page pre-park: the
    # streamed lane still applies at the wider page geometry
    _identity_case(params, list(PROMPT) + [2, 7, 1, 8], 10, page_size=8)


@pytest.mark.slow
def test_disaggregated_identity_page8_oneshot(params):
    _identity_case(params, list(PROMPT) + [2, 7, 1, 8], 10,
                   streamed=False, page_size=8)


@pytest.mark.slow
def test_disaggregated_identity_int8_speculative(params):
    _identity_case(params, PROMPT, 10, kv_dtype="int8",
                   decode_page_cache="quantized", **spec_kw(params))


# ---------------------------------------------------------------------------
# fallback contract: refusal / importer death / collapse
# ---------------------------------------------------------------------------

def test_refusal_falls_back_to_prefill_replica(params):
    """The decode side refuses the import (chaos knob): the sequence
    must resume decode ON the prefill replica — counted fallback, same
    tokens, never a request error."""
    from kubegpu_tpu.gateway import GatewayRequest

    ref = make_paged(params).run([np.asarray(PROMPT, np.int32)], [10])[0]
    stack, client, gw = _disagg_stack(
        2, lambda key: make_paged(params), roles=("prefill", "flex"),
    )
    try:
        for rep in stack.registry.live():
            if rep.role != "prefill":
                client.set_fail_migration(rep.key, True)
        p = gw.submit(GatewayRequest(
            prompt=PROMPT, max_new_tokens=10, request_id="fb0",
        ))
        assert p.wait(180)
        r = p.result()
        assert r.status == "ok", (r.status, r.error)
        assert list(r.tokens) == ref
        assert gw.metrics.get(
            "gateway_phase_handoff_total", outcome="fallback"
        ) == 1
        assert gw.metrics.get(
            "gateway_phase_handoff_total", outcome="ok"
        ) == 0
        # a fallback is co-located work: one replica did it all
        assert gw.metrics.histogram_count(
            "gateway_ttft_seconds", role="colocated"
        ) == 1
        assert gw.drain(60)
        _pools_balanced(client)
    finally:
        gw.stop()
        client.stop()


def test_importer_death_between_export_and_import(params):
    """The target dies BETWEEN the export and the import ack: the held
    payload re-imports into the source (the decode-even-when-parked
    leg) and the stream finishes there — never a request error.  Driven
    on the client directly so the kill lands at the exact window the
    contract names (under a gateway the dispatcher's own handoff would
    race the injection)."""
    from types import SimpleNamespace

    from kubegpu_tpu.gateway import InMemoryReplicaClient

    ref = make_paged(params).run([np.asarray(PROMPT, np.int32)], [10])[0]
    client = InMemoryReplicaClient(step_delay_s=0.0)
    client.add_replica("pre", make_paged(params, prefill_only=True))
    client.add_replica("dec", make_paged(params))
    try:
        got = []
        req = SimpleNamespace(
            request_id="kd0", prompt=list(PROMPT), max_new_tokens=10,
            temperature=0.0, session=None,
            on_tokens=lambda a, toks: got.extend(toks),
        )
        attempt = client.submit("pre", req)
        assert attempt.sealed.wait(60), "prompt never sealed"
        ok = client.migrate(
            attempt, req, "dec",
            _between=lambda: client.fail_replica("dec"),
            fallback=True,
        )
        assert ok, "fallback migrate refused"
        assert attempt.handoff_outcome == "fallback"
        assert attempt.wait(120)
        res = attempt.result()
        assert res.ok, res.error
        assert list(res.tokens) == ref
        assert got == ref                    # uninterrupted stream
        with client._lock:
            src = client._workers["pre"].batcher
        deadline = time.monotonic() + 30
        while src.has_work() and time.monotonic() < deadline:
            time.sleep(0.01)
        src.assert_page_accounting()
    finally:
        client.stop()


def test_collapse_unparks_locally(params):
    """Disaggregation OFF (the controller's collapse rung) with a
    prefill-role replica still in the fleet: the sealed signal must
    still be handled — the handoff targets the source itself and the
    sequence decodes where it prefilled."""
    from kubegpu_tpu.gateway import GatewayRequest

    ref = make_paged(params).run([np.asarray(PROMPT, np.int32)], [10])[0]
    stack, client, gw = _disagg_stack(
        2, lambda key: make_paged(params), roles=("prefill", "flex"),
    )
    try:
        gw.set_disaggregation(False)
        p = gw.submit(GatewayRequest(
            prompt=PROMPT, max_new_tokens=10, request_id="c0",
        ))
        assert p.wait(180)
        r = p.result()
        assert r.status == "ok", (r.status, r.error)
        assert list(r.tokens) == ref
        # local unpark counts with the fallback outcomes, never "ok"
        assert gw.metrics.get(
            "gateway_phase_handoff_total", outcome="ok"
        ) == 0
        assert gw.metrics.get(
            "gateway_phase_handoff_total", outcome="fallback"
        ) == 1
        assert gw.drain(60)
        _pools_balanced(client)
    finally:
        gw.stop()
        client.stop()


# ---------------------------------------------------------------------------
# streamed seal-time handoff: the delta pipeline (ISSUE 18)
# ---------------------------------------------------------------------------

PROMPT24 = [(i * 7 + 3) % 64 for i in range(24)]   # 6 pages at page_size=4
PROMPT24B = [(i * 5 + 11) % 64 for i in range(24)]


def test_delta_pipeline_batcher_identity(params):
    """The batcher-level pipeline: pages export as deltas WHILE the
    chunked prefill still runs, stage content-addressed on the decode
    twin, the source reclaims the acked pages at park, and the final
    cursor export ships only the remainder — token-identical to
    co-located, page accounting balanced on both ends throughout."""
    ref = make_paged(params, prompt_pad=32).run(
        [np.asarray(PROMPT24, np.int32)], [6]
    )[0]
    src = make_paged(params, prompt_pad=32, prefill_only=True)
    dst = make_paged(params, prompt_pad=32)
    src.submit(1, np.asarray(PROMPT24, np.int32), 6)
    cursor = 0
    deltas = 0
    deadline = time.monotonic() + 60
    sealed = []
    while not sealed:
        assert time.monotonic() < deadline, "prefill never parked"
        src.serve_step()
        sealed = src.drain_sealed()
        d = src.export_sealed_delta(1, cursor)
        if d is not None and d["page_keys"]:
            assert dst.import_sealed_delta(d) == len(d["page_keys"])
            cursor += len(d["page_keys"])
            deltas += 1
            src.assert_page_accounting()     # every delta boundary
            dst.assert_page_accounting()
    assert deltas >= 2, "one-page chunks must yield multiple deltas"
    freed = src.reclaim_handoff_pages(1, cursor)
    assert freed >= 1, "acked pages must return to the source pool"
    assert src.stats["pages_reclaimed"] == freed
    src.assert_page_accounting()
    payload = src.export_pages(1, cursor)
    assert payload["layer_base"] == cursor
    src.cancel(1)
    src.assert_page_accounting()
    dst.import_pages(11, payload)
    out = {}
    while dst.has_work():
        out.update(dst.serve_step())
    assert out[11] == ref
    dst.assert_page_accounting()


def test_delta_refusal_rolls_back_atomically(params):
    """A refused delta moves ZERO refcounts: the feasibility check runs
    before the first allocation, so the target's pool and cache are
    untouched; a refusal AFTER earlier deltas staged leaves that
    consistent prefix intact."""
    src = make_paged(params, prompt_pad=32, prefill_only=True)
    src.submit(1, np.asarray(PROMPT24, np.int32), 4)
    while not src.drain_sealed():
        src.serve_step()
    payload = src.export_sealed_delta(1, 0)
    assert len(payload["page_keys"]) == 5    # (24-1)//4 sealed pages

    # pool too small for the delta: refused pre-mutation
    tiny = make_paged(params, prompt_pad=32, pool_pages=4)
    free_before = set(tiny.free_pages)
    with pytest.raises(RuntimeError):
        tiny.import_sealed_delta(payload)
    assert set(tiny.free_pages) == free_before
    for keyhex in payload["page_keys"]:
        assert tiny.prefix_cache.lookup(bytes.fromhex(keyhex)) is None
    assert tiny.stats["pages_imported"] == 0
    tiny.assert_page_accounting()

    # refusal after a successful stage: the staged prefix survives
    dst = make_paged(params, prompt_pad=32)
    assert dst.import_sealed_delta(payload) == 5
    bad = dict(payload)
    bad["geometry"] = dict(payload["geometry"], page=8)
    with pytest.raises(ValueError):
        dst.import_sealed_delta(bad)
    for keyhex in payload["page_keys"]:
        assert dst.prefix_cache.lookup(bytes.fromhex(keyhex)) is not None
    dst.assert_page_accounting()
    src.cancel(1)
    src.assert_page_accounting()


def test_early_reclaim_admits_queued_prefill(params):
    """The satellite regression: a prefill DEFERRED on pool pressure
    must admit the moment the parked sequence's acked pages return to
    the pool — early reclaim raises prefill admission concurrency
    DURING the handoff window, before the final export ever runs."""
    src = make_paged(params, prompt_pad=24, pool_pages=10,
                     prefill_only=True)
    src.submit(1, np.asarray(PROMPT24, np.int32), 4)   # needs 7 pages
    deadline = time.monotonic() + 60
    while not src.drain_sealed():
        assert time.monotonic() < deadline
        src.serve_step()
    # second prefill: 7 more pages against 3 free — deferred
    src.submit(2, np.asarray(PROMPT24B, np.int32), 4)
    for _ in range(10):
        src.serve_step()
    assert src.drain_sealed() == [], "admitted despite pool pressure"
    # the importer acked the 5 sealed pages: reclaim frees them
    assert src.reclaim_handoff_pages(1, 5) == 5
    src.assert_page_accounting()
    sealed = []
    while not sealed:
        assert time.monotonic() < deadline, (
            "reclaimed pages never admitted the queued prefill"
        )
        src.serve_step()
        sealed = src.drain_sealed()
    assert sealed == [2]
    src.assert_page_accounting()
    src.cancel(1)
    src.cancel(2)
    src.assert_page_accounting()


def test_kill_mid_delta_falls_back_to_decode_on_prefill(params):
    """The decode target dies AFTER acking deltas — and after the
    source already reclaimed the acked pages: the final handoff falls
    back to decode-on-prefill, re-resolving the reclaimed pages from
    the source's own prefix cache by chain key — same tokens, counted
    fallback, source pool balanced at quiescence."""
    from types import SimpleNamespace

    from kubegpu_tpu.gateway import InMemoryReplicaClient

    ref = make_paged(params, prompt_pad=32).run(
        [np.asarray(PROMPT24, np.int32)], [6]
    )[0]
    client = InMemoryReplicaClient(step_delay_s=0.0)
    client.add_replica(
        "pre", make_paged(params, prompt_pad=32, prefill_only=True)
    )
    client.add_replica("dec", make_paged(params, prompt_pad=32))
    try:
        got = []
        req = SimpleNamespace(
            request_id="kmd0", prompt=list(PROMPT24), max_new_tokens=6,
            temperature=0.0, session=None,
            on_tokens=lambda a, toks: got.extend(toks),
        )
        attempt = client.submit("pre", req)
        assert attempt.sealed.wait(60), "prompt never sealed"
        payload = client.export_delta(attempt, req, 0)
        assert payload is not None and payload["page_keys"]
        n = len(payload["page_keys"])
        assert client.import_delta("dec", payload) == n
        assert client.reclaim(attempt, req, n) >= 1
        # kill the target mid-window: BETWEEN the final export and its
        # import, exactly like the dispatcher's _between chaos hook
        ok = client.migrate(
            attempt, req, "dec",
            _between=lambda: client.fail_replica("dec"),
            fallback=True, cursor=n,
        )
        assert ok, "fallback migrate refused"
        assert attempt.wait(120)
        res = attempt.result()
        assert res.ok, res.error
        assert list(res.tokens) == ref
        assert got == ref                    # uninterrupted stream
        assert attempt.handoff_outcome == "fallback"
        with client._lock:
            src = client._workers["pre"].batcher
        deadline = time.monotonic() + 30
        while src.has_work() and time.monotonic() < deadline:
            time.sleep(0.01)
        src.assert_page_accounting()
    finally:
        client.stop()


PROMPT24C = [(i * 11 + 7) % 64 for i in range(24)]


def test_parked_sequences_excluded_from_token_budget(params):
    """Satellite fix, pinned at the budget packer: a PARKED sequence
    runs zero decode rows, so its budget share goes straight back to
    prefill.  token_budget=9 net of ONE real decoder leaves two chunk
    rows — two in-flight prefill jobs must BOTH advance each step;
    counting the parked slot in the denominator would halve the
    prefill rate to one chunk per step."""
    b = make_paged(params, prompt_pad=32, prefill_only=True,
                   token_budget=9)
    b.submit(1, np.asarray(PROMPT24, np.int32), 4)
    deadline = time.monotonic() + 60
    while not b.drain_sealed():
        assert time.monotonic() < deadline
        b.serve_step()                       # seq 1 parks at seal
    # an imported twin of the parked content DECODES here (the
    # fallback-resume contract) — the one real budget consumer
    b.import_pages(9, b.export_pages(1))
    b.submit(2, np.asarray(PROMPT24B, np.int32), 4)
    b.submit(3, np.asarray(PROMPT24C, np.int32), 4)
    while len(b._jobs) < 2:
        assert time.monotonic() < deadline, "prefill jobs never opened"
        b.serve_step()
    before = b.stats["prefill_chunks"]
    b.serve_step()
    assert b.stats["prefill_chunks"] - before == 2, (
        "parked sequence still counted against the token budget: "
        "only one prefill chunk advanced"
    )
    for seq in (1, 2, 3, 9):
        b.cancel(seq)
    b.assert_page_accounting()


# ---------------------------------------------------------------------------
# controller: the prefill:decode ratio actuator
# ---------------------------------------------------------------------------

def _controller_stack(n_replicas=4, **cfg_kw):
    from kubegpu_tpu.controller import ControllerConfig, FleetController
    from kubegpu_tpu.gateway import (
        AdmissionQueue, FailoverPolicy, Gateway, InMemoryReplicaClient,
        SimBatcher,
    )
    from kubegpu_tpu.testing.fake_serving import build_fake_serving_stack
    from kubegpu_tpu.utils.metrics import Metrics

    metrics = Metrics()
    stack = build_fake_serving_stack(
        n_replicas, metrics=Metrics(), priority=50,
    )
    client = InMemoryReplicaClient(
        batcher_factory=lambda key: SimBatcher(slots=8),
        step_delay_s=0.001,
    )
    stack.registry.subscribe(client.sync_live)
    gw = Gateway(
        stack.registry, client, queue=AdmissionQueue(capacity=64),
        policy=FailoverPolicy(deadline_s=30.0),
        metrics=metrics, dispatchers=2,
    )
    stack.registry.refresh()
    gw.start()
    cfg = dict(
        group="decode", min_replicas=1, max_replicas=n_replicas,
        serving_priority=50, ttft_target_s=0.5,
        ratio_enabled=True, itl_target_s=0.05,
        ratio_up_ticks=2, ratio_down_ticks=2, ratio_cooldown_s=0.0,
        up_cooldown_s=0.0, down_cooldown_s=0.0, flap_window_s=0.0,
    )
    cfg.update(cfg_kw)
    ctrl = FleetController(
        api=stack.api, sched=stack.sched, registry=stack.registry,
        gateway=gw, client=client, metrics=metrics,
        config=ControllerConfig(**cfg),
    )
    return stack, client, gw, ctrl, metrics


def _roles(stack):
    return sorted(
        (r.key, r.role) for r in stack.registry.all()
    )


def test_ratio_reshape_under_ttft_pressure():
    stack, client, gw, ctrl, metrics = _controller_stack()
    try:
        metrics.observe("gateway_ttft_seconds", 0.9)
        ctrl.tick()                          # primes the TTFT window
        actions = []
        for _ in range(3):
            metrics.observe("gateway_ttft_seconds", 0.9)
            actions.append(ctrl.tick().get("role_action"))
        assert any(a and a.startswith("prefill") for a in actions), actions
        assert metrics.get(
            "controller_role_reshapes_total", dir="prefill"
        ) == 1
        roles = dict(_roles(stack))
        assert list(roles.values()).count("prefill") == 1
        # ITL pressure converts it back
        for _ in range(4):
            metrics.observe("gateway_itl_seconds", 0.2)
            metrics.observe("gateway_ttft_seconds", 0.001)
            ctrl.tick()
        assert "prefill" not in dict(_roles(stack)).values()
        assert metrics.get(
            "controller_role_reshapes_total", dir="decode"
        ) == 1
    finally:
        gw.stop()
        client.stop()


def test_ratio_holds_prefill_flip_when_handoff_bound():
    """TTFT pressure with a large EXPOSED handoff tax (total handoff
    time minus the streamed overlap, per handoff) is handoff-bound:
    more prefill bandwidth cannot shrink a wire tail, so hot ticks do
    not count toward the flex->prefill flip.  Once the pipeline
    overlaps the transfer (tax below the threshold), the same TTFT
    pressure flips a replica again."""
    stack, client, gw, ctrl, metrics = _controller_stack()
    try:
        metrics.observe("gateway_ttft_seconds", 0.9)
        ctrl.tick()                          # primes the windows
        # hot TTFT, but the handoff's critical-path share is 0.35s per
        # handoff >= handoff_tax_fraction(0.5) * ttft_target(0.5s)
        for _ in range(4):
            metrics.observe("gateway_ttft_seconds", 0.9)
            metrics.observe("gateway_phase_handoff_seconds", 0.4)
            metrics.observe(
                "gateway_phase_handoff_overlap_seconds", 0.05
            )
            assert ctrl.tick().get("role_action") in ("", None)
        assert "prefill" not in dict(_roles(stack)).values()
        assert metrics.get(
            "controller_role_reshapes_total", dir="prefill"
        ) == 0
        assert metrics.gauge("controller_handoff_exposed_tax_s") == (
            pytest.approx(0.35)
        )
        # the pipeline now overlaps the transfer: tax 0.02s per
        # handoff, same TTFT pressure -> compute-bound -> flip
        actions = []
        for _ in range(3):
            metrics.observe("gateway_ttft_seconds", 0.9)
            metrics.observe("gateway_phase_handoff_seconds", 0.4)
            metrics.observe(
                "gateway_phase_handoff_overlap_seconds", 0.38
            )
            actions.append(ctrl.tick().get("role_action"))
        assert any(a and a.startswith("prefill") for a in actions), actions
    finally:
        gw.stop()
        client.stop()


def test_ratio_never_strands_decode_capacity():
    """A single-replica fleet can never convert to prefill (the floor:
    at least one non-prefill replica must remain AFTER a flip — with
    one routable replica, ``len(routable) - prefill > 1`` never holds),
    no matter how long TTFT pressure persists."""
    stack, client, gw, ctrl, metrics = _controller_stack(n_replicas=1)
    try:
        metrics.observe("gateway_ttft_seconds", 0.9)
        ctrl.tick()
        for _ in range(4):
            metrics.observe("gateway_ttft_seconds", 0.9)
            ctrl.tick()
        assert "prefill" not in dict(_roles(stack)).values()
        assert metrics.get(
            "controller_role_reshapes_total", dir="prefill"
        ) == 0
    finally:
        gw.stop()
        client.stop()


def test_ratio_collapse_on_handoff_failures_and_rearm():
    stack, client, gw, ctrl, metrics = _controller_stack(
        collapse_clear_ticks=2,
    )
    try:
        # reshape one replica to prefill first
        metrics.observe("gateway_ttft_seconds", 0.9)
        ctrl.tick()
        for _ in range(3):
            metrics.observe("gateway_ttft_seconds", 0.9)
            ctrl.tick()
        assert "prefill" in dict(_roles(stack)).values()
        # now the handoff path starts failing hard
        for _ in range(3):
            metrics.inc("gateway_phase_handoff_total", outcome="failed")
        summary = ctrl.tick()
        assert summary.get("role_action") == "collapse"
        assert "prefill" not in dict(_roles(stack)).values()
        assert not gw.dispatcher.disaggregation
        assert metrics.get(
            "controller_role_reshapes_total", dir="collapse"
        ) == 1
        # clean ticks re-arm disaggregated serving
        for _ in range(3):
            metrics.inc("gateway_phase_handoff_total", outcome="ok")
            ctrl.tick()
        assert gw.dispatcher.disaggregation
    finally:
        gw.stop()
        client.stop()


# ---------------------------------------------------------------------------
# role surfaces: /v1/state, POST /v1/role, registry annotation
# ---------------------------------------------------------------------------

def test_replica_server_role_surface():
    from kubegpu_tpu.gateway import ReplicaServer, SimBatcher

    srv = ReplicaServer(
        SimBatcher(slots=4), step_delay_s=0.001, role="prefill",
    ).start()
    try:
        st = json.loads(urllib.request.urlopen(
            f"http://{srv.endpoint}/v1/state", timeout=5,
        ).read())
        assert st["role"] == "prefill"
        req = urllib.request.Request(
            f"http://{srv.endpoint}/v1/role",
            data=json.dumps({"role": "decode"}).encode(),
            headers={"Content-Type": "application/json"},
        )
        resp = json.loads(urllib.request.urlopen(req, timeout=5).read())
        assert resp["role"] == "decode"
        st = json.loads(urllib.request.urlopen(
            f"http://{srv.endpoint}/v1/state", timeout=5,
        ).read())
        assert st["role"] == "decode"
        # an unknown role is a 400, not a silent flex
        bad = urllib.request.Request(
            f"http://{srv.endpoint}/v1/role",
            data=json.dumps({"role": "turbo"}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(bad, timeout=5)
    finally:
        srv.stop()


def test_registry_reads_and_patches_role():
    from kubegpu_tpu.gateway import ReplicaRegistry
    from kubegpu_tpu.testing.fake_serving import build_fake_serving_stack

    stack = build_fake_serving_stack(
        2, roles=("prefill", None),
    )
    reg = ReplicaRegistry(stack.api)
    reg.refresh()
    roles = {r.key: r.role for r in reg.all()}
    assert sorted(roles.values()) == ["flex", "prefill"]
    pre = next(k for k, v in roles.items() if v == "prefill")
    reg.set_role(pre, "flex")
    assert reg.get(pre).role == "flex"


# ---------------------------------------------------------------------------
# soak: the kill schedules over the handoff path
# ---------------------------------------------------------------------------

def test_gateway_soak_disaggregation_inmemory():
    from kubegpu_tpu.testing.soak import GatewaySoak

    GatewaySoak(
        seed=515, n_replicas=4, migration=True, disaggregation=True,
    ).run(60)


def test_gateway_soak_disaggregation_http():
    from kubegpu_tpu.testing.soak import GatewaySoak

    GatewaySoak(
        seed=616, n_replicas=3, migration=True, http=True,
        disaggregation=True,
    ).run(40)


def test_gateway_soak_streamed_handoff_kill_schedule():
    """The streamed-handoff kill schedule: kills, importer refusals and
    kill-mid-migration land while the seal-watch ships deltas — I5 and
    page accounting hold on BOTH ends at quiescence (audited in
    GatewaySoak.check), and the schedule demonstrably streamed."""
    from kubegpu_tpu.testing.soak import GatewaySoak

    soak = GatewaySoak(
        seed=818, n_replicas=4, migration=True, disaggregation=True,
    )
    soak.run(60)
    assert soak.metrics.get("gateway_phase_handoff_deltas_total") >= 1


def test_gateway_soak_oneshot_schedule_ships_no_deltas():
    """stream_handoff=False forces every handoff through the one-shot
    transfer: the quiescence audit pins zero deltas schedule-wide."""
    from kubegpu_tpu.testing.soak import GatewaySoak

    GatewaySoak(
        seed=919, n_replicas=3, migration=True, disaggregation=True,
        stream_handoff=False,
    ).run(30)


@pytest.mark.slow
def test_gateway_soak_disaggregation_paged_kill_schedule(params):
    """The acceptance schedule: paged fp32 replicas, one a dedicated
    prefill front-end, under drains, migrations, kill-mid-migration and
    importer refusals — I5, the trace oracles and both-end page
    accounting hold at quiescence."""
    from kubegpu_tpu.testing.soak import GatewaySoak

    def factory(key):
        return make_paged(params, slots=8, prompt_pad=16, pool_pages=64)

    GatewaySoak(
        seed=717, n_replicas=3, batcher_factory=factory,
        multiturn=True, migration=True, disaggregation=True,
    ).run(24)
