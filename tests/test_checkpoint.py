"""Checkpoint/resume tests (models/checkpoint.py) — the workload half of
the elastic-recovery story (SURVEY.md §5.4): train, save, kill, restart,
restore into the restart mesh's shardings, resume at the saved step."""

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from kubegpu_tpu.models import (
    ResNet,
    create_train_state,
    make_resnet_train_step,
    place_resnet,
)
from kubegpu_tpu.models.checkpoint import (
    make_manager,
    restore_checkpoint,
    save_checkpoint,
)
from kubegpu_tpu.parallel import device_mesh

pytestmark = pytest.mark.slow  # JAX compile-heavy; run with -m slow


def _tiny_setup(mesh, seed=0):
    model = ResNet(stage_sizes=(1, 1, 1, 1), num_filters=8, num_classes=10)
    rng = jax.random.PRNGKey(seed)
    images = jnp.ones((8, 32, 32, 3), jnp.float32)
    labels = jnp.zeros((8,), jnp.int32)
    state = create_train_state(model, rng, images)
    state, images, labels = place_resnet(state, (images, labels), mesh)
    return state, images, labels


@pytest.mark.exhaustive
def test_restore_none_when_empty(tmp_path):
    mesh = device_mesh({"data": 2}, devices=jax.devices()[:2])
    state, _, _ = _tiny_setup(mesh)
    mgr = make_manager(str(tmp_path / "ckpt"))
    assert restore_checkpoint(mgr, state) is None


@pytest.mark.exhaustive
def test_save_restore_roundtrip_resumes_at_step(tmp_path):
    mesh = device_mesh({"data": 2}, devices=jax.devices()[:2])
    state, images, labels = _tiny_setup(mesh)
    step = make_resnet_train_step(mesh, donate=False)
    for _ in range(3):
        state, _loss = step(state, images, labels)

    mgr = make_manager(str(tmp_path / "ckpt"))
    saved_step = save_checkpoint(mgr, state)
    mgr.wait_until_finished()
    assert saved_step == 3

    # "restart": fresh init from a DIFFERENT seed — params must differ...
    fresh, images2, labels2 = _tiny_setup(mesh, seed=42)
    assert any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(fresh.params),
                        jax.tree_util.tree_leaves(state.params))
    )

    # ...until restore brings back the saved arrays, step included
    mgr2 = make_manager(str(tmp_path / "ckpt"))
    restored = restore_checkpoint(mgr2, fresh)
    assert restored is not None
    assert int(jax.device_get(restored.step)) == 3
    for a, b in zip(jax.tree_util.tree_leaves(restored.params),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree_util.tree_leaves(restored.opt_state),
                    jax.tree_util.tree_leaves(state.opt_state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))

    # training continues from the restored state
    restored, loss = step(restored, images2, labels2)
    assert int(jax.device_get(restored.step)) == 4
    assert np.isfinite(float(loss))


def test_tp_sharded_lm_checkpoint_roundtrip(tmp_path):
    """TP-sharded state (params AND mirrored optimizer moments sharded over
    "model") must checkpoint and restore back into TP shardings — the
    distributed-checkpoint path a rescheduled TP gang exercises."""
    from jax.sharding import PartitionSpec as P

    from kubegpu_tpu.models import TransformerLM, make_lm_train_step, place_lm

    mesh = device_mesh({"data": 2, "model": 4})
    model = TransformerLM(
        vocab_size=64, num_layers=2, num_heads=4, hidden=32, max_seq=32
    )
    tokens = (jnp.arange(4 * 17, dtype=jnp.int32) % 64).reshape(4, 17)
    state = create_train_state(model, jax.random.PRNGKey(0), tokens[:, :-1])
    state, tok = place_lm(state, tokens, mesh)
    step = make_lm_train_step(mesh, donate=False)
    state, _ = step(state, tok)

    mgr = make_manager(str(tmp_path / "tp-ckpt"))
    save_checkpoint(mgr, state)
    mgr.wait_until_finished()

    template = create_train_state(model, jax.random.PRNGKey(9), tokens[:, :-1])
    template, tok2 = place_lm(template, tokens, mesh)
    restored = restore_checkpoint(make_manager(str(tmp_path / "tp-ckpt")), template)
    assert restored is not None
    qk = restored.params["layer0"]["attn"]["q_proj"]["kernel"]
    assert qk.sharding.spec == P(None, "model")  # landed TP-sharded
    for a, b in zip(jax.tree_util.tree_leaves(restored.params),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    # the mirrored optimizer moments restore with values AND TP shardings
    for a, b in zip(jax.tree_util.tree_leaves(restored.opt_state),
                    jax.tree_util.tree_leaves(state.opt_state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    trace_qk = restored.opt_state[0].trace["layer0"]["attn"]["q_proj"]["kernel"]
    assert trace_qk.sharding.spec == P(None, "model")
    restored, loss = step(restored, tok2)
    assert np.isfinite(float(loss))
    # decoding consumes the restored checkpoint directly (shared param tree)
    from kubegpu_tpu.models import greedy_generate

    out = greedy_generate(
        jax.device_get(restored.params), jnp.ones((1, 4), jnp.int32), 3,
        vocab_size=64, num_layers=2, num_heads=4, hidden=32, max_seq=32,
    )
    assert out.shape == (1, 7)


@pytest.mark.exhaustive
def test_restore_onto_different_mesh_shardings(tmp_path):
    """A rescheduled gang may land on a different sub-mesh: save from a
    2-device mesh, restore into a 4-device template — arrays must land in
    the TEMPLATE's shardings."""
    mesh2 = device_mesh({"data": 2}, devices=jax.devices()[:2])
    state, images, labels = _tiny_setup(mesh2)
    step = make_resnet_train_step(mesh2, donate=False)
    state, _ = step(state, images, labels)
    mgr = make_manager(str(tmp_path / "ckpt"))
    save_checkpoint(mgr, state)
    mgr.wait_until_finished()

    mesh4 = device_mesh({"data": 4}, devices=jax.devices()[:4])
    template, images4, labels4 = _tiny_setup(mesh4, seed=7)
    restored = restore_checkpoint(make_manager(str(tmp_path / "ckpt")), template)
    assert restored is not None
    leaf = jax.tree_util.tree_leaves(restored.params)[0]
    assert leaf.sharding.mesh.devices.size == 4
    step4 = make_resnet_train_step(mesh4, donate=False)
    restored, loss = step4(restored, images4, labels4)
    assert np.isfinite(float(loss))

    # retention: max_to_keep bounds the kept steps
    mgr3 = make_manager(str(tmp_path / "ckpt2"), max_to_keep=2)
    s = state
    for _ in range(4):
        s, _ = step(s, images, labels)
        save_checkpoint(mgr3, s)
    mgr3.wait_until_finished()
    assert len(mgr3.all_steps()) <= 2
