"""L4 scheduler tests: verbs, gang planning, replay, races, HTTP wire."""

import json
import threading
import time
import urllib.request

import pytest

from kubegpu_tpu.plugins import Advertiser, FakeSlice
from kubegpu_tpu.scheduler import ExtenderServer, Scheduler
from kubegpu_tpu.types import RES_TPU, annotations, is_contiguous_submesh
from kubegpu_tpu.utils import InMemoryApiServer
from kubegpu_tpu.utils.metrics import Metrics


def fake_cluster(mesh=(4, 4), block=(2, 2)):
    api = InMemoryApiServer()
    fs = FakeSlice(slice_id="s0", mesh_shape=mesh, host_block=block)
    advs = {h: Advertiser(p, api) for h, p in fs.providers().items()}
    for a in advs.values():
        a.advertise_once()
    return api, fs, advs


def make_sched(api, **kw) -> Scheduler:
    s = Scheduler(api, metrics=Metrics(), **kw)
    s.cache.refresh()
    return s


def pod_obj(name, chips, ns="default", group=None, group_size=None, contiguous=True, uid=None, group_uid=None):
    ann = {}
    if group:
        ann[annotations.POD_GROUP] = group
        ann[annotations.POD_GROUP_SIZE] = str(group_size or 1)
        if group_uid:
            ann[annotations.POD_GROUP_UID] = group_uid
    if not contiguous:
        ann[annotations.POD_CONTIGUOUS] = "false"
    return {
        "metadata": {"name": name, "namespace": ns, "uid": uid or f"uid-{name}", "annotations": ann},
        "spec": {
            "containers": [
                {"name": "main", "resources": {"limits": {RES_TPU: str(chips)}}}
            ]
        },
    }


def nodes_of(api):
    return sorted(n["metadata"]["name"] for n in api.list_nodes())


# -- config 1: passthrough --------------------------------------------------

def test_filter_passthrough_zero_chips():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    obj = pod_obj("web", 0)
    api.create_pod(obj)
    r = sched.filter(obj, nodes_of(api))
    assert r.nodes == nodes_of(api) and not r.failed


# -- config 2: single chip --------------------------------------------------

def test_single_chip_schedule_and_bind():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    obj = pod_obj("one", 1)
    api.create_pod(obj)
    names = nodes_of(api)
    r = sched.filter(obj, names)
    assert len(r.nodes) == 4
    scores = dict(sched.prioritize(obj, r.nodes))
    assert all(0 <= s <= 10 for s in scores.values())
    target = max(r.nodes, key=lambda n: scores[n])
    assert sched.bind("default", "one", target) is None
    stored = api.get_pod("default", "one")
    assert stored["spec"]["nodeName"] == target
    a = annotations.assignment_from_pod(stored)
    assert a is not None and len(a.all_chips()) == 1
    assert sched.metrics.get("kubegpu_placements_total") == 1
    assert sched.metrics.get("kubegpu_placements_contiguous_total") == 1


# -- config 3: 4 chips contiguous -------------------------------------------

def test_four_chip_contiguous_bind():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    obj = pod_obj("quad", 4)
    api.create_pod(obj)
    r = sched.filter(obj, nodes_of(api))
    assert len(r.nodes) == 4
    assert sched.bind("default", "quad", r.nodes[0]) is None
    a = annotations.assignment_from_pod(api.get_pod("default", "quad"))
    coords = {c.coords for c in a.all_chips()}
    assert is_contiguous_submesh(coords, (4, 4))


def test_filter_reports_reasons_when_full():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    for i, n in enumerate(nodes_of(api)):
        obj = pod_obj(f"f{i}", 4)
        api.create_pod(obj)
        assert sched.filter(obj, [n]).nodes == [n]
        assert sched.bind("default", f"f{i}", n) is None
    late = pod_obj("late", 1)
    api.create_pod(late)
    r = sched.filter(late, nodes_of(api))
    assert r.nodes == []
    assert all("insufficient" in reason for reason in r.failed.values())


# -- bind edge cases --------------------------------------------------------

def test_bind_refits_on_chosen_node():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    n = nodes_of(api)[0]
    for i in range(4):
        obj = pod_obj(f"p{i}", 1)
        api.create_pod(obj)
        assert sched.bind("default", f"p{i}", n) is None
    chips = set()
    for i in range(4):
        a = annotations.assignment_from_pod(api.get_pod("default", f"p{i}"))
        chips |= {(c.host, c.device_index) for c in a.all_chips()}
    assert len(chips) == 4  # no double allocation
    obj = pod_obj("p4", 1)
    api.create_pod(obj)
    err = sched.bind("default", "p4", n)
    assert err is not None and "no longer fits" in err


def test_bind_unknown_pod_and_node():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    assert "not found" in sched.bind("default", "ghost", nodes_of(api)[0])
    obj = pod_obj("x", 1)
    api.create_pod(obj)
    assert "unknown node" in sched.bind("default", "x", "nope")


def test_concurrent_binds_never_double_allocate():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    names = nodes_of(api)
    for i in range(16):
        api.create_pod(pod_obj(f"c{i}", 1))
    errs = []

    def bind_one(i):
        err = sched.bind("default", f"c{i}", names[i % 4])
        if err:
            errs.append(err)

    threads = [threading.Thread(target=bind_one, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    seen = set()
    for i in range(16):
        a = annotations.assignment_from_pod(api.get_pod("default", f"c{i}"))
        for c in a.all_chips():
            key = (c.host, c.device_index)
            assert key not in seen
            seen.add(key)
    assert len(seen) == 16


# -- config 4: gang ---------------------------------------------------------

def test_gang_waits_for_all_members():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    p0 = pod_obj("w0", 1, group="job", group_size=4)
    api.create_pod(p0)
    r = sched.filter(p0, nodes_of(api))
    assert r.nodes == []
    assert any("waiting for members" in v for v in r.failed.values())


def test_gang_schedules_all_or_nothing():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    objs = [pod_obj(f"w{i}", 1, group="job", group_size=4) for i in range(4)]
    for o in objs:
        api.create_pod(o)
    coords = set()
    for o in objs:
        name = o["metadata"]["name"]
        r = sched.filter(o, nodes_of(api))
        assert len(r.nodes) == 1, r.failed
        assert sched.bind("default", name, r.nodes[0]) is None
        a = annotations.assignment_from_pod(api.get_pod("default", name))
        coords |= {c.coords for c in a.all_chips()}
    assert len(coords) == 4
    assert is_contiguous_submesh(coords, (4, 4))


def test_gang_too_big_rejected_with_reason():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    objs = [pod_obj(f"g{i}", 4, group="huge", group_size=5) for i in range(5)]
    for o in objs:
        api.create_pod(o)
    r = sched.filter(objs[0], nodes_of(api))
    assert r.nodes == []
    assert any("does not fit" in v for v in r.failed.values())


def test_gang_plan_expiry_returns_reservations():
    api, _, _ = fake_cluster()
    sched = make_sched(api, gang_plan_ttl_s=0.0)
    objs = [pod_obj(f"w{i}", 4, group="job", group_size=2) for i in range(2)]
    for o in objs:
        api.create_pod(o)
    r = sched.filter(objs[0], nodes_of(api))
    assert len(r.nodes) == 1
    time.sleep(0.01)
    # TTL elapsed, nothing committed: reservations must be released
    assert sched.groups.plan_for(annotations.pod_from_k8s(objs[0])) is None
    view = next(iter(sched.cache.views().values()))
    assert len(view.free) == 16


def test_gang_member_deleted_before_bind_drops_plan():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    objs = [pod_obj(f"w{i}", 4, group="job", group_size=2) for i in range(2)]
    for o in objs:
        api.create_pod(o)
    r = sched.filter(objs[0], nodes_of(api))
    assert len(r.nodes) == 1
    api.delete_pod("default", "w1")
    sched.on_pod_deleted(objs[1])
    view = next(iter(sched.cache.views().values()))
    assert len(view.free) == 16


def test_resync_preserves_gang_reservations():
    # regression (review finding): a cache refresh between gang planning and
    # the members' binds must NOT erase the plan's reservations
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    objs = [pod_obj(f"w{i}", 4, group="job", group_size=2) for i in range(2)]
    for o in objs:
        api.create_pod(o)
    r = sched.filter(objs[0], nodes_of(api))
    assert len(r.nodes) == 1
    sched.cache.refresh()  # the 30s resync loop fires mid-gang
    view = next(iter(sched.cache.views().values()))
    assert len(view.used) == 8  # both members' reservations survived
    # a competing pod cannot steal the reserved chips
    competitor = pod_obj("steal", 4)
    api.create_pod(competitor)
    rc = sched.filter(competitor, nodes_of(api))
    for n in rc.nodes:
        assert sched.bind("default", "steal", n) is None
        a = annotations.assignment_from_pod(api.get_pod("default", "steal"))
        assert not ({c.coords for c in a.all_chips()} & view.used)
        break
    # and the gang still binds cleanly
    for o in objs:
        name = o["metadata"]["name"]
        rf = sched.filter(o, nodes_of(api))
        assert len(rf.nodes) == 1, rf.failed
        assert sched.bind("default", name, rf.nodes[0]) is None


def test_fully_committed_plan_dropped_and_recreated_pod_replans():
    # regression (review finding): a deleted-then-recreated gang member must
    # get a fresh placement, not the stale plan's chips
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    objs = [pod_obj(f"w{i}", 4, group="job", group_size=2) for i in range(2)]
    for o in objs:
        api.create_pod(o)
    for o in objs:
        r = sched.filter(o, nodes_of(api))
        assert r.nodes, r.failed
        assert sched.bind("default", o["metadata"]["name"], r.nodes[0]) is None
    assert sched.groups._plans == {}  # plan dropped once fully committed
    # w1 dies and is recreated (Job/StatefulSet restart pattern)
    api.delete_pod("default", "w1")
    sched.on_pod_deleted(objs[1])
    fresh = pod_obj("w1", 4, group="job", group_size=2)
    api.create_pod(fresh)
    r = sched.filter(fresh, nodes_of(api))
    assert len(r.nodes) == 1, r.failed
    assert sched.bind("default", "w1", r.nodes[0]) is None
    # no chip double-booked
    seen = set()
    for name in ("w0", "w1"):
        a = annotations.assignment_from_pod(api.get_pod("default", name))
        for c in a.all_chips():
            assert (c.host, c.device_index) not in seen
            seen.add((c.host, c.device_index))
    assert len(seen) == 8


def test_partially_committed_gang_replans_remainder():
    # regression (review finding): after a partial commit + plan drop, the
    # unbound members must re-plan around the bound ones, not deadlock
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    objs = [pod_obj(f"w{i}", 4, group="job", group_size=2) for i in range(2)]
    for o in objs:
        api.create_pod(o)
    r0 = sched.filter(objs[0], nodes_of(api))
    assert sched.bind("default", "w0", r0.nodes[0]) is None
    # simulate plan loss before w1 binds (e.g. planned node cordoned)
    sched.groups.drop_plan("default/job")
    r1 = sched.filter(objs[1], nodes_of(api))
    assert len(r1.nodes) == 1, r1.failed
    assert sched.bind("default", "w1", r1.nodes[0]) is None
    seen = set()
    for name in ("w0", "w1"):
        a = annotations.assignment_from_pod(api.get_pod("default", name))
        seen |= {(c.host, c.device_index) for c in a.all_chips()}
    assert len(seen) == 8


# -- restart replay ---------------------------------------------------------

def test_restart_replay_restores_used_state():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    for i in range(3):
        obj = pod_obj(f"r{i}", 2)
        api.create_pod(obj)
        r = sched.filter(obj, nodes_of(api))
        assert sched.bind("default", f"r{i}", r.nodes[0]) is None
    # "restart": a brand-new scheduler over the same API server
    sched2 = make_sched(api)
    v1 = next(iter(sched.cache.views().values()))
    v2 = next(iter(sched2.cache.views().values()))
    assert v1.used == v2.used and len(v2.used) == 6
    # and new placements avoid the replayed chips
    obj = pod_obj("after", 4)
    api.create_pod(obj)
    r = sched2.filter(obj, nodes_of(api))
    assert sched2.bind("default", "after", r.nodes[0]) is None
    a = annotations.assignment_from_pod(api.get_pod("default", "after"))
    assert not ({c.coords for c in a.all_chips()} & v2.used)


# -- health-driven node updates --------------------------------------------

def test_dead_chip_falls_out_via_node_update():
    api, fs, advs = fake_cluster()
    sched = make_sched(api)
    fs.kill_chip((0, 0))
    victim = fs.topology.chips[(0, 0)].host_id
    advs[victim].advertise_once()
    sched.on_node_updated(api.get_node(victim))
    view = next(iter(sched.cache.views().values()))
    assert len(view.free) == 15 and (0, 0) not in view.free


def test_chip_death_evicts_only_affected_pod_and_replacement_reschedules():
    # elastic recovery (SURVEY.md §5.3): the pod holding a died chip is
    # evicted; its gang siblings keep running; the recreated member
    # re-plans onto healthy chips of the same slice
    api, fs, advs = fake_cluster()
    sched = make_sched(api)
    pods = [pod_obj(f"g{i}", 1, group="dp", group_size=4) for i in range(4)]
    for obj in pods:
        api.create_pod(obj)
    names = nodes_of(api)
    chip_of = {}
    for obj in pods:
        name = obj["metadata"]["name"]
        r = sched.filter(obj, names)
        assert r.nodes
        assert sched.bind("default", name, r.nodes[0]) is None
        a = annotations.assignment_from_pod(api.get_pod("default", name))
        chip_of[name] = a.all_chips()[0]
    dead_ref = chip_of["g1"]
    fs.kill_chip(dead_ref.coords)
    advs[dead_ref.host].advertise_once()
    sched.on_node_updated(api.get_node(dead_ref.host))
    # g1 evicted, siblings alive
    import pytest as _pytest

    from kubegpu_tpu.utils.apiserver import NotFound as _NF
    with _pytest.raises(_NF):
        api.get_pod("default", "g1")
    for other in ("g0", "g2", "g3"):
        assert annotations.assignment_from_pod(api.get_pod("default", other))
    # the controller recreates g1: it rejoins on a healthy chip
    api.create_pod(pod_obj("g1", 1, group="dp", group_size=4))
    r = sched.filter(pod_obj("g1", 1, group="dp", group_size=4), names)
    assert r.nodes, (r.failed, r.error)
    assert sched.bind("default", "g1", r.nodes[0]) is None
    a = annotations.assignment_from_pod(api.get_pod("default", "g1"))
    assert a.all_chips()[0].coords != dead_ref.coords


def test_chip_death_invalidates_partially_committed_gang_plan():
    # the live GangPlan still covers the victim: without dropping it, the
    # recreated member is rebound onto the EXACT dead chip by the stale
    # plan, then evicted again — an endless loop
    api, fs, advs = fake_cluster()
    sched = make_sched(api)
    pods = [pod_obj(f"p{i}", 1, group="pg", group_size=4) for i in range(4)]
    for obj in pods:
        api.create_pod(obj)
    names = nodes_of(api)
    # plan the whole gang (first filter) but bind only TWO members
    for obj in pods:
        assert sched.filter(obj, names).nodes
    for name in ("p0", "p1"):
        r = sched.filter(pod_obj(name, 1, group="pg", group_size=4), names)
        assert sched.bind("default", name, r.nodes[0]) is None
    dead_ref = annotations.assignment_from_pod(
        api.get_pod("default", "p1")
    ).all_chips()[0]
    fs.kill_chip(dead_ref.coords)
    advs[dead_ref.host].advertise_once()
    sched.on_node_updated(api.get_node(dead_ref.host))
    # p1 evicted; recreate it and re-schedule: must avoid the dead chip
    api.create_pod(pod_obj("p1", 1, group="pg", group_size=4))
    r = sched.filter(pod_obj("p1", 1, group="pg", group_size=4), names)
    assert r.nodes, (r.failed, r.error)
    assert sched.bind("default", "p1", r.nodes[0]) is None
    a = annotations.assignment_from_pod(api.get_pod("default", "p1"))
    assert a.all_chips()[0].coords != dead_ref.coords


def test_chip_death_leaves_unrelated_pods_alone():
    api, fs, advs = fake_cluster()
    sched = make_sched(api)
    obj = pod_obj("solo", 1)
    api.create_pod(obj)
    r = sched.filter(obj, nodes_of(api))
    assert sched.bind("default", "solo", r.nodes[0]) is None
    a = annotations.assignment_from_pod(api.get_pod("default", "solo"))
    # kill a chip the pod does NOT hold, on the same host
    host_chips = [c for c in fs.topology.chips.values() if c.host_id == a.node]
    other = next(
        c for c in host_chips if c.device_index != a.all_chips()[0].device_index
    )
    fs.kill_chip(other.coords)
    advs[a.node].advertise_once()
    sched.on_node_updated(api.get_node(a.node))
    assert annotations.assignment_from_pod(api.get_pod("default", "solo"))


def _advertise_without_chip(api, host, device_index, seq):
    """Re-advertise `host` with one chip silently MISSING from the tree (an
    advertiser restart / truncated enumeration), not marked unhealthy."""
    import dataclasses

    obj = api.get_node(host)
    node = annotations.node_from_k8s(obj)
    node = dataclasses.replace(
        node, chips=[c for c in node.chips if c.device_index != device_index]
    )
    api.patch_node_annotations(
        host,
        {
            annotations.NODE_TOPOLOGY: annotations.encode_node_topology(node),
            annotations.NODE_ADVERT_SEQ: str(seq),
        },
    )
    return api.get_node(host)


def test_absent_chip_needs_strikes_from_distinct_advertisements():
    # ADVICE r1: absence is ambiguous (advertiser restart) while eviction is
    # irreversible — one short advertisement must not kill a healthy pod,
    # and RE-READING the same stale advertisement (resync re-ticks, watch +
    # resync double-observation) must not accumulate strikes either
    api, fs, advs = fake_cluster()
    sched = make_sched(api)
    obj = pod_obj("solo", 1)
    api.create_pod(obj)
    r = sched.filter(obj, nodes_of(api))
    assert sched.bind("default", "solo", r.nodes[0]) is None
    a = annotations.assignment_from_pod(api.get_pod("default", "solo"))
    ref = a.all_chips()[0]
    # 1st short advertisement: pod survives
    node_obj = _advertise_without_chip(api, ref.host, ref.device_index, seq=1)
    sched.on_node_updated(node_obj)
    assert annotations.assignment_from_pod(api.get_pod("default", "solo"))
    # the SAME advertisement observed again (stale annotation re-read):
    # still one strike, pod survives
    sched.on_node_updated(node_obj)
    sched.on_node_updated(node_obj)
    assert annotations.assignment_from_pod(api.get_pod("default", "solo"))
    # advertiser recovers (full tree, fresh seq): strike resets
    advs[ref.host].advertise_once()
    sched.on_node_updated(api.get_node(ref.host))
    node_obj = _advertise_without_chip(api, ref.host, ref.device_index, seq=2)
    sched.on_node_updated(node_obj)
    assert annotations.assignment_from_pod(api.get_pod("default", "solo"))
    # a SECOND DISTINCT advertisement still missing the chip: now it's real
    node_obj = _advertise_without_chip(api, ref.host, ref.device_index, seq=3)
    sched.on_node_updated(node_obj)
    from kubegpu_tpu.utils.apiserver import NotFound
    with pytest.raises(NotFound):
        api.get_pod("default", "solo")


def test_undecodable_node_annotation_is_not_node_loss():
    # code-review r2: a node that IS listed but whose topology annotation
    # fails to decode orphans its pods in the cache exactly like a vanished
    # node — that is version skew, not node loss, and must never evict
    api, fs, advs = fake_cluster()
    sched = make_sched(api)
    obj = pod_obj("solo", 1)
    api.create_pod(obj)
    r = sched.filter(obj, nodes_of(api))
    assert sched.bind("default", "solo", r.nodes[0]) is None
    a = annotations.assignment_from_pod(api.get_pod("default", "solo"))
    api.patch_node_annotations(a.node, {annotations.NODE_TOPOLOGY: "{corrupt"})
    for _ in range(4):  # well past any grace window
        sched.resync()
    assert annotations.assignment_from_pod(api.get_pod("default", "solo"))


def test_explicit_unhealthy_chip_still_evicts_immediately():
    api, fs, advs = fake_cluster()
    sched = make_sched(api)
    obj = pod_obj("solo", 1)
    api.create_pod(obj)
    r = sched.filter(obj, nodes_of(api))
    assert sched.bind("default", "solo", r.nodes[0]) is None
    a = annotations.assignment_from_pod(api.get_pod("default", "solo"))
    ref = a.all_chips()[0]
    fs.kill_chip(ref.coords)
    advs[ref.host].advertise_once()
    sched.on_node_updated(api.get_node(ref.host))
    from kubegpu_tpu.utils.apiserver import NotFound
    with pytest.raises(NotFound):
        api.get_pod("default", "solo")


def test_vanished_node_evicts_assignments_after_grace():
    # ADVICE r1: a node deleted from the API (advertiser dead, no final
    # unhealthy report) must not wedge its pods forever — resync() diffs
    # assignment hosts against live nodes and evicts after the grace window
    api, fs, advs = fake_cluster()
    sched = make_sched(api)
    obj = pod_obj("solo", 1)
    api.create_pod(obj)
    r = sched.filter(obj, nodes_of(api))
    assert sched.bind("default", "solo", r.nodes[0]) is None
    a = annotations.assignment_from_pod(api.get_pod("default", "solo"))
    api.delete_node(a.node)
    sched.resync()  # strike 1: grace
    assert annotations.assignment_from_pod(api.get_pod("default", "solo"))
    sched.resync()  # strike 2: evict
    from kubegpu_tpu.utils.apiserver import NotFound
    with pytest.raises(NotFound):
        api.get_pod("default", "solo")
    assert sched.metrics.get("kubegpu_health_evictions_total") == 1


def test_node_blip_does_not_evict():
    api, fs, advs = fake_cluster()
    sched = make_sched(api)
    obj = pod_obj("solo", 1)
    api.create_pod(obj)
    r = sched.filter(obj, nodes_of(api))
    assert sched.bind("default", "solo", r.nodes[0]) is None
    a = annotations.assignment_from_pod(api.get_pod("default", "solo"))
    node_obj = api.get_node(a.node)
    api.delete_node(a.node)
    sched.resync()  # strike 1
    api.add_node(node_obj)  # node comes back
    sched.resync()  # strike reset
    api.delete_node(a.node)
    sched.resync()  # strike 1 again — still within grace
    assert annotations.assignment_from_pod(api.get_pod("default", "solo"))


def test_pod_delete_returns_chips():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    obj = pod_obj("tmp", 4)
    api.create_pod(obj)
    r = sched.filter(obj, nodes_of(api))
    assert sched.bind("default", "tmp", r.nodes[0]) is None
    api.delete_pod("default", "tmp")
    sched.on_pod_deleted(obj)
    view = next(iter(sched.cache.views().values()))
    assert len(view.free) == 16


# -- stranded-gang rollback hardening ---------------------------------------
#
# Rollback deletes running pods, so the partiality verdict must survive the
# three ways a HEALTHY gang can look partial (VERDICT r2 weak #4 / next #7,
# ADVICE r2 low #1): terminal-phase members, Terminating victims, and
# stale pod-group-size annotations.

def bind_gang(api, sched, group, names, chips=2, size=None):
    size = size or len(names)
    for name in names:
        obj = pod_obj(name, chips, group=group, group_size=size)
        api.create_pod(obj)
    for name in names:
        obj = api.get_pod("default", name)
        r = sched.filter(obj, nodes_of(api))
        assert r.nodes, (name, r.failed)
        assert sched.bind("default", name, r.nodes[0]) is None


def set_pod_status(api, name, phase=None, deleting=False, ns="default"):
    """Directly mutate stored pod state the InMemory API has no verb for."""
    with api._lock:
        pod = api._pods[f"{ns}/{name}"]
        if phase:
            pod["status"] = {"phase": phase}
        if deleting:
            pod["metadata"]["deletionTimestamp"] = "2026-07-30T00:00:00Z"


def test_succeeded_members_gc_one_at_a_time_is_not_a_stranded_gang():
    """ADVICE r2 low #1 scenario: a fully-Succeeded gang whose members a
    TTL controller garbage-collects one at a time must NOT be judged
    0 < bound < size and 'rolled back' (deleting the surviving completed
    pods)."""
    api, _, _ = fake_cluster()
    sched = make_sched(api, stranded_grace=2)
    bind_gang(api, sched, "done-gang", ["d-a", "d-b"])
    set_pod_status(api, "d-a", phase="Succeeded")
    set_pod_status(api, "d-b", phase="Succeeded")
    api.delete_pod("default", "d-a")  # GC'd first; d-b still listed+bound
    sched.on_pod_deleted(pod_obj("d-a", 2, group="done-gang", group_size=2))
    for _ in range(4):
        sched.resync()
    api.get_pod("default", "d-b")  # still exists — no rollback
    assert sched.metrics.get("kubegpu_stranded_gang_rollbacks_total") in (0, None)


def test_mixed_succeeded_and_running_gang_not_rolled_back():
    """Succeeded members shrink the denominator: a gang whose coordinator
    finished while its workers run is complete, not stranded."""
    api, _, _ = fake_cluster()
    sched = make_sched(api, stranded_grace=2)
    bind_gang(api, sched, "mix", ["m-a", "m-b", "m-c", "m-d"])
    set_pod_status(api, "m-a", phase="Succeeded")
    set_pod_status(api, "m-b", phase="Succeeded")
    for _ in range(4):
        sched.resync()
    for name in ("m-a", "m-b", "m-c", "m-d"):
        api.get_pod("default", name)
    assert sched.metrics.get("kubegpu_stranded_gang_rollbacks_total") in (0, None)


def test_stale_size_annotation_does_not_rollback_healthy_gang():
    """Consensus denominator (VERDICT r2 next #7): one recreated member
    carrying a stale larger pod-group-size must not move the denominator
    and get a fully-bound healthy gang rolled back."""
    api, _, _ = fake_cluster()
    sched = make_sched(api, stranded_grace=2)
    bind_gang(api, sched, "g", ["h-a", "h-b"])
    # stale straggler: same group, pending, claims size 3
    api.create_pod(pod_obj("h-stale", 2, group="g", group_size=3))
    for _ in range(4):
        sched.resync()
    api.get_pod("default", "h-a")
    api.get_pod("default", "h-b")
    assert sched.metrics.get("kubegpu_stranded_gang_rollbacks_total") in (0, None)


def test_terminating_victim_does_not_mask_stranded_gang():
    """A member stuck Terminating holds spec.nodeName but is leaving: it
    must not count as bound, or a gang that lost it would look complete
    forever and leak its chips."""
    api, _, _ = fake_cluster()
    sched = make_sched(api, stranded_grace=2)
    bind_gang(api, sched, "t", ["t-a", "t-b"])
    set_pod_status(api, "t-b", deleting=True)
    for _ in range(3):
        sched.resync()
    assert sched.metrics.get("kubegpu_stranded_gang_rollbacks_total") == 1
    # rollback freed EVERYTHING: the live member, and the Terminating
    # member's stale assignment annotation (releasable sweep)
    with pytest.raises(Exception):
        api.get_pod("default", "t-a")
    view = next(iter(sched.cache.views().values()))
    assert len(view.free) == 16


def test_genuine_stranded_gang_still_rolled_back():
    """Regression guard: the hardening must not blunt the sweep — a gang
    with one bound member and one that never arrived still rolls back
    after stranded_grace no-progress resyncs."""
    api, _, _ = fake_cluster()
    sched = make_sched(api, stranded_grace=2)
    bind_gang(api, sched, "s", ["s-a", "s-b"])
    # s-b vanishes without the watch seeing it (hard kill + missed event):
    # the gang is 1/2 bound with no plan and no replacement in sight
    api.delete_pod("default", "s-b")
    sched.cache.remove_pod("default/s-b")
    for _ in range(3):
        sched.resync()
    assert sched.metrics.get("kubegpu_stranded_gang_rollbacks_total") == 1
    with pytest.raises(Exception):
        api.get_pod("default", "s-a")


def test_gcd_succeeded_members_keep_shrinking_denominator():
    """Once a member is SEEN Succeeded, the sweep remembers it: the TTL
    controller deleting it between resyncs must not resurrect the partial
    verdict and roll back the still-running siblings."""
    api, _, _ = fake_cluster()
    sched = make_sched(api, stranded_grace=2)
    bind_gang(api, sched, "gc", ["gc-a", "gc-b", "gc-c", "gc-d"])
    set_pod_status(api, "gc-a", phase="Succeeded")
    set_pod_status(api, "gc-b", phase="Succeeded")
    sched.resync()  # sweep observes the Succeeded phases
    for name in ("gc-a", "gc-b"):
        obj = api.get_pod("default", name)
        api.delete_pod("default", name)  # TTL-controller GC
        sched.on_pod_deleted(obj)
    for _ in range(4):
        sched.resync()
    api.get_pod("default", "gc-c")  # running members untouched
    api.get_pod("default", "gc-d")
    assert sched.metrics.get("kubegpu_stranded_gang_rollbacks_total") in (0, None)


def test_terminal_phase_pod_holds_no_chips():
    """kube-scheduler accounting: a Succeeded/Failed pod's chips are free
    the moment the phase lands, annotation lingering or not — so a
    shrunken gang (or anyone) can re-admit on them without waiting for
    pod GC."""
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    obj = pod_obj("done", 4)
    api.create_pod(obj)
    r = sched.filter(obj, nodes_of(api))
    assert sched.bind("default", "done", r.nodes[0]) is None
    view = next(iter(sched.cache.views().values()))
    assert len(view.free) == 12
    set_pod_status(api, "done", phase="Succeeded")
    sched.cache.refresh()
    view = next(iter(sched.cache.views().values()))
    assert len(view.free) == 16
    # the annotation is history, not a claim — it is left in place
    a = annotations.assignment_from_pod(api.get_pod("default", "done"))
    assert a is not None


def test_pod_deleted_event_survives_malformed_extended_resource():
    """The watch fast path must parse leniently: a DELETED event for a pod
    with an unparseable extended-resource quantity still frees its chips
    and drops its gang plan (strict parsing would silently drop the event
    and reintroduce the TTL wait)."""
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    obj = pod_obj("messy", 4)
    api.create_pod(obj)
    r = sched.filter(obj, nodes_of(api))
    assert sched.bind("default", "messy", r.nodes[0]) is None
    gone = api.get_pod("default", "messy")
    gone["spec"]["containers"][0]["resources"]["limits"]["vendor.com/dev"] = "1Gi"
    api.delete_pod("default", "messy")
    sched.on_pod_deleted(gone)
    view = next(iter(sched.cache.views().values()))
    assert len(view.free) == 16


def test_replacement_plans_while_sibling_succeeded():
    """Planner/sweep arithmetic must agree: a gang with one Succeeded
    member and one dead member re-plans the replacement against the
    OUTSTANDING size (declared minus completed) — it must not wait
    forever for a 4th member that already finished."""
    api, _, _ = fake_cluster()
    sched = make_sched(api, stranded_grace=2)
    bind_gang(api, sched, "rp", ["rp-a", "rp-b", "rp-c", "rp-d"])
    set_pod_status(api, "rp-a", phase="Succeeded")
    sched.resync()  # observe the completion
    # rp-d dies and is recreated by its controller
    dead = api.get_pod("default", "rp-d")
    api.delete_pod("default", "rp-d")
    sched.on_pod_deleted(dead)
    fresh = pod_obj("rp-d", 2, group="rp", group_size=4)
    api.create_pod(fresh)
    r = sched.filter(fresh, nodes_of(api))
    assert r.nodes, r.failed  # plans 1 replacement vs outstanding 3, not 4
    assert sched.bind("default", "rp-d", r.nodes[0]) is None
    # and the sweep agrees the gang is whole: no rollback ever fires
    for _ in range(4):
        sched.resync()
    assert sched.metrics.get("kubegpu_stranded_gang_rollbacks_total") in (0, None)


def test_stale_deleted_event_for_recreated_name_is_ignored():
    """The watch delivers by name: a delayed DELETED event must not free
    the chips of a same-named RECREATED pod that has since bound (the
    double-allocation the GET-confirm guard exists to stop)."""
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    obj = pod_obj("phoenix", 4)
    api.create_pod(obj)
    r = sched.filter(obj, nodes_of(api))
    assert sched.bind("default", "phoenix", r.nodes[0]) is None
    old = api.get_pod("default", "phoenix")
    api.delete_pod("default", "phoenix")
    sched.on_pod_deleted(old)
    # controller recreates the name; it schedules and binds again
    api.create_pod(pod_obj("phoenix", 4))
    r2 = sched.filter(pod_obj("phoenix", 4), nodes_of(api))
    assert sched.bind("default", "phoenix", r2.nodes[0]) is None
    view = next(iter(sched.cache.views().values()))
    assert len(view.free) == 12
    # the OLD pod's DELETED event finally drains — and must be a no-op
    sched.on_pod_deleted(old)
    view = next(iter(sched.cache.views().values()))
    assert len(view.free) == 12, "stale DELETED freed the recreated pod's chips"


def test_resync_reconciles_plan_with_vanished_member():
    """Missed-DELETED backstop (found by the chaos soak): a gang plan
    covering a member whose deletion event was never seen would otherwise
    shield the gang from re-planning and hold reservations until plan
    TTL.  resync() GET-confirms the absence and drops the plan; the
    remaining members re-plan and admit with a replacement."""
    api, _, _ = fake_cluster()
    sched = make_sched(api, gang_plan_ttl_s=3600.0)
    objs = [pod_obj(f"v{i}", 4, group="van", group_size=2) for i in range(2)]
    for o in objs:
        api.create_pod(o)
    r = sched.filter(objs[0], nodes_of(api))
    assert r.nodes
    assert sched.groups.has_live_plan("default/van")
    # v1 vanishes WITHOUT the watch seeing it (hard kill + dropped event)
    api.delete_pod("default", "v1")
    sched.resync()
    assert not sched.groups.has_live_plan("default/van")
    # reservations returned: v0's chips are free again for the re-plan
    assert sched.cache.assignment_of("default/v0") is None
    assert sched.cache.assignment_of("default/v1") is None
    # the controller recreates v1; the gang re-plans and fully admits
    api.create_pod(pod_obj("v1", 4, group="van", group_size=2))
    for name in ("v0", "v1"):
        obj = api.get_pod("default", name)
        r = sched.filter(obj, nodes_of(api))
        assert r.nodes, r.failed
        assert sched.bind("default", name, r.nodes[0]) is None


# -- conflict sweep gating + detector cleanup (ADVICE r2 lows #2, #3) --------

def make_conflict(api, sched):
    """Two live annotations claiming one chip set: bind 'owner' normally,
    then plant 'thief' with a copy of its assignment annotation."""
    obj = pod_obj("owner", 2)
    api.create_pod(obj)
    r = sched.filter(obj, nodes_of(api))
    assert sched.bind("default", "owner", r.nodes[0]) is None
    bound = api.get_pod("default", "owner")
    thief = pod_obj("thief", 2)
    thief["metadata"]["annotations"][annotations.POD_ASSIGNMENT] = (
        bound["metadata"]["annotations"][annotations.POD_ASSIGNMENT]
    )
    thief["spec"]["nodeName"] = bound["spec"]["nodeName"]
    api.create_pod(thief)
    sched.cache.refresh()
    assert "default/thief" in sched.cache.conflicted_assignments()


def test_conflict_sweep_runs_with_chip_eviction_disabled():
    """ADVICE r2 low #2: disabling chip-health eviction must not silently
    disable durable double-annotation resolution."""
    api, _, _ = fake_cluster()
    sched = make_sched(api, evict_on_chip_failure=False, absent_grace=2)
    make_conflict(api, sched)
    sched.resync()  # strike 1
    sched.resync()  # strike 2: evict the uncharged claimant
    with pytest.raises(Exception):
        api.get_pod("default", "thief")
    api.get_pod("default", "owner")  # charged owner untouched


def test_remove_pod_clears_conflict_and_orphan_tracking():
    """ADVICE r2 low #3: a pod deleted while conflict-tracked must leave
    every detector immediately — no strikes toward evicting a ghost."""
    api, _, _ = fake_cluster()
    sched = make_sched(api, absent_grace=2)
    make_conflict(api, sched)
    sched.cache.remove_pod("default/thief")
    assert "default/thief" not in sched.cache.conflicted_assignments()
    # orphan path: vanish a node, then remove its pod
    sched.cache.refresh()
    assert "default/thief" in sched.cache.conflicted_assignments()
    victim_node = api.get_pod("default", "owner")["spec"]["nodeName"]
    api.delete_node(victim_node)
    sched.cache.refresh()
    assert "default/owner" in sched.cache.orphaned_assignments()
    sched.cache.remove_pod("default/owner")
    assert "default/owner" not in sched.cache.orphaned_assignments()


# -- HTTP wire --------------------------------------------------------------

@pytest.fixture()
def http_server():
    api, _, _ = fake_cluster()
    sched = Scheduler(api, metrics=Metrics())
    srv = ExtenderServer(sched, listen=("127.0.0.1", 0))
    srv.start()
    yield api, srv
    srv.stop()


def _post(addr, path, payload):
    req = urllib.request.Request(
        f"http://{addr[0]}:{addr[1]}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=5) as resp:
        return json.loads(resp.read())


def _get(addr, path):
    with urllib.request.urlopen(f"http://{addr[0]}:{addr[1]}{path}", timeout=5) as resp:
        return resp.read().decode()


def test_http_extender_end_to_end(http_server):
    api, srv = http_server
    addr = srv.address
    assert _get(addr, "/healthz") == "ok"
    obj = pod_obj("h0", 2)
    api.create_pod(obj)
    flt = _post(addr, "/filter", {"Pod": obj, "NodeNames": nodes_of(api)})
    assert flt["Error"] == "" and len(flt["NodeNames"]) == 4
    pri = _post(addr, "/prioritize", {"Pod": obj, "NodeNames": flt["NodeNames"]})
    assert all(0 <= e["Score"] <= 10 for e in pri)
    best = max(pri, key=lambda e: e["Score"])["Host"]
    bnd = _post(
        addr, "/bind", {"PodName": "h0", "PodNamespace": "default", "Node": best}
    )
    assert bnd["Error"] == ""
    assert api.get_pod("default", "h0")["spec"]["nodeName"] == best
    metrics = _get(addr, "/metrics")
    assert "kubegpu_bind_total 1.0" in metrics
    state = json.loads(_get(addr, "/state"))
    assert len(state["slices"]["s0"]["used"]) == 2


def test_http_full_node_objects_supported(http_server):
    api, srv = http_server
    addr = srv.address
    obj = pod_obj("h1", 1)
    api.create_pod(obj)
    flt = _post(addr, "/filter", {"Pod": obj, "Nodes": {"Items": api.list_nodes()}})
    assert len(flt["Nodes"]["Items"]) == 4


def test_http_malformed_body_is_400_not_crash(http_server):
    _, srv = http_server
    addr = srv.address
    req = urllib.request.Request(
        f"http://{addr[0]}:{addr[1]}/filter", data=b"{not json", method="POST"
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=5)
    assert ei.value.code == 400
    # server still alive
    assert _get(addr, "/healthz") == "ok"


def test_http_malformed_pod_returns_error_not_500(http_server):
    api, srv = http_server
    addr = srv.address
    bad = {"metadata": {"name": "b"}, "spec": {"containers": [
        {"name": "m", "resources": {"limits": {RES_TPU: "four"}}}]}}
    flt = _post(addr, "/filter", {"Pod": bad, "NodeNames": nodes_of(api)})
    assert "unparseable pod" in flt["Error"]


def test_http_malformed_extended_resource_rejected_like_tpu(http_server):
    # a quantity the plugin registry can't parse must FAIL the pod, exactly
    # like a malformed google.com/tpu — not silently bypass device accounting
    api, srv = http_server
    addr = srv.address
    bad = {"metadata": {"name": "npu-bad"}, "spec": {"containers": [
        {"name": "m", "resources": {"limits": {"example.com/npu": "2k"}}}]}}
    flt = _post(addr, "/filter", {"Pod": bad, "NodeNames": nodes_of(api)})
    assert "unparseable pod" in flt["Error"]


# -- prioritize score fidelity (VERDICT r1 #10) -----------------------------

def test_scale_scores_rank_preserving_and_stretched():
    from kubegpu_tpu.scheduler.core import _scale_scores

    # distinct raw scores must stay distinct after quantization (when the
    # candidate set has <= 10 fitting nodes) — round(/10) provably merged
    # scores 71 and 78 into one bucket
    raw = [("a", 78.0), ("b", 71.0), ("c", 45.0), ("d", None)]
    out = dict(_scale_scores(raw))
    assert out["d"] == 0
    assert out["a"] == 10                      # best always 10
    assert out["c"] == 1                       # worst fitting always 1
    assert 1 < out["b"] < 10
    assert out["a"] > out["b"] > out["c"] > out["d"]
    # ties stay ties; all-fitting-equal -> all 10
    assert dict(_scale_scores([("x", 50.0), ("y", 50.0)])) == {"x": 10, "y": 10}
    assert dict(_scale_scores([("x", None)])) == {"x": 0}
    assert _scale_scores([]) == []


def test_prioritize_distinguishes_placements_round_would_merge():
    """Integration: two hosts whose raw grpalloc scores differ by less than
    a round(/10) bucket must still get different extender scores."""
    api, fs, _ = fake_cluster()
    sched = make_sched(api)
    # occupy one host's block partially so its anti-frag score differs
    filler = pod_obj("filler", 1)
    api.create_pod(filler)
    r = sched.filter(filler, nodes_of(api))
    assert sched.bind("default", "filler", r.nodes[0]) is None

    obj = pod_obj("probe", 2)
    api.create_pod(obj)
    r = sched.filter(obj, nodes_of(api))
    scores = dict(sched.prioritize(obj, r.nodes))
    assert max(scores.values()) == 10
    assert min(scores.values()) >= 1  # every fitting node beats non-fitting
    # the candidate set is stretched: unless every raw score ties, at least
    # two distinct extender scores exist
    raw = set(scores.values())
    assert len(raw) >= 2, scores


# -- operator status CLI ------------------------------------------------------

def test_status_cli_renders_live_extender(capsys):
    """The kubectl-get-style surface: status.py against a live extender
    shows slice occupancy, in-flight gang plans, and headline counters."""
    from kubegpu_tpu.scheduler import status

    api, _, _ = fake_cluster()
    sched = Scheduler(api, metrics=Metrics())
    srv = ExtenderServer(sched, listen=("127.0.0.1", 0))
    srv.start()
    try:
        url = f"http://{srv.address[0]}:{srv.address[1]}"
        # one bound pod + one planned-but-unbound gang member in flight
        obj = pod_obj("solo", 4)
        api.create_pod(obj)
        r = sched.filter(obj, nodes_of(api))
        assert sched.bind("default", "solo", r.nodes[0]) is None
        for i in range(2):
            api.create_pod(pod_obj(f"s{i}", 2, group="st", group_size=2))
        rg = sched.filter(api.get_pod("default", "s0"), nodes_of(api))
        assert rg.nodes

        assert status.main(["--url", url]) == 0
        out = capsys.readouterr().out
        assert "slice s0" in out and "mesh 4x4" in out
        assert "#" in out and "." in out            # occupancy map
        assert "default/st" in out                   # in-flight plan
        assert "placements_total" in out             # headline counter
        assert status.main(["--url", url, "--json"]) == 0
        parsed = json.loads(capsys.readouterr().out)
        assert parsed["gang_plans"]["default/st"]["committed"] == []
    finally:
        srv.stop()


def test_status_cli_unreachable_is_clean_error(capsys):
    from kubegpu_tpu.scheduler import status

    assert status.main(["--url", "http://127.0.0.1:1", "--timeout", "0.5"]) == 1
    assert "cannot reach" in capsys.readouterr().err


def test_status_render_slice_3d():
    """v4/v5p 3D topologies render one 2D map per z-layer, not garbage."""
    from kubegpu_tpu.scheduler.status import render_slice

    out = render_slice("v4", {
        "mesh": [2, 2, 2],
        "used": [[0, 0, 0], [1, 0, 0]],
        "free": [[0, 1, 0], [1, 1, 0], [0, 0, 1], [1, 0, 1], [0, 1, 1], [1, 1, 1]],
        "hosts": ["h0"],
    })
    assert "mesh 2x2x2" in out
    assert "z=0:" in out and "z=1:" in out
    map_rows = [
        ln for ln in out.splitlines() if ln.startswith("    ") and " " in ln.strip()
    ]
    assert map_rows and all("x" not in ln for ln in map_rows), map_rows
    assert sum(ln.count("#") for ln in map_rows) == 2  # exactly the used pair


def test_gang_name_reuse_after_success_not_wedged():
    """ADVICE r3 medium: a NEW generation of pods created under a reused
    gang name, while the previous generation's Succeeded pods are still
    listed, must schedule.  Remembered-done arithmetic would otherwise pin
    outstanding at 0 and _select_members would reject every new member —
    the gang permanently unschedulable until scheduler restart."""
    api, _, _ = fake_cluster()
    sched = make_sched(api, stranded_grace=2)
    bind_gang(api, sched, "job", ["gen1-a", "gen1-b"])
    set_pod_status(api, "gen1-a", phase="Succeeded")
    set_pod_status(api, "gen1-b", phase="Succeeded")
    sched.resync()  # the sweep remembers both members Succeeded
    assert sched.groups.done_count("default/job") == 2
    # second generation reuses the gang name with fresh pod names
    objs = [pod_obj(f"gen2-{s}", 2, group="job", group_size=2) for s in "ab"]
    for o in objs:
        api.create_pod(o)
    for o in objs:
        name = o["metadata"]["name"]
        r = sched.filter(o, nodes_of(api))
        assert r.nodes, (name, r.failed)
        assert sched.bind("default", name, r.nodes[0]) is None
    # and the sweep judges the new generation healthy (no rollback)
    for _ in range(4):
        sched.resync()
    api.get_pod("default", "gen2-a")
    api.get_pod("default", "gen2-b")
    assert sched.metrics.get("kubegpu_stranded_gang_rollbacks_total") in (0, None)


def bind_gang_uid(api, sched, group, names, group_uid, chips=2):
    for name in names:
        api.create_pod(pod_obj(name, chips, group=group,
                               group_size=len(names), group_uid=group_uid))
    for name in names:
        obj = api.get_pod("default", name)
        r = sched.filter(obj, nodes_of(api))
        assert r.nodes, (name, r.failed)
        assert sched.bind("default", name, r.nodes[0]) is None


def test_gang_name_reuse_with_uid_new_run_can_still_strand():
    """Incarnation ids (pod-group-uid) make reuse unambiguous: a new run
    that binds one member and loses the other is judged against the full
    size — the old run's completions never shrink its denominator — and
    still rolls back after stranded_grace no-progress resyncs."""
    api, _, _ = fake_cluster()
    sched = make_sched(api, stranded_grace=2)
    bind_gang_uid(api, sched, "rg", ["r1-a", "r1-b"], group_uid="run-1")
    set_pod_status(api, "r1-a", phase="Succeeded")
    set_pod_status(api, "r1-b", phase="Succeeded")
    sched.resync()
    assert sched.groups.done_count("default/rg", "run-1") == 2
    bind_gang_uid(api, sched, "rg", ["r2-a", "r2-b"], group_uid="run-2")
    # r2-b vanishes hard (missed DELETED event): 1/2 bound, no plan
    api.delete_pod("default", "r2-b")
    sched.cache.remove_pod("default/r2-b")
    for _ in range(3):
        sched.resync()
    assert sched.metrics.get("kubegpu_stranded_gang_rollbacks_total") == 1


def test_reused_name_partial_success_not_rolled_back():
    """Code-review r4 regression: a reused-name gang whose NEW run
    partially succeeds (one member done, one still running) must not be
    judged stranded — neither with incarnation ids (done memory scoped
    per run) nor without (arithmetic ambiguous -> sweep declines)."""
    for uids in (("run-1", "run-2"), (None, None)):
        api, _, _ = fake_cluster()
        sched = make_sched(api, stranded_grace=2)
        if uids[0]:
            bind_gang_uid(api, sched, "pr", ["p1-a", "p1-b"], group_uid=uids[0])
        else:
            bind_gang(api, sched, "pr", ["p1-a", "p1-b"])
        set_pod_status(api, "p1-a", phase="Succeeded")
        set_pod_status(api, "p1-b", phase="Succeeded")
        sched.resync()
        if uids[1]:
            bind_gang_uid(api, sched, "pr", ["p2-a", "p2-b"], group_uid=uids[1])
        else:
            bind_gang(api, sched, "pr", ["p2-a", "p2-b"])
        # the new run's first member completes; its sibling keeps running
        set_pod_status(api, "p2-a", phase="Succeeded")
        for _ in range(4):
            sched.resync()
        api.get_pod("default", "p2-b")  # survivor untouched
        assert sched.metrics.get(
            "kubegpu_stranded_gang_rollbacks_total"
        ) in (0, None), f"false rollback with uids={uids}"


def test_wrong_node_bind_with_racing_drop_plan_frees_reservation():
    """Code-review r4 regression: bind marks the key mid-bind for the
    whole verb, so a drop_plan racing it skips the key when freeing the
    plan's reservations.  The early wrong-node return must then free the
    now-ownerless (planless, still-assumed) reservation itself, or the
    chips stay charged forever."""
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    objs = [pod_obj(f"wn-{s}", 2, group="wj", group_size=2) for s in "ab"]
    for o in objs:
        api.create_pod(o)
    r = sched.filter(objs[0], nodes_of(api))
    assert r.nodes, r.failed
    planned_node = r.nodes[0]
    wrong = next(n for n in nodes_of(api) if n != planned_node)
    # simulate reconcile dropping the plan between bind's plan lookup and
    # its wrong-node check (the key is already marked mid-bind there)
    orig = sched.groups.plan_for

    def racing_plan_for(pod, now=None):
        plan = orig(pod, now=now)
        if plan is not None:
            sched.groups.drop_plan("default/wj")
        return plan

    sched.groups.plan_for = racing_plan_for
    try:
        err = sched.bind("default", "wn-a", wrong)
    finally:
        sched.groups.plan_for = orig
    assert err is not None and "gang plan places" in err
    # no reservation may survive: the plan freed wn-b, the bind freed wn-a
    assert sched.cache.assumed_keys() == []
    view = next(iter(sched.cache.views().values()))
    assert len(view.free) == 16


def _backdate_assignment(api, name, by_s, ns="default"):
    """Age a pod's durable bind stamp in its annotation by `by_s` (a live
    cache keeps its own in-memory objects by design — refresh never lets
    stale LIST data displace live memory — so aging is observed through a
    restart-shaped cold adoption, not a refresh)."""
    obj = api.get_pod(ns, name)
    a = annotations.assignment_from_pod(obj)
    a.bound_at -= by_s
    api.patch_pod_annotations(
        ns, name, {annotations.POD_ASSIGNMENT: annotations.encode_assignment(a)}
    )


def test_min_runtime_shield_prevents_gang_starvation():
    """VERDICT r3 #8: two high-priority tenants alternately preempting a
    low-priority gang must not starve it.  With the min-runtime shield a
    freshly-admitted gang is non-preemptible — the VIP's preemption
    attempt finds no victims and the VIP waits; once the gang has had its
    guaranteed runtime, preemption proceeds as before.  The shield rides
    the assignment annotation, so it also survives a scheduler restart."""
    api, _, _ = fake_cluster()
    sched = make_sched(api, preemption_min_runtime_s=300.0)
    # low-priority gang fills the whole slice (4 members x 4 chips/host)
    for i in range(4):
        api.create_pod(pod_obj(f"low-{i}", 4, group="lowg", group_size=4))
    for i in range(4):
        obj = api.get_pod("default", f"low-{i}")
        r = sched.filter(obj, nodes_of(api))
        assert r.nodes, r.failed
        assert sched.bind("default", f"low-{i}", r.nodes[0]) is None
    # VIP arrives immediately: the gang is inside its shield window, so
    # active preemption finds no victims and the VIP is refused
    vip = {
        "metadata": {"name": "vip", "namespace": "default", "uid": "uid-vip",
                     "annotations": {annotations.POD_PRIORITY: "9"}},
        "spec": {"containers": [
            {"name": "m", "resources": {"limits": {RES_TPU: "4"}}}]},
    }
    api.create_pod(vip)
    r = sched.filter(vip, nodes_of(api))
    assert r.nodes == [], "VIP admitted by evicting a shielded gang"
    for i in range(4):
        api.get_pod("default", f"low-{i}")  # the gang survived
    # the advisory verb honors the same shield
    assert sched.preemption_victims(vip) == {}
    # the shield SURVIVES a scheduler restart: a fresh instance adopts the
    # bind stamps from the annotations and still refuses
    sched2 = make_sched(api, preemption_min_runtime_s=300.0)
    assert sched2.filter(vip, nodes_of(api)).nodes == []
    for i in range(4):
        api.get_pod("default", f"low-{i}")
    # guaranteed runtime elapses (age the durable stamps past the window;
    # observed through restart-shaped cold adoption)
    for i in range(4):
        _backdate_assignment(api, f"low-{i}", 3600.0)
    sched3 = make_sched(api, preemption_min_runtime_s=300.0)
    r = sched3.filter(vip, nodes_of(api))
    assert r.nodes, (r.failed, "aged gang should be preemptible again")
    assert sched3.bind("default", "vip", r.nodes[0]) is None
