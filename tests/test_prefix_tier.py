"""Fleet-wide shared-prefix KV tier (ISSUE 16).

Layers under test:

- chain-key parity — ``prompt_chain_keys`` computed gateway-side equals
  the ``page_keys`` a replica's ``export_sealed_chain`` seals under, so
  a tier probe keyed off the raw prompt hits chains the replica sealed;
- the store's prefix namespace — payload dedup by content hash with
  refcounted references (a payload captured by N sessions and published
  as a prefix rests ONCE), double publish as a popularity bump (never a
  duplicate), popularity-weighted LRU eviction (hot chains outlive
  colder newer ones), and the longest-match probe;
- the ``PrefixTier`` engine — publish → probe → pre-prefill import over
  a fake client, local-warmth skip, miss accounting, and the full
  degradation contract (store unreachable ⇒ counted cold prefill,
  ``degraded_log`` mirroring the labeled metric, never an exception);
- ``PrefixLocalityRouter`` — routes to the warmest replica, breaks ties
  by least-outstanding, falls back to the consistent-hash ring when
  nothing is warm, and drops warmth on ``forget_replica``;
- REAL paged batchers — fp32 token identity of the tier-imported lane
  against a local-prefill reference across page sizes x fp32/int8/bf16
  pools, longest-prefix-that-fits under a small pool and re-import
  through LRU holes, ``/v1/state``'s prefix-cache economy surface, and
  page accounting after every import;
- the chaos lane — ``GatewaySoak(prefix_tier=True)``: the kill/revive
  schedule over paged replicas with the tier and locality router in the
  dispatch path, ``assert_page_accounting`` at quiescence, and (with
  ``store_chaos``) store outages resolving as counted tier degradations.
"""

import threading
from types import SimpleNamespace

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubegpu_tpu.gateway import (
    HttpStoreClient,
    InProcessStoreBackend,
    PrefixTier,
    prompt_chain_keys,
)
from kubegpu_tpu.gateway.prefixtier import PREFIX_DEGRADE_REASONS
from kubegpu_tpu.gateway.router import PrefixLocalityRouter
from kubegpu_tpu.gateway.sessionstore import payload_key
from kubegpu_tpu.models import TransformerLM
from kubegpu_tpu.models.paging import PagedContinuousBatcher
from kubegpu_tpu.utils.metrics import Metrics

CFG = dict(vocab_size=61, num_layers=2, num_heads=4, hidden=32, max_seq=96)

_params_cache = {}


def trained_params():
    if "p" not in _params_cache:
        model = TransformerLM(dtype=jnp.float32, **CFG)
        _params_cache["p"] = model.init(
            jax.random.PRNGKey(0), jnp.ones((2, 8), jnp.int32)
        )["params"]
    return _params_cache["p"]


def make_paged(params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("prompt_pad", 48)
    kw.setdefault("page_size", 4)
    kw.setdefault("pool_pages", 48)
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("decode_page_cache", "fp32")
    return PagedContinuousBatcher(params, **CFG, **kw)


class _BatcherClient:
    """The two-verb client surface the tier drives, over named local
    batchers — the in-process twin of ``InMemoryReplicaClient``'s
    export_sealed/import_sealed."""

    def __init__(self, batchers):
        self.batchers = batchers
        self.imports = []

    def export_sealed(self, key, stream):
        fn = getattr(self.batchers[key], "export_sealed_chain", None)
        return fn(np.asarray(stream, np.int32)) if fn else None

    def import_sealed(self, key, payload):
        fn = getattr(self.batchers[key], "import_sealed_chain", None)
        if fn is None:
            return False
        pages = fn(payload)
        self.imports.append((key, pages))
        return pages > 0


class _CannedClient:
    """Replays one canned sealed payload — the no-jax tier harness."""

    def __init__(self, payload):
        self.payload = payload
        self.imports = []

    def export_sealed(self, key, stream):
        return self.payload

    def import_sealed(self, key, payload):
        self.imports.append((key, payload))
        return True


def canned_payload(stream, page):
    keys = prompt_chain_keys(stream, page)
    n = len(keys)
    return {
        "kind": "sealed",
        "geometry": {"page": page, "layers": 1, "heads": 2, "head_dim": 4,
                     "dtype": "float32", "kv_dtype": "float32",
                     "schema": 2, "tp": 1},
        "page_keys": keys,
        "page_kinds": ["prompt"] * n,
        "layers": [(np.zeros((n, page, 2, 4), np.float32),
                    np.zeros((n, page, 2, 4), np.float32))],
    }


# ---------------------------------------------------------------------------
# 1. chain-key parity: gateway-side hashing == replica-side sealing
# ---------------------------------------------------------------------------

def test_prompt_chain_keys_match_sealed_export():
    """Keys computed from the raw token stream gateway-side must equal
    the page_keys the replica seals (same cumulative sha256 windows) —
    the property the whole probe path rests on."""
    params = trained_params()
    cb = make_paged(params)
    rng = np.random.RandomState(3)
    prompt = np.array(rng.randint(0, CFG["vocab_size"], size=9), np.int32)
    out = cb.run([prompt], [8])[0]
    stream = np.concatenate([prompt, np.asarray(out, np.int32)])
    payload = cb.export_sealed_chain(stream)
    # export seals COMMITTED rows only (len-1): mirror that window
    committed = len(stream) - 1
    want = prompt_chain_keys(stream[:committed], cb.page)
    assert payload["page_keys"] == want
    # the partial tail page never gets a key
    assert len(want) == committed // cb.page


def test_prompt_chain_keys_edges():
    assert prompt_chain_keys([], 4) == []
    assert prompt_chain_keys([1, 2, 3], 4) == []        # no full page
    assert prompt_chain_keys([1, 2, 3], 0) == []        # degenerate page
    a = prompt_chain_keys([1, 2, 3, 4, 5], 4)
    b = prompt_chain_keys([1, 2, 3, 4, 9], 4)           # same full page
    assert len(a) == 1 and a == b
    c = prompt_chain_keys([1, 2, 3, 9, 5], 4)           # diverges inside
    assert c != a


# ---------------------------------------------------------------------------
# 2. store: payload dedup + the prefix namespace
# ---------------------------------------------------------------------------

def sealed_entry(stream, page=4, replica="rA"):
    payload = canned_payload(np.asarray(stream, np.int32), page)
    return {"replica": replica, "stream": list(stream),
            "payload": payload, "lost": False}, payload


def test_session_payload_dedup_refcounted():
    """The satellite bugfix: two sessions capturing byte-identical
    payloads rest ONCE store-side — refcount 2, unique payload 1, and
    the payload outlives either single session."""
    b = InProcessStoreBackend()
    e1, payload = sealed_entry([1, 2, 3, 4, 5, 6, 7, 8, 9])
    e2, _ = sealed_entry([1, 2, 3, 4, 5, 6, 7, 8, 9], replica="rB")
    assert b.put("s1", e1, if_version=None).status == "ok"
    assert b.put("s2", e2, if_version=None).status == "ok"
    assert b.payload_refs(payload) == 2
    st = b.stats()
    assert st["unique_payloads"] == 1
    # one session dies: the payload survives for the other
    b.delete("s1")
    assert b.payload_refs(payload) == 1
    got = b.get("s2").entry["payload"]
    assert got["page_keys"] == payload["page_keys"]
    b.delete("s2")
    assert b.payload_refs(payload) == 0
    assert b.stats()["unique_payloads"] == 0


def test_prefix_publish_dedup_and_popularity():
    """Double publish is a popularity bump, never a duplicate; the
    payload is shared by refcount across the session and prefix
    namespaces."""
    b = InProcessStoreBackend()
    e, payload = sealed_entry([5, 4, 3, 2, 1, 0, 6, 7, 8])
    chain = payload["page_keys"][-1]
    r1 = b.put_prefix(chain, {"payload": payload,
                              "page_keys": payload["page_keys"],
                              "pages": len(payload["page_keys"])})
    assert r1.status == "ok" and r1.entry["stored"]
    r2 = b.put_prefix(chain, {"payload": payload,
                              "page_keys": payload["page_keys"],
                              "pages": len(payload["page_keys"])})
    assert r2.status == "ok" and not r2.entry["stored"]
    assert b.payload_refs(payload) == 1          # prefix namespace: once
    assert b.stats()["prefixes"] == 1
    # a session capturing the same bytes shares the record: refs 2,
    # unique payload still 1
    assert b.put("s1", e, if_version=None).status == "ok"
    assert b.payload_refs(payload) == 2
    assert b.stats()["unique_payloads"] == 1
    # the prefix keeps the payload alive past the session's delete
    b.delete("s1")
    assert b.payload_refs(payload) == 1
    full = b.get_prefix(chain)
    assert full.status == "ok"
    assert full.entry["payload"]["page_keys"] == payload["page_keys"]


def test_prefix_popularity_weighted_lru_eviction():
    """Under byte pressure the COLDEST chain (fewest hits, oldest
    touch) evicts first — a hot old chain outlives a cold newer one."""
    b = InProcessStoreBackend(max_prefix_bytes=1)  # every put overflows
    streams = ([1] * 9, [2] * 9, [3] * 9)
    chains = []
    for i, s in enumerate(streams):
        _, payload = sealed_entry(s)
        chain = payload["page_keys"][-1]
        chains.append(chain)
        b.put_prefix(chain, {"payload": payload,
                             "page_keys": payload["page_keys"],
                             "pages": len(payload["page_keys"])})
        if i == 0:
            # make chain 0 HOT before the next publishes arrive
            for _ in range(3):
                b.probe_prefix(payload["page_keys"])
    # byte budget of 1: at most the newest/hottest survives each put;
    # the hot chain-0 must have outlived the cold chain-1
    assert b.get_prefix(chains[0], meta=True).status in ("ok", "absent")
    st = b.stats()
    assert st["prefixes"] <= 2
    evicted = b.metrics_evictions if hasattr(b, "metrics_evictions") else None
    # the direct oracle: chain 1 (cold, older than 2) cannot have
    # survived while 0 and 2 are present
    present = [
        c for c in chains if b.get_prefix(c, meta=True).status == "ok"
    ]
    assert chains[1] not in present or len(present) == 1


def test_prefix_probe_longest_match():
    b = InProcessStoreBackend()
    stream = [7, 7, 1, 2, 3, 4, 5, 6, 9, 9, 9, 9, 0]
    _, payload = sealed_entry(stream)
    chain = payload["page_keys"][-1]
    b.put_prefix(chain, {"payload": payload,
                         "page_keys": payload["page_keys"],
                         "pages": len(payload["page_keys"])})
    # a prompt sharing 2 full pages then diverging probes to match 2
    probe_keys = prompt_chain_keys(stream[:8] + [42, 43, 44, 45], 4)
    res = b.probe_prefix(probe_keys)
    assert res.status == "ok"
    assert res.entry["chain"] == chain
    assert res.entry["match_pages"] == 2
    assert res.entry["pages"] == 3
    # nothing shared: absent
    res = b.probe_prefix(prompt_chain_keys([40] * 12, 4))
    assert res.status == "absent"


def test_prefix_ttl_reaps_idle_chains():
    b = InProcessStoreBackend(prefix_lease_s=0.0)  # instant lapse
    _, payload = sealed_entry([1] * 9)
    chain = payload["page_keys"][-1]
    b.put_prefix(chain, {"payload": payload,
                         "page_keys": payload["page_keys"], "pages": 2})
    # TTL 0: the very next probe sees it reaped (immortal only while hot)
    assert b.probe_prefix(payload["page_keys"]).status == "absent"
    assert b.get_prefix(chain).status == "absent"


# ---------------------------------------------------------------------------
# 3. PrefixTier engine (no jax): publish/probe/import + degradation
# ---------------------------------------------------------------------------

def test_tier_publish_then_import_on_cold_replica():
    metrics = Metrics()
    tier = PrefixTier(page=4, metrics=metrics)
    stream = [1, 2, 3, 4, 5, 6, 7, 8, 9]
    payload = canned_payload(np.asarray(stream, np.int32), 4)
    client = _CannedClient(payload)
    assert tier.publish(client, "rA", stream)
    assert metrics.get("gateway_prefix_tier_publishes_total") == 1
    # rA sealed it: advisory warmth says rA is warm, probe skipped
    req = SimpleNamespace(prompt=stream)
    assert not tier.ensure_warm(req, "rA", client)
    assert metrics.get("gateway_prefix_tier_hits_total") == 0
    # rB is cold: probe hits, payload imports
    assert tier.ensure_warm(req, "rB", client)
    assert metrics.get("gateway_prefix_tier_hits_total") == 1
    assert metrics.get("gateway_prefix_tier_imports_total") == 1
    assert client.imports and client.imports[0][0] == "rB"
    # now rB is warm too: the same prompt skips the probe entirely
    assert not tier.ensure_warm(req, "rB", client)
    assert metrics.get("gateway_prefix_tier_hits_total") == 1
    # an unrelated prompt misses (counted)
    assert not tier.ensure_warm(
        SimpleNamespace(prompt=[40] * 12), "rB", client
    )
    assert metrics.get("gateway_prefix_tier_misses_total") == 1
    assert tier.degraded_log == []
    tier.close()


def test_tier_publish_is_deduped_and_metadata_first():
    """The second publish of the same chain (same gateway or a sibling)
    must not re-upload the payload: the gateway's published-set gates
    first, the store meta-GET second."""
    metrics = Metrics()
    backend = InProcessStoreBackend()
    stream = [9, 8, 7, 6, 5, 4, 3, 2, 1]
    payload = canned_payload(np.asarray(stream, np.int32), 4)
    client = _CannedClient(payload)
    gw1 = PrefixTier(backend=backend, page=4, metrics=metrics)
    gw2 = PrefixTier(backend=backend, page=4, metrics=metrics)
    assert gw1.publish(client, "rA", stream)
    # same gateway: gated by the published set, zero store traffic
    assert not gw1.publish(client, "rA", stream)
    # sibling gateway: meta-GET sees it stored, skips the upload
    assert not gw2.publish(client, "rB", stream)
    assert metrics.get("gateway_prefix_tier_publishes_total") == 1
    assert backend.stats()["prefixes"] == 1
    gw1.close()
    gw2.close()


def test_tier_async_publish_queue_flushes():
    metrics = Metrics()
    tier = PrefixTier(page=4, metrics=metrics)
    stream = [3, 1, 4, 1, 5, 9, 2, 6, 5]
    client = _CannedClient(canned_payload(np.asarray(stream, np.int32), 4))
    tier.publish_async(client, "rA", stream)
    assert tier.flush_publishes(10.0)
    assert metrics.get("gateway_prefix_tier_publishes_total") == 1
    # re-queueing the published stream is a no-op pre-gated off the
    # queue (chain already in the published set)
    tier.publish_async(client, "rA", stream)
    assert tier.flush_publishes(10.0)
    assert metrics.get("gateway_prefix_tier_publishes_total") == 1
    tier.close()


def test_tier_store_outage_degrades_counted_never_raises():
    """The degradation contract: with the store dead every probe and
    publish resolves as a COUNTED cold prefill — log and labeled metric
    agree, reasons are documented, nothing raises."""
    metrics = Metrics()
    # a port nothing listens on: connect refuses instantly
    dead = HttpStoreClient(
        "http://127.0.0.1:9", timeout_s=0.2, retries=0,
        breaker_threshold=2, breaker_cooldown_s=60.0,
    )
    tier = PrefixTier(backend=dead, page=4, metrics=metrics)
    stream = [1, 2, 3, 4, 5, 6, 7, 8, 9]
    client = _CannedClient(canned_payload(np.asarray(stream, np.int32), 4))
    req = SimpleNamespace(prompt=stream)
    assert not tier.ensure_warm(req, "rB", client)   # probe degrades
    assert not tier.publish(client, "rA", [11, 12, 13, 14, 15])
    assert len(tier.degraded_log) == 2
    ops = [op for op, _ in tier.degraded_log]
    assert ops == ["probe", "publish"]
    for op, reason in tier.degraded_log:
        assert reason in PREFIX_DEGRADE_REASONS
    counted = sum(
        metrics.get("gateway_prefix_tier_degraded_total", reason=r)
        for r in PREFIX_DEGRADE_REASONS
    )
    assert counted == len(tier.degraded_log)
    # no hit/miss accounting polluted by the outage
    assert metrics.get("gateway_prefix_tier_hits_total") == 0
    assert metrics.get("gateway_prefix_tier_misses_total") == 0
    tier.close()


def test_tier_warmth_lifecycle():
    tier = PrefixTier(page=4)
    keys = prompt_chain_keys([1, 2, 3, 4, 5, 6, 7, 8, 9], 4)
    tier.note_warm("rA", keys)
    assert tier.warm_pages("rA", keys) == 2
    assert tier.warm_pages("rA", keys[:1]) == 1
    scores = tier.locality_scores([1, 2, 3, 4, 5, 6, 7, 8, 9],
                                  ["rA", "rB"])
    assert scores == {"rA": 2, "rB": 0}
    tier.forget_replica("rA")
    assert tier.warm_pages("rA", keys) == 0
    tier.note_warm("rA", keys)
    tier.sync_live(["rB"])       # rA left the live set
    assert tier.warm_pages("rA", keys) == 0
    tier.close()


# ---------------------------------------------------------------------------
# 4. PrefixLocalityRouter
# ---------------------------------------------------------------------------

def _replicas(*keys):
    return [SimpleNamespace(key=k) for k in keys]


def test_locality_router_routes_warm_falls_back_cold():
    metrics = Metrics()
    tier = PrefixTier(page=4, metrics=metrics)
    router = PrefixLocalityRouter(tier, metrics=metrics)
    prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9]
    keys = prompt_chain_keys(prompt, 4)
    replicas = _replicas("rA", "rB", "rC")
    # nothing warm: the ring fallback answers (deterministically)
    req = SimpleNamespace(prompt=prompt, session=None, request_id="q1")
    cold_pick = router.pick(req, replicas, {})
    assert cold_pick is not None
    assert metrics.get("gateway_prefix_route_warm_total") == 0
    # warm rB: the router must route there regardless of the ring
    tier.note_warm("rB", keys)
    assert router.pick(req, replicas, {}).key == "rB"
    assert metrics.get("gateway_prefix_route_warm_total") == 1
    # equal warmth breaks by least outstanding
    tier.note_warm("rC", keys)
    assert router.pick(req, replicas, {"rB": 5, "rC": 1}).key == "rC"
    # excluded warm replicas are not candidates
    assert router.pick(
        req, replicas, {}, exclude=frozenset({"rB", "rC"})
    ).key == "rA"
    # forget drops warmth (and keeps the dispatcher's mispin duck-type)
    router.forget_replica("rB")
    router.forget_replica("rC")
    assert router.pick(req, replicas, {}).key == cold_pick.key
    assert hasattr(router, "forget_replica")
    tier.close()


# ---------------------------------------------------------------------------
# 5. real paged batchers: identity, longest-that-fits, /v1/state
# ---------------------------------------------------------------------------

POOLS = {
    "fp32": dict(decode_page_cache="fp32"),
    "int8": dict(kv_dtype="int8", decode_page_cache="quantized"),
    "bf16": dict(dtype=jnp.bfloat16, decode_page_cache="all"),
}


def _tier_identity(page, pool_kw, exact_cold=True):
    """Three lanes on the same pool config: tier-imported (replica A
    seals a scaffold, cold replica B imports it through the tier),
    locally-warm (replica A continues its own stream), and never-cached
    (cache-less prefill).  Tier-imported must ALWAYS equal locally-warm
    — the wire round-trip adds zero drift, whatever the pool dtype.
    Where page bytes are exact against recomputation (fp32 pools, int8
    pools whose requantization both lanes share), the never-cached lane
    must match too; bf16 pools carry decode-computed KV whose rounding
    legitimately differs from a fresh prefill's, so there the cache-less
    lane is a different numerical program (same reason the local
    multiturn identity suite runs fp32 serving only)."""
    params = trained_params()
    A = make_paged(params, page_size=page, **pool_kw)
    B = make_paged(params, page_size=page, **pool_kw)
    client = _BatcherClient({"A": A, "B": B})
    tier = PrefixTier(page=page, metrics=Metrics())
    rng = np.random.RandomState(11 + page)
    scaffold = np.array(
        rng.randint(0, CFG["vocab_size"], size=10), np.int32
    )
    out1 = A.run([scaffold], [10])[0]
    stream = list(scaffold) + list(out1)
    assert tier.publish(client, "A", stream)
    # the agent-turn prompt: the full sealed stream + a fresh delta
    prompt2 = np.asarray(
        stream + [int(x) for x in rng.randint(0, CFG["vocab_size"], 3)],
        np.int32,
    )
    req = SimpleNamespace(prompt=[int(t) for t in prompt2])
    assert tier.ensure_warm(req, "B", client), "tier import refused"
    got = B.run([prompt2], [6])[0]
    # locally-warm lane: A still holds its own sealed pages
    warm = A.run([prompt2], [6])[0]
    assert A.stats["prefix_hit_tokens"] > 0
    assert got == warm, (page, pool_kw, got, warm)
    if exact_cold:
        ref = make_paged(
            params, page_size=page, prefix_cache=False, **pool_kw
        )
        expected = ref.run([prompt2], [6])[0]
        assert got == expected, (page, pool_kw, got, expected)
    # admission on B actually hit the imported pages (decode kind
    # included — every pool here seals decode)
    assert B.stats["prefix_hit_tokens"] > 0
    assert B.stats["prefix_hit_tokens_decode"] > 0
    A.assert_page_accounting()
    B.assert_page_accounting()
    tier.close()
    return tier


def test_tier_import_token_identity_fp32_page4():
    _tier_identity(4, POOLS["fp32"])


@pytest.mark.slow
def test_tier_import_token_identity_matrix():
    for page in (4, 8):
        for name, kw in POOLS.items():
            _tier_identity(page, dict(kw), exact_cold=(name != "bf16"))


def test_tier_import_longest_that_fits_and_lru_holes():
    """A cramped importer takes the longest chain PREFIX that fits
    (never a mid-chain fragment), admission hits exactly that prefix,
    and tokens stay identical.  Then: an LRU hole punched into a warm
    cache re-imports through the tier and heals (import dedups present
    pages, fills the missing one)."""
    params = trained_params()
    A = make_paged(params)
    B = make_paged(params, slots=1, pool_pages=12)
    ref = make_paged(params, prefix_cache=False)
    client = _BatcherClient({"A": A, "B": B})
    tier = PrefixTier(page=4, metrics=Metrics())
    rng = np.random.RandomState(23)
    scaffold = np.array(rng.randint(0, CFG["vocab_size"], size=12),
                        np.int32)
    out1 = A.run([scaffold], [12])[0]
    stream = list(scaffold) + list(out1)
    assert tier.publish(client, "A", stream)
    n_chain = (len(stream) - 1) // 4
    # squeeze B's pool mid-import: hold all but 3 free pages so the
    # importer's budget is 3 of the 5-page chain (restored after)
    held = [B.free_pages.pop() for _ in range(len(B.free_pages) - 3)]
    req = SimpleNamespace(prompt=stream)
    assert tier.ensure_warm(req, "B", client)
    B.free_pages.update(held)
    imported = client.imports[-1][1]
    assert 0 < imported < n_chain, (
        f"expected a partial import, got {imported}/{n_chain}"
    )
    assert imported == 3
    # the imported pages are the chain's PREFIX: admission hits exactly
    # imported*page rows and recomputes the tail
    prompt2 = np.asarray(stream, np.int32)
    expected = ref.run([prompt2], [5])[0]
    got = B.run([prompt2], [5])[0]
    assert got == expected
    assert B.stats["prefix_hit_tokens"] == imported * 4
    B.assert_page_accounting()

    # -- LRU hole: evict one mid-chain page from a ROOMY warm cache ----
    C = make_paged(params, pool_pages=48)
    client2 = _BatcherClient({"A": A, "C": C})
    req2 = SimpleNamespace(prompt=stream)
    assert tier.ensure_warm(req2, "C", client2)
    full = client2.imports[-1][1]
    assert full == n_chain
    # punch the hole: pin every idle page except the second, evict it
    cache = C.prefix_cache
    keys = [k for k in cache._entries]
    hole_key = keys[1]
    pinned = [cache.acquire(k) for k in keys if k != hole_key]
    hole_page = cache.evict_lru()             # the hole
    assert hole_page is not None
    C.free_pages.add(hole_page)               # eviction frees the page
    for p in pinned:
        cache.release(p)
    # the tier still believes C warm — a replica lifecycle event resets
    # that (advisory map), after which the probe re-imports and heals
    tier.forget_replica("C")
    assert tier.ensure_warm(req2, "C", client2)
    healed = client2.imports[-1][1]
    assert healed == 1, "re-import must fill exactly the hole"
    got = C.run([prompt2], [5])[0]
    assert got == expected
    assert C.stats["prefix_hit_tokens"] == n_chain * 4
    C.assert_page_accounting()
    tier.close()


def test_v1_state_grows_prefix_cache_economy():
    """The warmth surface: /v1/state exposes cached chains, pages by
    kind, and hit/miss tokens split per prompt|decode kind."""
    from kubegpu_tpu.gateway.dataplane import ReplicaServingLoop

    params = trained_params()
    cb = make_paged(params)
    rng = np.random.RandomState(7)
    t1 = np.array(rng.randint(0, CFG["vocab_size"], size=9), np.int32)
    out1 = cb.run([t1], [8])[0]
    turn2 = np.concatenate([t1, np.asarray(out1, np.int32),
                            np.array([5, 6], np.int32)])
    cb.run([turn2], [4])
    econ = cb.prefix_cache_stats()
    assert econ["chains"] >= 1
    assert econ["pages"]["prompt"] > 0
    assert econ["pages"]["decode"] > 0
    assert econ["hit_tokens"]["prompt"] > 0
    assert econ["hit_tokens"]["decode"] > 0
    assert set(econ) == {"chains", "pages", "idle_pages", "hit_tokens",
                         "miss_tokens"}
    # ...and it rides the wire surface
    loop = ReplicaServingLoop(cb)
    state = loop.state()
    assert state["prefix_cache"] == econ
    # stats carries the new miss counter too (turn 1 was all misses)
    assert state["stats"]["prefix_miss_tokens"] >= 0
    cb.assert_page_accounting()


def test_prefix_cache_chain_count_with_divergence_and_holes():
    from kubegpu_tpu.models.paging import PrefixPageCache

    c = PrefixPageCache()
    c.insert(b"a", 1, kind="prompt", prev=None)
    c.insert(b"b", 2, kind="prompt", prev=b"a")
    assert c.chains() == 1
    c.insert(b"c", 3, kind="decode", prev=b"b")
    c.insert(b"d", 4, kind="decode", prev=b"b")     # divergent suffixes
    assert c.chains() == 2
    assert c.pages_by_kind() == {"prompt": 2, "decode": 2}
    # a hole splits the chain exactly as admission would see it
    for p in (1, 2, 3, 4):
        c.release(p)
    c.acquire(b"a")
    c.acquire(b"c")
    c.acquire(b"d")
    assert c.evict_lru() == 2                        # b evicts
    assert c.chains() == 3


# ---------------------------------------------------------------------------
# 6. the chaos lane: GatewaySoak(prefix_tier=True)
# ---------------------------------------------------------------------------

def test_gateway_soak_prefix_tier_inmemory():
    """The tier + locality router in the dispatch path over SimBatcher
    replicas (no sealed verbs: publishes no-op cleanly) under the kill
    schedule — I5 and zero degradations must hold."""
    from kubegpu_tpu.testing.soak import GatewaySoak

    GatewaySoak(
        seed=1601, n_replicas=3, gateways=2, prefix_tier=True,
    ).run(25)


@pytest.mark.slow
def test_gateway_soak_prefix_tier_paged_store_chaos():
    """The acceptance lane: paged replicas sealing real chains, the
    tier publishing/importing through a REAL external store that dies
    and revives mid-schedule, the locality router routing by warmth —
    kill/revive replicas throughout.  At quiescence: I5, page
    accounting on every surviving pool, and every tier failure counted
    as a degradation."""
    from kubegpu_tpu.testing.soak import GatewaySoak

    cfg = dict(vocab_size=64, num_layers=1, num_heads=2, hidden=16,
               max_seq=64)
    params = TransformerLM(dtype=jnp.float32, **cfg).init(
        jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32)
    )["params"]

    def factory(key):
        return PagedContinuousBatcher(
            params, dtype=jnp.float32, slots=4, prompt_pad=16,
            page_size=4, pool_pages=48, decode_page_cache="fp32", **cfg,
        )

    GatewaySoak(
        seed=1607, n_replicas=2, batcher_factory=factory,
        multiturn=True, follow_prompt_cap=16, store_chaos=True,
        prefix_tier=True, prefix_page=4,
    ).run(25)
