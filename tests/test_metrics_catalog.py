"""Metrics exposition conformance + the metric-name catalog lint
(ISSUE 6 satellites).

Three layers:

1. labeled histograms — the capability ``serve_ttft_seconds`` never
   had: per-label series with their own count/sum/quantiles, TYPE
   lines, and back-compatible unlabeled accessors;
2. exposition hardening — label-value escaping, stable ordering, and
   line-by-line parseability of ``render()``;
3. the catalog lint — every metric name emitted anywhere under
   ``kubegpu_tpu/`` must be declared in ``utils/metric_names.CATALOG``
   (and vice versa), so code, README and dashboards cannot drift apart
   silently.
"""

import re
from pathlib import Path

from kubegpu_tpu.utils.metric_names import CATALOG, assert_known
from kubegpu_tpu.utils.metrics import Metrics, escape_label_value

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "kubegpu_tpu"

# an emission is .inc( / .observe( / .set_gauge( / .timer( whose first
# argument is a STRING LITERAL (possibly on the next line); names built
# dynamically would defeat the lint and are banned by convention
_EMIT_RE = re.compile(
    r"\.(?:inc|observe|set_gauge|timer)\(\s*[\"']([a-z0-9_]+)[\"']",
    re.S,
)


def emitted_names():
    names = {}
    for path in sorted(PKG.rglob("*.py")):
        for m in _EMIT_RE.finditer(path.read_text()):
            names.setdefault(m.group(1), set()).add(
                str(path.relative_to(REPO))
            )
    return names


# ---------------------------------------------------------------------------
# 1. labeled histograms
# ---------------------------------------------------------------------------

def test_labeled_histograms_are_independent_series():
    m = Metrics()
    m.observe("serve_ttft_seconds", 0.5)
    m.observe("serve_ttft_seconds", 0.1, tenant="a")
    m.observe("serve_ttft_seconds", 0.3, tenant="a")
    m.observe("serve_ttft_seconds", 0.9, tenant="b")
    # exact-series accessors: labels select, absence selects unlabeled
    assert m.histogram_count("serve_ttft_seconds") == 1
    assert m.histogram_count("serve_ttft_seconds", tenant="a") == 2
    assert m.histogram_sum("serve_ttft_seconds", tenant="a") == 0.4
    assert m.quantile("serve_ttft_seconds", 0.5, tenant="b") == 0.9
    assert m.histogram_count("serve_ttft_seconds", tenant="zzz") == 0
    text = m.render()
    lines = text.splitlines()
    assert lines.count("# TYPE serve_ttft_seconds summary") == 1
    assert "serve_ttft_seconds_count 1" in lines
    assert 'serve_ttft_seconds_count{tenant="a"} 2' in lines
    assert 'serve_ttft_seconds_sum{tenant="b"} 0.9' in lines
    assert any(
        line.startswith('serve_ttft_seconds{tenant="a",quantile="0.5"}')
        for line in lines
    )
    # the TYPE line precedes every series of its family
    t = lines.index("# TYPE serve_ttft_seconds summary")
    assert t < lines.index("serve_ttft_seconds_count 1")
    assert t < lines.index('serve_ttft_seconds_count{tenant="a"} 2')


def test_labeled_timer_context_manager():
    m = Metrics()
    with m.timer("serve_phase_seconds", phase="queue"):
        pass
    assert m.histogram_count("serve_phase_seconds", phase="queue") == 1
    assert m.histogram_count("serve_phase_seconds") == 0


# ---------------------------------------------------------------------------
# 2. exposition conformance
# ---------------------------------------------------------------------------

def test_label_values_are_escaped():
    assert escape_label_value('a"b') == 'a\\"b'
    assert escape_label_value("a\\b") == "a\\\\b"
    assert escape_label_value("a\nb") == "a\\nb"
    m = Metrics()
    m.inc("gateway_requests_total", outcome='bad"quote')
    m.set_gauge("gateway_queue_depth", 1, note="back\\slash")
    m.observe("serve_ttft_seconds", 0.1, tenant="two\nlines")
    text = m.render()
    assert 'outcome="bad\\"quote"' in text
    assert 'note="back\\\\slash"' in text
    assert 'tenant="two\\nlines"' in text
    # nothing rendered a raw newline inside a line (the broken-exposition
    # failure mode this satellite hardens against)
    for line in text.splitlines():
        assert line.count('"') % 2 == 0 or "\\" in line


_LINE_RE = re.compile(
    r"^(?:# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (?:gauge|summary)"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(?:\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\""
    r"(?:,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\")*\})?"
    r" -?[0-9.e+-]+(?:[0-9]|\.0?)?)$"
)


def _fill(m: Metrics, order: int):
    ops = [
        lambda: m.inc("gateway_requests_total", outcome="ok"),
        lambda: m.inc("gateway_requests_total", outcome="rejected"),
        lambda: m.set_gauge("gateway_queue_depth", 3),
        lambda: m.set_gauge("gateway_live_replicas", 2),
        lambda: m.observe("serve_ttft_seconds", 0.25),
        lambda: m.observe("serve_phase_seconds", 0.1, phase="queue"),
        lambda: m.observe("serve_phase_seconds", 0.2, phase="prefill"),
    ]
    for op in (ops if order == 0 else list(reversed(ops))):
        op()


def test_render_is_stable_ordered_and_line_parseable():
    a, b = Metrics(), Metrics()
    _fill(a, 0)
    _fill(b, 1)                      # reversed insertion order
    assert a.render() == b.render()  # ordering is by name, not arrival
    assert a.render() == a.render()  # and idempotent
    text = a.render()
    assert text.endswith("\n")
    for line in text.splitlines():
        assert _LINE_RE.match(line), f"unparseable exposition line: {line!r}"


# ---------------------------------------------------------------------------
# 3. the catalog lint
# ---------------------------------------------------------------------------

def test_every_emitted_metric_name_is_in_the_catalog():
    missing = {
        name: sorted(files)
        for name, files in emitted_names().items()
        if name not in CATALOG
    }
    assert not missing, (
        "metric names emitted but missing from utils/metric_names."
        f"CATALOG (add type/labels/help): {missing}"
    )


def test_every_catalog_entry_is_emitted_somewhere():
    emitted = emitted_names()
    stale = sorted(n for n in CATALOG if n not in emitted)
    assert not stale, (
        "catalog entries no code emits (drift — delete or re-wire): "
        f"{stale}"
    )


def test_catalog_specs_are_well_formed():
    for name, spec in CATALOG.items():
        assert spec.type in ("counter", "gauge", "histogram"), name
        assert isinstance(spec.labels, tuple), name
        assert spec.help and spec.help == spec.help.strip(), name
        if spec.type == "counter":
            assert name.endswith("_total") or name.startswith(
                "serve_spec_"
            ), f"{name}: counters end in _total by convention"
    assert_known("serve_ttft_seconds")
    try:
        assert_known("totally_unknown_metric")
    except KeyError:
        pass
    else:
        raise AssertionError("assert_known accepted an unknown name")


def test_readme_observability_documents_every_serving_metric():
    """README's Observability section must name every serve_*/gateway_*
    metric: the catalog is the source of truth, the README is the copy
    operators read — keep them equal."""
    readme = (REPO / "README.md").read_text()
    missing = [
        n for n in CATALOG
        if (n.startswith("serve_") or n.startswith("gateway_"))
        and n not in readme
    ]
    assert not missing, f"README Observability section missing: {missing}"
