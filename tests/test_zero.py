"""ZeRO-1 optimizer-state sharding (parallel/zero.py).

Virtual 8-device CPU mesh from conftest; fp32 so the sharded-vs-replicated
loss parity is tight.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kubegpu_tpu.models import TransformerLM, create_train_state
from kubegpu_tpu.models.train import make_lm_train_step
from kubegpu_tpu.parallel import (
    device_mesh,
    make_zero1_lm_train_step,
    place_zero1_lm,
    state_bytes_per_device,
    zero1_state_shardings,
)
from kubegpu_tpu.parallel.sharding import batch_sharding, replicated

pytestmark = pytest.mark.slow  # JAX compile-heavy; run with -m slow

CFG = dict(vocab_size=64, num_layers=2, num_heads=4, hidden=32, max_seq=33)


def _state(rng, tokens):
    model = TransformerLM(dtype=jnp.float32, **CFG)
    # adam: the optimizer family ZeRO-1 exists for (two fp32 moments)
    return create_train_state(model, rng, tokens, tx=optax.adam(1e-3))


def test_zero1_moments_are_sharded_and_params_replicated():
    mesh = device_mesh({"data": 8})
    tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 33), 0, 64)
    state = _state(jax.random.PRNGKey(1), tokens)
    state, ptok, sh = place_zero1_lm(state, jnp.asarray(tokens), mesh)

    # params replicated: every leaf's sharding covers the whole mesh with
    # an empty spec
    for leaf in jax.tree.leaves(state.params):
        assert leaf.sharding.spec == jax.sharding.PartitionSpec(), leaf.sharding
    # moments: every leaf with a data-divisible axis is ACTUALLY sharded
    sharded = [
        leaf
        for leaf in jax.tree.leaves(state.opt_state)
        if hasattr(leaf, "sharding")
        and leaf.ndim > 0
        and any(d >= 8 and d % 8 == 0 for d in leaf.shape)
    ]
    assert sharded, "no shardable moment leaves found"
    for leaf in sharded:
        assert "data" in jax.tree_util.tree_leaves(tuple(leaf.sharding.spec)), (
            leaf.shape,
            leaf.sharding,
        )

    # measured memory delta: per-device moment bytes shrink ~8x (modulo
    # the scalar/indivisible leaves that stay replicated)
    p_b, o_b = state_bytes_per_device(state, sh)
    full_o = sum(
        l.nbytes for l in jax.tree.leaves(state.opt_state) if hasattr(l, "nbytes")
    )
    assert o_b < full_o / 4, (o_b, full_o)


def test_zero1_loss_matches_replicated_dp():
    """The ZeRO-1 layout is pure memory layout: the training trajectory
    must match plain replicated DP step for step."""
    mesh = device_mesh({"data": 8})
    tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 33), 0, 64)
    rng = jax.random.PRNGKey(1)

    z_state = _state(rng, tokens)
    z_state, z_tok, sh = place_zero1_lm(z_state, jnp.asarray(tokens), mesh)
    z_step = make_zero1_lm_train_step(mesh, sh, donate=False)

    r_state = _state(rng, tokens)
    r_state = jax.device_put(r_state, replicated(mesh))
    r_tok = jax.device_put(jnp.asarray(tokens), batch_sharding(mesh))
    r_step = make_lm_train_step(mesh, donate=False)

    for i in range(3):
        z_state, z_loss = z_step(z_state, z_tok)
        r_state, r_loss = r_step(r_state, r_tok)
        np.testing.assert_allclose(
            float(z_loss), float(r_loss), rtol=1e-5, err_msg=f"step {i}"
        )
    # the new moments kept their sharded layout through the step (the
    # out_shardings pin — without it XLA may silently re-replicate)
    for leaf in jax.tree.leaves(z_state.opt_state):
        if (
            hasattr(leaf, "sharding")
            and leaf.ndim > 0
            and any(d >= 8 and d % 8 == 0 for d in leaf.shape)
        ):
            assert "data" in jax.tree_util.tree_leaves(
                tuple(leaf.sharding.spec)
            ), leaf.sharding
