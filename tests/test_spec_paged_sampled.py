"""Sampled speculation on the paged hot path (ISSUE 20).

The contract under test: ``PagedContinuousBatcher(speculate_k=k,
sampling=True)`` runs ``rejection_sample_block`` INSIDE the compiled
verify step — the accept/resample decision stays device-resident, the
pipelined loop's one designated readback ships committed ids + accept
counts, and the greedy program stays byte-unchanged.

Layers:

1. fp32 token identity — the paged sampled-spec stream equals the DENSE
   sampled-spec batcher's (PR 19's reference) across page sizes and TP
   widths, with ``draft_window=max_seq`` and equal slots pinned (the
   paged draft ring then replays the dense draft schedule exactly);
2. the int8 draft ring — storage-dtype-polymorphic like the pool:
   deterministic replay, migration bit-identity through the whole-ring
   wire section, per-dtype accounting with a full-width-imposter
   negative (the PR 15 pool discipline applied to the ring);
3. mid-stream migration — a seed-pinned sampled-spec sequence exported
   mid-decode continues bit-identical on the importer;
4. the gateway regression ISSUE 20 exists to close — sampled+seeded
   traffic on a speculative paged replica KEEPS speculation and
   populates ``serve_spec_accept_rate{mode=sampled}`` (no silent
   sampled->unspeculated demotion), plus the GatewaySoak kill schedule
   over sampled speculative paged replicas holding page accounting;
5. compile stability — the sampled batcher mints exactly one entry per
   speculative program (the dense-phasing first-token program included)
   and greedy traffic on it never traces the sampled-only programs.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubegpu_tpu.models import TransformerLM
from kubegpu_tpu.models.paging import PagedContinuousBatcher
from kubegpu_tpu.models.spec_serving import SpeculativeContinuousBatcher
from kubegpu_tpu.parallel import device_mesh
from kubegpu_tpu.utils.metrics import Metrics

# vocab and heads divisible by the tested TP widths (lm_head is
# column-parallel over the vocab; the ring shards whole heads)
CFG = dict(vocab_size=64, num_layers=2, num_heads=4, hidden=32, max_seq=32)
DRAFT = dict(draft_num_layers=1, draft_num_heads=2, draft_hidden=16)

BUDGETS = [8, 6, 7, 5]
TEMPS = [0.9, 0.0, 1.2, 0.8]          # a greedy row rides along
SEEDS = [41, None, 42, 43]            # ...and an unpinned sampled row


@pytest.fixture(scope="module")
def params():
    model = TransformerLM(dtype=jnp.float32, **CFG)
    return model.init(
        jax.random.PRNGKey(0), jnp.ones((2, 8), jnp.int32)
    )["params"]


@pytest.fixture(scope="module")
def dparams():
    model = TransformerLM(
        vocab_size=CFG["vocab_size"], max_seq=CFG["max_seq"],
        num_layers=DRAFT["draft_num_layers"],
        num_heads=DRAFT["draft_num_heads"], hidden=DRAFT["draft_hidden"],
        dtype=jnp.float32,
    )
    return model.init(
        jax.random.PRNGKey(7), jnp.ones((2, 8), jnp.int32)
    )["params"]


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.RandomState(9)
    return [
        np.array(rng.randint(0, CFG["vocab_size"], size=n), np.int32)
        for n in (3, 5, 7, 4)
    ]


def make_sampled_paged(params, dparams, tp=1, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("prompt_pad", 16)
    kw.setdefault("page_size", 4)
    kw.setdefault("pool_pages", 44)
    # draft_window=max_seq: the ring never wraps, so its draft context
    # (and therefore the proposal schedule) matches the dense batcher's
    # row-for-row — the precondition for the ≡-dense identity lanes
    kw.setdefault("draft_window", CFG["max_seq"])
    mesh = None
    if tp > 1:
        if jax.device_count() < tp:
            pytest.skip(f"need {tp} devices, have {jax.device_count()}")
        mesh = device_mesh({"model": tp}, devices=jax.devices()[:tp])
    return PagedContinuousBatcher(
        params, draft_params=dparams, speculate_k=2, sampling=True,
        dtype=jnp.float32, mesh=mesh, **DRAFT, **CFG, **kw,
    )


def dense_ref(params, dparams, prompts):
    """The dense sampled-spec stream — PR 19's seed-pinned reference
    (equal slots, k, and draft geometry to the paged batchers here)."""
    return SpeculativeContinuousBatcher(
        params, dparams, k=2, slots=4, prompt_pad=16,
        dtype=jnp.float32, sampling=True, **DRAFT, **CFG,
    ).run(prompts, BUDGETS, temperatures=TEMPS, seeds=SEEDS)


def drive_until(cb, seq_id, n_tokens, max_steps=200):
    """Step until the sequence committed >= n_tokens (still live)."""
    for _ in range(max_steps):
        cb.serve_step()
        s = next((s for s in cb._seqs if s.seq_id == seq_id), None)
        if s is not None and s.active and len(s.tokens) >= n_tokens:
            return
    raise AssertionError(
        f"seq {seq_id} never reached {n_tokens} live tokens"
    )


def drain(cb):
    done = {}
    while cb.has_work():
        done.update(cb.serve_step())
    return done


# ---------------------------------------------------------------------------
# 1. fp32 token identity: paged sampled-spec ≡ dense sampled-spec
# ---------------------------------------------------------------------------

def test_paged_sampled_spec_matches_dense(params, dparams, prompts):
    """The core identity at page 4 / TP 1, plus replay determinism: a
    fresh engine given the same seeds emits byte-identical streams (the
    hedge/migration precondition)."""
    ref = dense_ref(params, dparams, prompts)
    m = Metrics()
    cb = make_sampled_paged(params, dparams, metrics=m)
    got = cb.run(prompts, BUDGETS, temperatures=TEMPS, seeds=SEEDS)
    assert got == ref, {
        i: (got[i], ref[i]) for i in ref if got[i] != ref[i]
    }
    cb.assert_page_accounting()
    assert cb.stats["spec_steps"] > 0
    # restart invariance (a fresh engine = another replica)
    again = make_sampled_paged(params, dparams).run(
        prompts, BUDGETS, temperatures=TEMPS, seeds=SEEDS
    )
    assert again == got
    # both verify modes fed the labeled accept histogram
    assert m.histogram_count("serve_spec_accept_rate", mode="sampled") > 0
    assert m.histogram_count("serve_spec_accept_rate", mode="greedy") > 0


@pytest.mark.slow
@pytest.mark.parametrize("page,tp", [(8, 1), (4, 2), (8, 2)])
def test_paged_sampled_spec_grid(params, dparams, prompts, page, tp):
    """The page-size x TP grid: head-sharded pools, the sharded draft
    ring, and the TP verify psums must not perturb the seed-pinned
    stream (fp32: identity is exact per numerics class)."""
    ref = dense_ref(params, dparams, prompts)
    cb = make_sampled_paged(params, dparams, tp=tp, page_size=page,
                            pool_pages=44 if page == 4 else 24)
    got = cb.run(prompts, BUDGETS, temperatures=TEMPS, seeds=SEEDS)
    assert got == ref, (page, tp, {
        i: (got[i], ref[i]) for i in ref if got[i] != ref[i]
    })
    cb.assert_page_accounting()


# ---------------------------------------------------------------------------
# 2. mid-stream migration: seed-pinned continuation bit-identical
# ---------------------------------------------------------------------------

def test_sampled_spec_migration_mid_stream(params, dparams, prompts):
    """Export a sampled-spec sequence mid-decode, import on a fresh
    engine: the continuation must be BIT-identical to the un-migrated
    stream — the seed pin plus the draft-ring wire section make the
    importer's windows replay the exporter's schedule exactly."""
    src = make_sampled_paged(params, dparams)
    ref = src.run(
        [prompts[0]], [BUDGETS[0]], temperatures=[0.9], seeds=[41]
    )[0]
    assert len(ref) == BUDGETS[0]
    src.submit(1, prompts[0], BUDGETS[0], temperature=0.9, seed=41)
    drive_until(src, 1, 3)
    payload = src.export_pages(1)
    assert payload["tokens"] == ref[: len(payload["tokens"])]
    # the sampled exporter ships its draft ring on the wire
    assert "draft" in payload
    src.cancel(1)
    src.assert_page_accounting()
    dst = make_sampled_paged(params, dparams)
    dst.import_pages(11, payload)
    dst.assert_page_accounting()
    out = drain(dst)
    assert out[11] == ref
    dst.assert_page_accounting()


def test_sampled_import_needs_sampling_engine(params, dparams, prompts):
    """Importing a sampled sequence into a greedy-only speculative
    engine still refuses crisply (guard #2 relaxed only for
    sampling=True targets)."""
    src = make_sampled_paged(params, dparams)
    src.submit(1, prompts[0], 6, temperature=0.9, seed=41)
    drive_until(src, 1, 2)
    payload = src.export_pages(1)
    greedy = PagedContinuousBatcher(
        params, draft_params=dparams, speculate_k=2, slots=4,
        prompt_pad=16, page_size=4, pool_pages=44,
        draft_window=CFG["max_seq"], dtype=jnp.float32, **DRAFT, **CFG,
    )
    with pytest.raises(ValueError, match="greedy-only"):
        greedy.import_pages(11, payload)


# ---------------------------------------------------------------------------
# 3. the int8 draft ring: replay determinism + migration bit-identity
# ---------------------------------------------------------------------------

def test_int8_ring_replay_deterministic(params, dparams, prompts):
    """The quantized ring shifts accept rates (quantized q), so the
    int8 lane's claims are REPLAY determinism and in-mode consistency,
    never ≡-dense identity."""
    kw = dict(kv_dtype="int8")
    a = make_sampled_paged(params, dparams, **kw).run(
        prompts, BUDGETS, temperatures=TEMPS, seeds=SEEDS
    )
    b = make_sampled_paged(params, dparams, **kw).run(
        prompts, BUDGETS, temperatures=TEMPS, seeds=SEEDS
    )
    assert a == b
    assert all(len(a[i]) == BUDGETS[i] for i in a)


@pytest.mark.slow
def test_int8_ring_migration_bit_identity(params, dparams, prompts):
    """int8 mid-stream migration: the importer rests the exporter's
    EXACT ring bytes (whole-lane rows + scales on the wire — the
    grow-and-rescale scale evolution depends on junk rows from rejected
    tails, so a re-quantized reconstruction would diverge), making the
    continuation bit-identical to the un-migrated int8 stream."""
    kw = dict(kv_dtype="int8")
    src = make_sampled_paged(params, dparams, **kw)
    ref = src.run(
        [prompts[2]], [BUDGETS[2]], temperatures=[1.2], seeds=[42]
    )[0]
    src.submit(1, prompts[2], BUDGETS[2], temperature=1.2, seed=42)
    drive_until(src, 1, 3)
    payload = src.export_pages(1)
    assert payload["tokens"] == ref[: len(payload["tokens"])]
    assert payload["draft"]["dtype"] == "int8"
    src.cancel(1)
    dst = make_sampled_paged(params, dparams, **kw)
    dst.import_pages(11, payload)
    out = drain(dst)
    assert out[11] == ref
    src.assert_page_accounting()
    dst.assert_page_accounting()


def test_accounting_catches_int8_ring_imposter(params, dparams):
    """The per-dtype bytes leg on the RING (the PR 15 pool negative,
    applied to the draft cache): a full-width allocation wearing the
    int8 label must fail accounting loudly, and so must a quantized
    pair smuggled into a declared-full-width ring."""
    cb = make_sampled_paged(params, dparams, kv_dtype="int8")
    cb.assert_page_accounting()
    (kd, ks), vent = cb.d_caches[0]
    cb.d_caches[0] = ((kd.astype(jnp.float32), ks), vent)
    with pytest.raises(AssertionError):
        cb.assert_page_accounting()
    cb.d_caches[0] = ((kd, ks), vent)
    cb.assert_page_accounting()
    # the full-width twin: a half-width imposter in a full-width ring
    full = make_sampled_paged(params, dparams)
    ck, cv = full.d_caches[0]
    full.d_caches[0] = (ck.astype(jnp.bfloat16), cv)
    with pytest.raises(AssertionError):
        full.assert_page_accounting()


def test_draft_ring_bytes_gauge(params, dparams):
    """serve_draft_ring_bytes reports the resting ring economy by
    storage dtype: the int8 ring rests one byte per element plus f32
    scales; the full-width ring one series at the compute dtype."""
    m8 = Metrics()
    make_sampled_paged(params, dparams, kv_dtype="int8", metrics=m8)
    d_hd = DRAFT["draft_hidden"] // DRAFT["draft_num_heads"]
    elems = (
        2 * DRAFT["draft_num_layers"] * 4 * CFG["max_seq"]
        * DRAFT["draft_num_heads"] * d_hd
    )
    assert m8.gauge("serve_draft_ring_bytes", dtype="int8") == elems
    assert m8.gauge("serve_draft_ring_bytes", dtype="float32") == (
        2 * DRAFT["draft_num_layers"] * 4 * DRAFT["draft_num_heads"] * 4
    )
    mf = Metrics()
    make_sampled_paged(params, dparams, metrics=mf)
    assert mf.gauge("serve_draft_ring_bytes", dtype="float32") == elems * 4


# ---------------------------------------------------------------------------
# 4. the gateway regression: sampled traffic KEEPS speculation
# ---------------------------------------------------------------------------

def test_sampled_paged_reports_sampled_spec_iterations(
    params, dparams, prompts
):
    """The regression ISSUE 20 closes: a speculative paged replica
    given sampled+seeded traffic (the worker's --sample-temperature
    --sample-seed flags construct exactly this batcher) must KEEP
    speculation — sampled-spec verify iterations run and
    serve_spec_accept_rate{mode=sampled} populates — where it
    previously refused at submit and the gateway demoted the request
    to unspeculated decode."""
    m = Metrics()
    cb = make_sampled_paged(params, dparams, metrics=m)
    out = cb.run(
        prompts[:2], BUDGETS[:2], temperatures=[0.9, 0.8], seeds=[10, 11]
    )
    assert all(len(out[i]) == BUDGETS[i] for i in out)
    assert cb.stats["spec_steps"] > 0
    assert m.histogram_count("serve_spec_accept_rate", mode="sampled") > 0
    assert 0.0 <= m.histogram_sum(
        "serve_spec_accept_rate", mode="sampled"
    ) <= m.histogram_count("serve_spec_accept_rate", mode="sampled")
    # the greedy-only construction still refuses crisply (guard #1
    # survives for engines built WITHOUT sampling=True)
    greedy = PagedContinuousBatcher(
        params, draft_params=dparams, speculate_k=2, slots=4,
        prompt_pad=16, page_size=4, pool_pages=44, dtype=jnp.float32,
        **DRAFT, **CFG,
    )
    with pytest.raises(ValueError, match="greedy-only"):
        greedy.submit(0, prompts[0], 4, temperature=0.7)


@pytest.mark.slow
def test_gateway_soak_sampled_paged_kill_schedule(params):
    """GatewaySoak's kill/revive/hedge schedule with EVERY request
    sampled+seed-pinned over sampled speculative paged replicas:
    invariant I5 (served exactly once or explicitly rejected) plus
    page accounting at quiescence on every surviving replica —
    rejected/resampled windows must never leak pool pages."""
    from kubegpu_tpu.testing.soak import GatewaySoak

    tiny = dict(vocab_size=61, num_layers=1, num_heads=2, hidden=16,
                max_seq=24)
    tparams = TransformerLM(dtype=jnp.float32, **tiny).init(
        jax.random.PRNGKey(1), jnp.ones((1, 4), jnp.int32)
    )["params"]
    soak = GatewaySoak(
        seed=23, n_replicas=2, follow_prompt_cap=4, sampled=True,
        batcher_factory=lambda key: PagedContinuousBatcher(
            tparams, slots=4, prompt_pad=4, page_size=4, pool_pages=24,
            station_slots=2, token_budget=8, dtype=jnp.float32,
            draft_params=tparams, speculate_k=2, sampling=True,
            draft_num_layers=tiny["num_layers"],
            draft_num_heads=tiny["num_heads"],
            draft_hidden=tiny["hidden"], **tiny,
        ),
    )
    soak.run(steps=18)


# ---------------------------------------------------------------------------
# 5. compile stability: one entry per program, greedy path untouched
# ---------------------------------------------------------------------------

def test_sampled_compile_stability(params, dparams, prompts):
    """Mixed greedy/sampled churn through the sampled batcher leaves
    exactly ONE compiled entry per speculative program — the
    dense-phasing first-token program included — and never traces the
    plain step; greedy-only traffic on the SAME engine never traces
    the first-token program at all (the sampled machinery costs greedy
    traffic nothing)."""
    cb = make_sampled_paged(params, dparams)
    greedy_only = cb.run(prompts[:2], BUDGETS[:2])    # greedy traffic
    assert cb._spec_first._cache_size() == 0, (
        "greedy traffic traced the sampled first-token program"
    )
    cb.run(prompts, BUDGETS, temperatures=TEMPS, seeds=SEEDS)
    cb.run(prompts, BUDGETS, temperatures=[0.5] * 4, seeds=[9] * 4)
    for name in ("_spec_draft", "_spec_verify", "_draft_admit",
                 "_spec_first"):
        assert getattr(cb, name)._cache_size() == 1, (
            f"{name}: {getattr(cb, name)._cache_size()} compiled entries"
        )
    assert cb._step._cache_size() == 0, "plain step traced under spec"
    cb.assert_page_accounting()
    # ...and the greedy rows the mixed runs emitted match the pure
    # greedy-only engine's (the greedy program is byte-unchanged)
    pure = PagedContinuousBatcher(
        params, draft_params=dparams, speculate_k=2, slots=4,
        prompt_pad=16, page_size=4, pool_pages=44,
        draft_window=CFG["max_seq"], dtype=jnp.float32, **DRAFT, **CFG,
    ).run(prompts[:2], BUDGETS[:2])
    assert pure == greedy_only
