"""Token-budget batched multi-admission prefill: the widened station.

The paged batcher's prefill station grew from a serial b=1 pipe to
``station_slots`` concurrent admissions packed under a ``token_budget``
per serving iteration.  The widening must be INVISIBLE in the output
(greedy-token-identical to the serial station, to monolithic prefill,
and to the per-sequence oracle, across slot counts, chunk/page
boundaries, budgets, and prefix-cache hits), strictly FIFO in admission
order, page-balanced under the soak's kill schedule with the station
half-full, and compile-stable (occupancy patterns and budget remainders
never mint new programs)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubegpu_tpu.models import TransformerLM, greedy_generate
from kubegpu_tpu.models.paging import PagedContinuousBatcher
from kubegpu_tpu.models.serving import ContinuousBatcher

pytestmark = pytest.mark.slow

CFG = dict(vocab_size=61, num_layers=2, num_heads=4, hidden=32, max_seq=32)


def trained_params():
    model = TransformerLM(dtype=jnp.float32, **CFG)
    return model.init(jax.random.PRNGKey(0), jnp.ones((2, 8), jnp.int32))[
        "params"
    ]


def oracle(params, prompt, n):
    out = greedy_generate(
        params, jnp.asarray(prompt)[None, :], n, dtype=jnp.float32, **CFG
    )
    return list(np.asarray(out)[0, len(prompt):])


def make_paged(params, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("prompt_pad", 20)
    kw.setdefault("page_size", 4)
    kw.setdefault("pool_pages", 40)
    return PagedContinuousBatcher(params, dtype=jnp.float32, **CFG, **kw)


# ---------------------------------------------------------------------------
# Property: batched station ≡ serial station ≡ monolithic, across the grid
# ---------------------------------------------------------------------------

def test_batched_station_token_identical_across_slot_counts():
    """Greedy, fixed seed: prompt lengths straddling every page boundary
    (page=4: 3/4/5, 7/8/9, 12/13) plus a DUPLICATE prompt (an in-burst
    prefix-cache hit) must emit exactly the per-sequence oracle's tokens
    — which is also what the serial station (station_slots=1) and the
    dense monolithic batcher emit — for 1, 2, and 4 station slots, with
    and without a token budget, and for multi-page prefill_chunk."""
    params = trained_params()
    rng = np.random.RandomState(0)
    lengths = (1, 3, 4, 5, 7, 8, 9, 12, 13)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=n), np.int32)
        for n in lengths
    ]
    prompts.append(prompts[6].copy())  # duplicate: hits pages mid-burst
    budgets = [5, 4, 6, 3, 5, 4, 6, 5, 4, 5]
    expected = {
        i: oracle(params, p, n)
        for i, (p, n) in enumerate(zip(prompts, budgets))
    }
    mono = ContinuousBatcher(
        params, slots=4, prompt_pad=20, prefill_chunk=None,
        dtype=jnp.float32, **CFG,
    ).run(prompts, budgets)
    assert mono == expected
    serial = make_paged(params, station_slots=1)
    got_serial = serial.run(prompts, budgets)
    assert got_serial == expected
    serial.assert_page_accounting()
    for kw in (
        dict(station_slots=2),
        dict(station_slots=4),
        dict(station_slots=4, token_budget=9),
        dict(station_slots=3, prefill_chunk=8),
    ):
        cb = make_paged(params, **kw)
        got = cb.run(prompts, budgets)
        assert got == expected, (kw, {
            i: (got[i], expected[i])
            for i in expected if got[i] != expected[i]
        })
        cb.assert_page_accounting()
        # work is conserved: batching changes packing, not chunk count
        assert cb.stats["prefill_chunks"] == serial.stats["prefill_chunks"]
        # the duplicate prompt hit its twin's registered pages
        assert cb.stats["prefix_hit_tokens"] >= 8, kw


def test_batched_station_overlaps_admissions():
    """The perf contract behind the identity property: with N station
    slots, N concurrent long admits reach activation in far fewer
    serving iterations than the serial pipe (which pays N× sequential
    prefill) — each iteration advances every in-flight admission."""
    params = trained_params()
    rng = np.random.RandomState(2)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=17), np.int32)
        for _ in range(4)
    ]

    def iterations_to_drain(station_slots):
        cb = make_paged(params, station_slots=station_slots)
        for i, p in enumerate(prompts):
            cb.submit(i, p, 2)
        steps = 0
        while cb.has_work():
            cb.serve_step()
            steps += 1
            assert steps < 200
        return steps

    serial, batched = iterations_to_drain(1), iterations_to_drain(4)
    # 17-token prompts are 4 chunks each: the serial pipe pays ~4x4
    # chunk iterations end to end, the batched station ~4 — anything
    # under half proves the admissions overlapped
    assert batched * 2 <= serial, (batched, serial)


def test_fully_cached_prefix_admits_alongside_inflight_twin():
    """A prefix the cache already resolves in FULL must never defer
    behind an in-flight admission that merely shares its first-page
    key: nothing would be recomputed, so serializing them is a pure
    FIFO head-of-line stall (the defer is only for prefixes whose
    first MISSED page is mid-prefill).  18-token prompts: 4 sharable
    pages all cached, one private tail row still to chunk — so the
    first twin's job is genuinely in flight when the second admits."""
    params = trained_params()
    rng = np.random.RandomState(5)
    prompt = np.array(rng.randint(0, CFG["vocab_size"], size=18), np.int32)
    cb = make_paged(params, station_slots=4)
    cb.submit(0, prompt, 2)  # seed the cache, then retire
    warm = {}
    while cb.has_work():
        warm.update(cb.serve_step())
    order = _spy_admission_order(cb)
    cb.submit(1, prompt, 2)
    cb.submit(2, prompt, 2)
    cb.serve_step()
    # one sweep admits BOTH twins: every sharable page of seq 2 was a
    # cache hit, so it must not wait for seq 1's job to activate
    assert order == [1, 2], order
    out = dict(warm)
    while cb.has_work():
        out.update(cb.serve_step())
    exp = oracle(params, prompt, 2)
    assert out == {0: exp, 1: exp, 2: exp}
    cb.assert_page_accounting()


# ---------------------------------------------------------------------------
# Fairness: admission is strictly FIFO under a full station
# ---------------------------------------------------------------------------

def _spy_admission_order(cb):
    order = []
    orig = cb._try_begin_admit

    def spy(slot, seq_id, *a, **kw):
        ok = orig(slot, seq_id, *a, **kw)
        if ok:
            order.append(seq_id)
        return ok

    cb._try_begin_admit = spy
    return order


def test_admission_fifo_under_full_station():
    """Six multi-chunk prompts through a 2-slot station: admissions
    begin in exact submit order — a full station defers the queue, it
    never re-orders it."""
    params = trained_params()
    rng = np.random.RandomState(3)
    cb = make_paged(params, station_slots=2)
    order = _spy_admission_order(cb)
    for i in range(6):
        cb.submit(
            i,
            np.array(rng.randint(0, CFG["vocab_size"], size=10), np.int32),
            3, session_id=f"tenant-{i % 3}",
        )
    while cb.has_work():
        cb.serve_step()
    assert order == list(range(6)), order
    cb.assert_page_accounting()


def test_admission_fifo_head_of_line_on_pool_pressure():
    """A head deferred on pool pressure holds the line: a smaller
    request behind it that WOULD fit must not jump the queue."""
    params = trained_params()
    rng = np.random.RandomState(4)
    # 9 allocatable pages (page=4): a long-running seq holds 5
    # (8 prompt + 12 new = 20 rows), leaving 4
    cb = make_paged(params, slots=3, pool_pages=10)
    runner = np.array(rng.randint(0, CFG["vocab_size"], size=8), np.int32)
    cb.submit(0, runner, 12)
    while not cb._seqs[0].active:
        cb.serve_step()
    order = _spy_admission_order(cb)
    big = np.array(rng.randint(0, CFG["vocab_size"], size=16), np.int32)
    small = np.array(rng.randint(0, CFG["vocab_size"], size=4), np.int32)
    cb.submit(1, big, 4)    # needs 5 pages: defers behind the runner
    cb.submit(2, small, 4)  # needs 2: would fit NOW, must wait its turn
    for _ in range(3):
        cb.serve_step()
        assert order == [], "queue jumped the deferred head"
    done = {}
    while cb.has_work():
        done.update(cb.serve_step())
    assert order == [1, 2]
    assert done[1] == oracle(params, big, 4)
    assert done[2] == oracle(params, small, 4)
    cb.assert_page_accounting()


# ---------------------------------------------------------------------------
# Soak: kill schedule with the station half-full
# ---------------------------------------------------------------------------

def test_gateway_soak_kill_schedule_station_half_full():
    """The GatewaySoak kill/revive/hedge schedule over paged batchers
    whose stations run multi-admission (station_slots=2 of slots=4, so
    bursts keep the station partially occupied at kill time): invariant
    I5 plus assert_page_accounting on every surviving replica."""
    from kubegpu_tpu.testing.soak import GatewaySoak

    tiny = dict(vocab_size=61, num_layers=1, num_heads=2, hidden=16,
                max_seq=16)
    params = TransformerLM(dtype=jnp.float32, **tiny).init(
        jax.random.PRNGKey(1), jnp.ones((1, 4), jnp.int32)
    )["params"]
    soak = GatewaySoak(
        # workload prompts must fit the replicas' prompt_pad below
        seed=13, n_replicas=2, follow_prompt_cap=4,
        batcher_factory=lambda key: PagedContinuousBatcher(
            params, slots=4, prompt_pad=4, page_size=4, pool_pages=20,
            station_slots=2, token_budget=8, dtype=jnp.float32, **tiny,
        ),
    )
    soak.run(steps=18)


# ---------------------------------------------------------------------------
# Compile stability: occupancy and budget remainders never recompile
# ---------------------------------------------------------------------------

def test_compile_stability_fixed_jit_cache():
    """A varied admission schedule — mixed lengths across page
    boundaries, cache hits, cancels mid-prefill, zero-budget admits,
    partial station occupancy, odd token-budget remainders — must leave
    exactly ONE compiled entry per program: the packer's shapes are
    static (station_slots × page rows, masked), so no schedule can
    trigger a recompile storm."""
    params = trained_params()
    rng = np.random.RandomState(5)
    cb = make_paged(params, station_slots=3, token_budget=11,
                    prefill_chunk=8)
    seq = 0
    live = []
    for step in range(40):
        roll = rng.rand()
        if roll < 0.5:
            n = int(rng.randint(1, 14))
            max_new = int(rng.randint(0, 5))  # zero-budget admits too
            prompt = (
                np.arange(n, dtype=np.int32) % 7 if roll < 0.1
                else np.array(
                    rng.randint(0, CFG["vocab_size"], size=n), np.int32
                )
            )  # the arange prompts repeat -> prefix-cache hits
            cb.submit(seq, prompt, max_new)
            live.append(seq)
            seq += 1
        elif roll < 0.6 and live:
            cb.cancel(live.pop(rng.randint(len(live))))
        else:
            for s in cb.serve_step():
                live.remove(s)
    while cb.has_work():
        for s in cb.serve_step():
            live.remove(s)
    cb.assert_page_accounting()
    for name in ("_chunk", "_step"):
        assert getattr(cb, name)._cache_size() == 1, (
            f"{name}: {getattr(cb, name)._cache_size()} compiled entries"
        )
    # bucketed multi-page programs: one compiled entry per padded width
    assert cb._write_pages, "no multi-page scatter ran"
    for w, fn in cb._write_pages.items():
        assert fn._cache_size() == 1, f"scatter width {w} recompiled"
    for w, fn in cb._gather_pages.items():
        assert fn._cache_size() == 1, f"gather width {w} recompiled"


# ---------------------------------------------------------------------------
# Dense batcher: token budget bounds chunk work per step, output-invisible
# ---------------------------------------------------------------------------

def test_dense_token_budget_identical_and_bounded():
    params = trained_params()
    rng = np.random.RandomState(6)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=n), np.int32)
        for n in (9, 11, 13)
    ]
    budgets = [4, 3, 4]
    expected = {
        i: oracle(params, p, n)
        for i, (p, n) in enumerate(zip(prompts, budgets))
    }
    cb = ContinuousBatcher(
        params, slots=3, prompt_pad=16, prefill_chunk=4, token_budget=6,
        dtype=jnp.float32, **CFG,
    )
    for i, (p, n) in enumerate(zip(prompts, budgets)):
        cb.submit(i, p, n)
    # all three slots are prefilling, but budget 6 with chunk 4 allows
    # exactly ONE chunk per iteration — earliest admission first
    cb.serve_step()
    assert cb.stats["prefill_chunks"] == 1
    assert cb._slots[0].prefill_pos == 4
    assert cb._slots[1].prefill_pos == 0
    got = dict()
    while cb.has_work():
        got.update(cb.serve_step())
    assert got == expected
    with pytest.raises(ValueError, match="token_budget"):
        ContinuousBatcher(
            params, slots=1, prompt_pad=16, prefill_chunk=None,
            token_budget=8, dtype=jnp.float32, **CFG,
        )
