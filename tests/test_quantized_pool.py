"""Quantized KV page pool (ISSUE 15): int8 pages + per-page per-head
scales through the whole paged serving hot loop.

The contract under test: with ``kv_dtype="int8"`` the pool stores int8
pages and (P, h) float32 scales — the paged kernels dequantize
IN-KERNEL (property-tested against a dequantize-then-reference oracle),
station scatters quantize whole pages at their tight scale, decode
commits go through grow-and-rescale row writes, and sealing
REQUANTIZES pages to their tight scale before they enter the shared
chain.  Streams are deterministic in-mode (same traffic ⇒ identical
tokens), page accounting grows a per-dtype BYTES leg (a full-width
allocation wearing an int8 label must fail loudly), and the migration
verbs carry dtype + scales with an atomic refusal on mismatch.  The
full-width paths stay bit-untouched — the fp32 identity oracles
elsewhere in tier-1 keep their teeth, and this file pins the fp32 lane
against the dense serial oracle too.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubegpu_tpu.models import TransformerLM
from kubegpu_tpu.models.paging import PagedContinuousBatcher
from kubegpu_tpu.models.serving import (
    ContinuousBatcher,
    DECODE_PAGE_CACHE_POLICIES,
    KV_DTYPES,
    resolve_decode_page_cache,
    resolve_kv_dtype,
)
from kubegpu_tpu.ops.paged_attention import (
    dequantize_pages,
    paged_chunk_attention,
    paged_decode_attention,
    quantize_pages,
    reference_paged_attention,
    reference_paged_chunk_attention,
)
from kubegpu_tpu.utils.metrics import Metrics

CFG = dict(vocab_size=61, num_layers=2, num_heads=4, hidden=32, max_seq=64)


def trained_params():
    model = TransformerLM(dtype=jnp.float32, **CFG)
    return model.init(jax.random.PRNGKey(0), jnp.ones((2, 8), jnp.int32))[
        "params"
    ]


def make_paged(params, **kw):
    kw.setdefault("slots", 3)
    kw.setdefault("prompt_pad", 24)
    kw.setdefault("page_size", 8)
    kw.setdefault("pool_pages", 40)
    kw.setdefault("dtype", jnp.float32)
    return PagedContinuousBatcher(params, **CFG, **kw)


def spec_kw(params, k=2, **kw):
    return dict(
        draft_params=params, speculate_k=k,
        draft_num_layers=CFG["num_layers"],
        draft_num_heads=CFG["num_heads"], draft_hidden=CFG["hidden"],
        **kw,
    )


# ---------------------------------------------------------------------------
# Contract resolution (fast — tier-1)
# ---------------------------------------------------------------------------

def test_kv_dtype_contract_resolution():
    assert not resolve_kv_dtype(None, jnp.bfloat16)
    assert not resolve_kv_dtype("bf16", jnp.bfloat16)
    assert not resolve_kv_dtype("fp32", jnp.float32)
    assert resolve_kv_dtype("int8", jnp.bfloat16)
    assert resolve_kv_dtype("int8", jnp.float32)
    with pytest.raises(ValueError):
        resolve_kv_dtype("fp16", jnp.float32)       # unknown format
    with pytest.raises(ValueError):
        resolve_kv_dtype("bf16", jnp.float32)       # contradicts dtype
    with pytest.raises(ValueError):
        resolve_kv_dtype("fp32", jnp.bfloat16)


def test_decode_page_cache_quantized_policy():
    # "quantized" seals only on a quantized pool; "fp32" names the
    # FULL-WIDTH float32 trust class, so a quantized pool demotes it
    assert resolve_decode_page_cache("quantized", jnp.float32, True)
    assert resolve_decode_page_cache("quantized", jnp.bfloat16, True)
    assert not resolve_decode_page_cache("quantized", jnp.float32, False)
    assert not resolve_decode_page_cache("fp32", jnp.float32, True)
    assert resolve_decode_page_cache("fp32", jnp.float32, False)
    assert resolve_decode_page_cache("all", jnp.bfloat16, True)
    assert not resolve_decode_page_cache("off", jnp.float32, True)


def test_gateway_mirrors_pin_the_contract_tuples():
    from kubegpu_tpu.gateway import client

    assert client.DECODE_PAGE_CACHE_POLICIES == DECODE_PAGE_CACHE_POLICIES
    assert client.KV_DTYPES == KV_DTYPES


# ---------------------------------------------------------------------------
# Kernels: in-kernel dequant vs the dequantize-then-reference oracle
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("page,hd", [(4, 8), (8, 16)])
def test_quantized_kernels_match_dequantize_oracle(page, hd):
    rs = np.random.RandomState(3)
    P, h, b, npg = 12, 4, 3, 3
    kf = jnp.asarray(rs.randn(P, h, page, hd).astype(np.float32))
    vf = jnp.asarray(rs.randn(P, h, page, hd).astype(np.float32))
    kd, ks = quantize_pages(kf)
    vd, vs = quantize_pages(vf)
    assert kd.dtype == jnp.int8 and ks.dtype == jnp.float32
    tbl = jnp.stack([
        jnp.asarray(
            rs.choice(np.arange(1, P), size=npg, replace=False)
        ).astype(jnp.int32)
        for _ in range(b)
    ])
    ln = jnp.asarray(
        rs.randint(1, npg * page, size=b).astype(np.int32)
    )
    q = jnp.asarray(rs.randn(b, h, hd).astype(np.float32))
    out = paged_decode_attention(q, kd, vd, tbl, ln, k_scale=ks, v_scale=vs)
    ref = reference_paged_attention(
        q, dequantize_pages(kd, ks), dequantize_pages(vd, vs), tbl, ln
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # the multi-query (speculative verify) twin, per ROW
    L = 3
    qc = jnp.asarray(rs.randn(b, L, h, hd).astype(np.float32))
    ln_c = jnp.asarray(
        rs.randint(1, npg * page - L, size=b).astype(np.int32)
    )
    outc = paged_chunk_attention(
        qc, kd, vd, tbl, ln_c, k_scale=ks, v_scale=vs
    )
    refc = reference_paged_chunk_attention(
        qc, dequantize_pages(kd, ks), dequantize_pages(vd, vs), tbl, ln_c
    )
    np.testing.assert_allclose(np.asarray(outc), np.asarray(refc),
                               rtol=1e-5, atol=1e-5)


def test_quantize_pages_roundtrip_properties():
    rs = np.random.RandomState(7)
    pages = jnp.asarray(rs.randn(6, 3, 4, 8).astype(np.float32)) * 3.0
    data, scale = quantize_pages(pages)
    deq = dequantize_pages(data, scale)
    # error bounded by half a quantization step per element
    err = np.abs(np.asarray(deq) - np.asarray(pages))
    bound = np.asarray(scale)[:, :, None, None] * 0.5 + 1e-7
    assert (err <= bound).all()
    # tight: every (page, head) block with content reaches full range
    mx = np.abs(np.asarray(data)).max(axis=(2, 3))
    assert ((mx == 127) | (np.asarray(scale) == 0.0)).all()
    # all-zero block quantizes to exact zeros at scale 0
    zd, zs = quantize_pages(jnp.zeros((2, 3, 4, 8)))
    assert not np.asarray(zd).any() and not np.asarray(zs).any()


# ---------------------------------------------------------------------------
# The int8 pool end to end: determinism, agreement, accounting
# ---------------------------------------------------------------------------

def _traffic(rs, n=5, lo=4, hi=20):
    return [
        rs.randint(0, CFG["vocab_size"], size=rs.randint(lo, hi))
        .astype(np.int32)
        for _ in range(n)
    ]


def test_int8_pool_deterministic_and_agrees_with_fullwidth():
    params = trained_params()
    rs = np.random.RandomState(0)
    prompts = _traffic(rs)
    budgets = [9, 12, 5, 8, 11]
    full = make_paged(params)
    q1 = make_paged(params, kv_dtype="int8")
    q2 = make_paged(params, kv_dtype="int8")
    out_f = full.run([p.copy() for p in prompts], budgets)
    out_1 = q1.run([p.copy() for p in prompts], budgets)
    out_2 = q2.run([p.copy() for p in prompts], budgets)
    assert out_1 == out_2, "int8 streams must be deterministic in-mode"
    for cb in (full, q1, q2):
        cb.assert_page_accounting()   # incl. the per-dtype bytes leg
    assert q1.kv_dtype == "int8" and full.kv_dtype == "float32"
    # lengths match request-for-request; agreement is MEASURED (the
    # quantized numerics class), and on this trained tiny config it is
    # high — a collapse would mean a real plumbing bug, not rounding
    total = agree = 0
    for i in out_f:
        assert len(out_1[i]) == len(out_f[i])
        total += len(out_f[i])
        agree += sum(a == b for a, b in zip(out_f[i], out_1[i]))
    assert agree / total > 0.5, f"agreement collapsed: {agree}/{total}"


def test_fp32_fullwidth_lane_token_identical_to_dense_oracle():
    # the machinery must not perturb today's full-width path
    params = trained_params()
    rs = np.random.RandomState(1)
    prompts = _traffic(rs, n=4)
    budgets = [7, 10, 6, 9]
    paged = make_paged(params)
    dense = ContinuousBatcher(
        params, slots=3, prompt_pad=24, dtype=jnp.float32, **CFG
    )
    assert (
        paged.run([p.copy() for p in prompts], budgets)
        == dense.run([p.copy() for p in prompts], budgets)
    )


@pytest.mark.parametrize("page_size,spec", [(4, False), (8, True)])
def test_int8_agreement_property_multiturn_spec_churn(page_size, spec):
    """Page sizes x speculation x multi-turn sealing x cancel/LRU
    churn: the int8 pool holds accounting (bytes leg included) at every
    quiescent point, multi-turn turn-2 prompts HIT through sealed
    decode pages, and the whole schedule replayed on a fresh batcher is
    token-identical (in-mode determinism under churn)."""
    params = trained_params()
    kw = dict(
        kv_dtype="int8", decode_page_cache="quantized",
        page_size=page_size, pool_pages=46, station_slots=2,
    )
    if spec:
        kw.update(spec_kw(params, k=2, draft_window=32))

    def run_schedule():
        cb = make_paged(params, **kw)
        rs = np.random.RandomState(13)
        outs = {}
        # turn 1s
        p0 = rs.randint(0, CFG["vocab_size"], size=11).astype(np.int32)
        outs.update(cb.run([p0], [8]))
        # turn 2 extends turn 1's stream through the sealed region
        stream = [int(t) for t in p0] + outs[0]
        p2 = np.asarray(stream + [3], np.int32)
        cb.submit(10, p2, 6)
        # churn: enough traffic to force LRU eviction, plus a cancel
        extra = _traffic(rs, n=6, lo=4, hi=16)
        for j, p in enumerate(extra):
            cb.submit(20 + j, p, 7)
        cb.submit(99, extra[0].copy(), 9)
        stepped = 0
        while cb.has_work():
            outs.update(cb.serve_step())
            stepped += 1
            if stepped == 4:
                cb.cancel(99)
            if stepped % 7 == 0:
                cb.assert_page_accounting()
        cb.assert_page_accounting()
        return outs, dict(cb.stats)

    outs1, stats1 = run_schedule()
    outs2, _ = run_schedule()
    assert outs1 == outs2, "int8 schedule not deterministic"
    assert stats1["decode_pages_sealed"] > 0
    assert stats1["prefix_hit_tokens_decode"] > 0, (
        "turn-2 prompt never hit the sealed decode region"
    )
    assert stats1["seal_requants"] > 0


def test_seal_time_requantization_leaves_tight_scales():
    """After retirement sealing, every cache-owned page's int8 content
    reaches full range (max|int8| == 127 per head, or the head is
    all-zero): the requantization undid any grow-and-rescale inflation
    before the page became immutable shared state."""
    params = trained_params()
    cb = make_paged(
        params, kv_dtype="int8", decode_page_cache="quantized",
        **spec_kw(params, k=2, draft_window=32),
    )
    rs = np.random.RandomState(5)
    p0 = rs.randint(0, CFG["vocab_size"], size=13).astype(np.int32)
    cb.run([p0], [10])
    cb.assert_page_accounting()
    assert cb.stats["seal_requants"] > 0
    cached = sorted(cb.prefix_cache.pages())
    assert cached
    for kent, vent in cb.pools:
        for data, scale in (kent, vent):
            d = np.abs(np.asarray(data)[cached]).max(axis=(2, 3))
            s = np.asarray(scale)[cached]
            assert ((d == 127) | (s == 0.0)).all(), (d, s)


def test_accounting_bytes_leg_catches_fullwidth_imposter():
    params = trained_params()
    cb = make_paged(params, kv_dtype="int8")
    cb.assert_page_accounting()
    (kd, ks), vent = cb.pools[0]
    # a silent full-width allocation wearing the int8 label
    cb.pools[0] = ((kd.astype(jnp.float32), ks), vent)
    with pytest.raises(AssertionError):
        cb.assert_page_accounting()
    cb.pools[0] = ((kd, ks), vent)
    cb.assert_page_accounting()
    # and the full-width twin: an int8 imposter in a declared-bf16 pool
    full = make_paged(params)
    kp, vp = full.pools[0]
    full.pools[0] = (kp.astype(jnp.bfloat16), vp)
    with pytest.raises(AssertionError):
        full.assert_page_accounting()


def test_pool_bytes_gauges_ledger_and_state_surface():
    params = trained_params()
    m = Metrics()
    cb = make_paged(params, kv_dtype="int8", metrics=m)
    kv = m.gauge("serve_pool_kv_bytes", dtype="int8")
    sc = m.gauge("serve_pool_kv_bytes", dtype="float32")
    hd = CFG["hidden"] // CFG["num_heads"]
    assert kv == 2 * CFG["num_layers"] * 40 * CFG["num_heads"] * 8 * hd
    assert sc == 2 * CFG["num_layers"] * 40 * CFG["num_heads"] * 4
    rs = np.random.RandomState(2)
    cb.run(_traffic(rs, n=2), [4, 4])
    row = cb.ledger_rows()[-1]
    assert row["kv_dtype"] == "int8"
    assert row["pool_kv_bytes"] == kv
    assert row["pool_scale_bytes"] == sc
    assert row["pool_bytes_per_device"] == kv + sc
    # the /v1/state surface (dataplane serving loop)
    from kubegpu_tpu.gateway.dataplane import ReplicaServingLoop

    loop = ReplicaServingLoop(cb)
    try:
        state = loop.state()
        assert state["kv_dtype"] == "int8"
        assert state["pages"]["kv_dtype"] == "int8"
        assert state["pages"]["kv_bytes"] == kv
        assert state["pages"]["scale_bytes"] == sc
    finally:
        loop.stop()
    # full-width pools declare their own dtype, one series
    m2 = Metrics()
    make_paged(params, metrics=m2)
    assert m2.gauge("serve_pool_kv_bytes", dtype="float32") > 0


# ---------------------------------------------------------------------------
# Migration: schema v2 (dtype + scales), refusal atomicity, wire codec
# ---------------------------------------------------------------------------

def test_int8_live_migration_roundtrip_token_identical():
    params = trained_params()
    rs = np.random.RandomState(4)
    src = make_paged(params, kv_dtype="int8")
    dst = make_paged(params, kv_dtype="int8")
    ref = make_paged(params, kv_dtype="int8")
    warm = rs.randint(0, CFG["vocab_size"], size=9).astype(np.int32)
    for cb in (src, dst, ref):
        cb.run([warm.copy()], [3])
    prompt = rs.randint(0, CFG["vocab_size"], size=17).astype(np.int32)
    src.submit(7, prompt.copy(), 12)
    for _ in range(6):
        src.serve_step()
    payload = src.export_pages(7)
    assert payload["geometry"]["kv_dtype"] == "int8"
    assert payload["geometry"]["schema"] == 2
    assert len(payload["scales"]) == CFG["num_layers"]
    # the wire codec round-trips int8 bytes + f32 scales exactly
    import json

    from kubegpu_tpu.gateway.dataplane import (
        decode_kv_payload,
        encode_kv_payload,
    )

    wire = json.loads(json.dumps(encode_kv_payload(payload)))
    back = decode_kv_payload(wire)
    for (k0, v0), (k1, v1) in zip(payload["layers"], back["layers"]):
        assert np.asarray(k1).dtype == np.int8
        assert (np.asarray(k0) == np.asarray(k1)).all()
        assert (np.asarray(v0) == np.asarray(v1)).all()
    for (k0, v0), (k1, v1) in zip(payload["scales"], back["scales"]):
        assert np.asarray(k1).dtype == np.float32
        assert (np.asarray(k0) == np.asarray(k1)).all()
        assert (np.asarray(v0) == np.asarray(v1)).all()
    src.cancel(7)
    dst.import_pages(7, back)
    done = {}
    while dst.has_work():
        done.update(dst.serve_step())
    assert done[7] == ref.run([prompt.copy()], [12])[0]
    src.assert_page_accounting()
    dst.assert_page_accounting()


def test_dtype_mismatched_import_refuses_atomically():
    params = trained_params()
    rs = np.random.RandomState(6)
    src = make_paged(params, kv_dtype="int8")
    prompt = rs.randint(0, CFG["vocab_size"], size=14).astype(np.int32)
    src.submit(1, prompt, 10)
    for _ in range(5):
        src.serve_step()
    payload = src.export_pages(1)
    # a full-width batcher must refuse the quantized payload with ZERO
    # refcounts moved — live import AND sealed twin
    full = make_paged(params, decode_page_cache="fp32")
    free0 = set(full.free_pages)
    cache0 = len(full.prefix_cache)
    with pytest.raises(ValueError, match="kv_dtype"):
        full.import_pages(5, payload)
    assert full.free_pages == free0 and len(full.prefix_cache) == cache0
    full.assert_page_accounting()
    # and the reverse direction: a legacy full-width payload into int8
    sealed_src = make_paged(params, decode_page_cache="fp32")
    out = sealed_src.run([prompt.copy()], [10])
    sealed = sealed_src.export_sealed_chain(
        [int(t) for t in prompt] + out[0]
    )
    assert sealed is not None
    q = make_paged(params, kv_dtype="int8",
                   decode_page_cache="quantized")
    free0 = set(q.free_pages)
    with pytest.raises(ValueError, match="kv_dtype"):
        q.import_sealed_chain(sealed)
    assert q.free_pages == free0
    q.assert_page_accounting()


def test_int8_sealed_chain_roundtrip_warms_the_importer():
    params = trained_params()
    rs = np.random.RandomState(8)
    a = make_paged(params, kv_dtype="int8", decode_page_cache="quantized")
    b = make_paged(params, kv_dtype="int8", decode_page_cache="quantized")
    p0 = rs.randint(0, CFG["vocab_size"], size=12).astype(np.int32)
    out = a.run([p0], [9])
    stream = [int(t) for t in p0] + out[0]
    payload = a.export_sealed_chain(stream)
    assert payload is not None and payload["geometry"]["kv_dtype"] == "int8"
    n = b.import_sealed_chain(payload)
    assert n > 0
    b.submit(2, np.asarray(stream + [1], np.int32), 5)
    while b.has_work():
        b.serve_step()
    assert b.stats["prefix_hit_tokens"] > 0
    a.assert_page_accounting()
    b.assert_page_accounting()


def test_session_store_budget_charges_quantized_scales():
    """The store's byte budget must charge a quantized payload's
    ``scales`` section too — retained-but-unbilled bytes would let the
    resident set silently exceed ``max_payload_bytes``."""
    from kubegpu_tpu.gateway.sessionstore import payload_bytes

    k = np.zeros((2, 4, 8, 8), np.int8)
    s = np.zeros((2, 4), np.float32)
    host = {"layers": [(k, k)], "scales": [(s, s)]}
    assert payload_bytes(host) == 2 * k.nbytes + 2 * s.nbytes
    wire = {"layers": [{"k": "aa", "v": "bb"}],
            "scales": [{"k": "cc", "v": "dd"}]}
    assert payload_bytes(wire) == 8


def test_simbatcher_kv_dtype_contract():
    from kubegpu_tpu.gateway.client import SimBatcher

    with pytest.raises(ValueError):
        SimBatcher(kv_dtype="fp16")
    sim8 = SimBatcher(kv_dtype="int8")
    sim16 = SimBatcher()
    # the mill advertises the REAL batchers' numpy-style names, so a
    # mixed SimBatcher/real fleet never reads as a kv_dtype skew
    assert sim8.kv_dtype == "int8" and sim16.kv_dtype == "bfloat16"
    sim8.submit(0, [1, 2, 3], 4)
    sim8.serve_step()
    payload = sim8.export_pages(0)
    assert payload["kv_dtype"] == "int8"
    with pytest.raises(ValueError, match="kv_dtype"):
        sim16.import_pages(1, payload)
    sim8b = SimBatcher(kv_dtype="int8")
    sim8b.import_pages(1, payload)   # twins transfer fine


def test_worker_cli_rejects_kv_dtype_off_the_paged_path():
    from kubegpu_tpu.models import worker

    tiny = ["--vocab", "61", "--layers", "1", "--heads", "2",
            "--hidden", "16", "--seq", "32", "--prompt-len", "8",
            "--batch-per-chip", "2", "--steps", "2"]
    with pytest.raises(SystemExit):
        worker.main(["--model", "decode", "--serving", "continuous",
                     "--kv-dtype", "int8"] + tiny)
    with pytest.raises(SystemExit):
        # contradictory pair: bf16 pool label on an fp32 server
        worker.main(["--model", "decode", "--serving", "paged",
                     "--serve-fp32", "--kv-dtype", "bf16"] + tiny)


def test_worker_cli_serves_paged_int8(capsys):
    from kubegpu_tpu.models import worker

    tiny = ["--vocab", "61", "--layers", "1", "--heads", "2",
            "--hidden", "16", "--seq", "32", "--prompt-len", "8",
            "--batch-per-chip", "2", "--steps", "2"]
    rc = worker.main(["--model", "decode", "--serving", "paged",
                      "--kv-dtype", "int8"] + tiny)
    assert rc == 0
    out = capsys.readouterr().out
    assert "DECODE_DONE" in out and "serving=paged" in out


# ---------------------------------------------------------------------------
# Compile stability: one jit entry per quantized program
# ---------------------------------------------------------------------------

def test_compile_stability_quantized_40_steps():
    """40 steps of admits, cancels, prefix hits, speculation, sealing
    and station churn on an int8 pool: exactly ONE compiled entry per
    program — the quantized step/draft/verify programs, each bucketed
    scatter/gather width, and each seal-time requant width."""
    params = trained_params()
    rng = np.random.RandomState(9)
    cb = make_paged(
        params, kv_dtype="int8", decode_page_cache="quantized",
        station_slots=2, token_budget=11, prefill_chunk=8,
        **spec_kw(params, k=2, draft_window=32),
    )
    seq, live = 0, []
    for _ in range(40):
        roll = rng.rand()
        if roll < 0.5:
            n = int(rng.randint(1, 13))
            max_new = int(rng.randint(1, 6))
            prompt = (
                np.arange(n, dtype=np.int32) % 7 if roll < 0.15
                else np.array(
                    rng.randint(0, CFG["vocab_size"], size=n), np.int32
                )
            )  # the arange prompts repeat -> prefix-cache hits
            cb.submit(seq, prompt, max_new)
            live.append(seq)
            seq += 1
        elif roll < 0.6 and live:
            cb.cancel(live.pop(rng.randint(len(live))))
        else:
            for s in cb.serve_step():
                live.remove(s)
    while cb.has_work():
        for s in cb.serve_step():
            live.remove(s)
    cb.assert_page_accounting()
    for name in ("_spec_draft", "_spec_verify", "_draft_admit", "_chunk"):
        assert getattr(cb, name)._cache_size() == 1, (
            f"{name}: {getattr(cb, name)._cache_size()} compiled entries"
        )
    assert cb._write_pages, "no multi-page scatter ran"
    for w, fn in cb._write_pages.items():
        assert fn._cache_size() == 1, f"scatter width {w} recompiled"
    for w, fn in cb._gather_pages.items():
        assert fn._cache_size() == 1, f"gather width {w} recompiled"
    assert cb._requant_pages, "no seal-time requant ran"
    for w, fn in cb._requant_pages.items():
        assert fn._cache_size() == 1, f"requant width {w} recompiled"
    assert cb._zero_scales, "no admission scale-zeroing ran"
    for w, fn in cb._zero_scales.items():
        assert fn._cache_size() == 1, f"zero-scales width {w} recompiled"


def test_fresh_pages_start_with_clean_scales():
    """Page-reuse regression (review finding): a page coming off the
    free list still carries its previous occupant's scale, and
    grow-and-rescale only ever grows — so without the admission-time
    reset, a new sequence's int8 bytes would depend on allocation
    HISTORY.  Pool sized so the second request can only get reused
    pages; its decode-headroom page must start at scale 0."""
    params = trained_params()
    cb = make_paged(
        params, kv_dtype="int8", prefix_cache=False, slots=1,
        station_slots=1, pool_pages=5,
    )
    rs = np.random.RandomState(21)
    p1 = rs.randint(0, CFG["vocab_size"], size=20).astype(np.int32)
    cb.run([p1], [10])
    ks = np.asarray(cb.pools[0][0][1])
    freed = sorted(cb.free_pages)
    assert ks[freed].max() > 0, "no stale scale to inherit — vacuous"
    p2 = rs.randint(0, CFG["vocab_size"], size=6).astype(np.int32)
    cb.submit(5, p2, 10)
    cb.serve_step()   # admission + first chunk; headroom page untouched
    s = next(s for s in cb._seqs if s.seq_id == 5)
    for kent, vent in cb.pools:
        for _, scale in (kent, vent):
            assert np.asarray(scale)[s.pages[-1]].max() == 0.0, (
                "fresh page inherited a previous occupant's scale"
            )
    while cb.has_work():
        cb.serve_step()
    cb.assert_page_accounting()


def test_reused_batcher_streams_identical_to_fresh():
    """The determinism contract across BOTH review findings (inherited
    pool scales, station-slot junk above the prompt inflating the tail
    page's scatter scale): a request served on a heavily-reused
    batcher must emit exactly the stream a fresh batcher emits —
    quantized state can never leak between sequences."""
    params = trained_params()
    rs = np.random.RandomState(22)
    prompts = _traffic(rs, n=4, lo=5, hi=22)
    budgets = [10, 7, 12, 9]
    kw = dict(kv_dtype="int8", prefix_cache=False, slots=1,
              station_slots=1, pool_pages=6)
    reused = make_paged(params, **kw)
    for i, (p, b) in enumerate(zip(prompts, budgets)):
        got = reused.run([p.copy()], [b])
        want = make_paged(params, **kw).run([p.copy()], [b])
        assert got[0] == want[0], (
            f"request {i}'s stream depends on allocation/station history"
        )
        reused.assert_page_accounting()


# ---------------------------------------------------------------------------
# Tensor parallelism: int8 pool + scales head-sharded over a mesh
# ---------------------------------------------------------------------------

def test_tp2_int8_pool_token_identity_and_sharded_scales():
    """TP=2 over the 8-way host sim: the int8 pool (pages AND scales)
    rests head-sharded, the quantized kernels run per head-shard under
    shard_map token-identically to the single-device int8 batcher, the
    layout+bytes accounting legs compose, and a TP=2 export imports
    into a TP=1 twin (shard-local scale reads reassemble in head
    order)."""
    from kubegpu_tpu.parallel import device_mesh

    if jax.device_count() < 2:
        pytest.skip("need 2 devices")
    # vocab/heads divisible by tp (lm_head is column-parallel)
    tcfg = dict(vocab_size=64, num_layers=2, num_heads=8, hidden=32,
                max_seq=32)
    model = TransformerLM(dtype=jnp.float32, **tcfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((2, 8), jnp.int32)
    )["params"]

    def mk(tp):
        mesh = (
            device_mesh({"model": tp}, devices=jax.devices()[:tp])
            if tp > 1 else None
        )
        return PagedContinuousBatcher(
            params, slots=3, prompt_pad=12, page_size=4, pool_pages=32,
            dtype=jnp.float32, kv_dtype="int8", mesh=mesh, **tcfg,
        )

    rs = np.random.RandomState(2)
    prompts = [
        rs.randint(0, 64, size=n).astype(np.int32) for n in (3, 7, 11)
    ]
    budgets = [6, 5, 7]
    one = mk(1)
    two = mk(2)
    out1 = one.run([p.copy() for p in prompts], budgets)
    out2 = two.run([p.copy() for p in prompts], budgets)
    assert out1 == out2, "TP=2 int8 tokens diverged from TP=1"
    two.assert_page_accounting()   # layout leg incl. scale sharding
    assert two._pool_bytes_per_device == one._pool_bytes_per_device // 2
    # migration across widths: TP=2 export → TP=1 import, resumable
    two.submit(50, prompts[0].copy(), 8)
    for _ in range(5):
        two.serve_step()
    payload = two.export_pages(50)
    two.cancel(50)
    dst = mk(1)
    dst.run([prompts[1].copy()], [3])
    dst.import_pages(50, payload)
    done = {}
    while dst.has_work():
        done.update(dst.serve_step())
    ref = mk(1).run([prompts[0].copy()], [8])
    assert done[50] == ref[0]
    dst.assert_page_accounting()
    two.assert_page_accounting()


# ---------------------------------------------------------------------------
# Soak: the acceptance kill schedule over int8 pools (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_gateway_soak_int8_kill_schedule():
    """The GatewaySoak kill/revive/hedge schedule with multi-turn
    sessions over REAL int8-pool batchers (quantized decode-page
    sealing AND speculation on): invariant I5, and page accounting —
    including the per-dtype bytes leg — on every surviving replica at
    quiescence."""
    from kubegpu_tpu.testing.soak import GatewaySoak

    params = trained_params()
    soak = GatewaySoak(
        seed=31, n_replicas=2, multiturn=True, follow_prompt_cap=12,
        batcher_factory=lambda key: PagedContinuousBatcher(
            params, slots=4, prompt_pad=16, page_size=4, pool_pages=56,
            station_slots=2, token_budget=8, dtype=jnp.float32,
            kv_dtype="int8", decode_page_cache="quantized",
            draft_params=params, speculate_k=2, draft_window=24,
            draft_num_layers=CFG["num_layers"],
            draft_num_heads=CFG["num_heads"],
            draft_hidden=CFG["hidden"], **CFG,
        ),
    )
    soak.run(steps=20)
