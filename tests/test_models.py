"""Workload-layer tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from kubegpu_tpu.models import (
    ResNet,
    TransformerLM,
    create_train_state,
    make_lm_train_step,
    make_resnet_train_step,
    place_lm,
    place_resnet,
)
from kubegpu_tpu.parallel import (
    TRANSFORMER_TP_RULES,
    device_mesh,
    distributed_init_from_env,
    mesh_from_assignment,
    spec_for_param,
)
from kubegpu_tpu.types.info import Assignment, ChipRef

pytestmark = pytest.mark.slow  # JAX compile-heavy; run with -m slow


def tiny_resnet():
    return ResNet(stage_sizes=(1, 1), num_filters=8, num_classes=10)


def tiny_lm(tp=2, sp=True):
    return TransformerLM(
        vocab_size=64, num_layers=2, num_heads=tp, hidden=16 * tp, max_seq=32,
        sequence_parallel=sp,
    )


# -- mesh helpers -----------------------------------------------------------

def test_device_mesh_inference_and_validation():
    mesh = device_mesh({"data": -1})
    assert mesh.shape["data"] == 8
    mesh2 = device_mesh({"data": 2, "model": 4})
    assert mesh2.shape == {"data": 2, "model": 4}
    with pytest.raises(ValueError):
        device_mesh({"data": 3})
    with pytest.raises(ValueError):
        device_mesh({"data": -1, "model": -1})


def test_distributed_init_noop_for_single_process():
    assert distributed_init_from_env({}) is False
    assert distributed_init_from_env({"JAX_NUM_PROCESSES": "1"}) is False
    assert distributed_init_from_env({"JAX_NUM_PROCESSES": "bogus"}) is False


def test_mesh_from_assignment_orders_by_coords():
    # chips deliberately listed with device_index order != coord order
    a = Assignment(
        node="n0",
        slice_id="s0",
        per_container={
            "m": [
                ChipRef("n0", 0, 0, (1, 1)),
                ChipRef("n0", 1, 1, (0, 0)),
                ChipRef("n0", 2, 2, (1, 0)),
                ChipRef("n0", 3, 3, (0, 1)),
            ]
        },
    )
    devs = jax.devices()[:4]
    mesh = mesh_from_assignment(a, {"data": 4}, devices=devs)
    flat = list(mesh.devices.flat)
    # coord order (0,0),(0,1),(1,0),(1,1) -> device_index 1,3,2,0
    assert [d.id for d in flat] == [devs[1].id, devs[3].id, devs[2].id, devs[0].id]


# -- sharding rules ---------------------------------------------------------

def test_tp_rules_cover_transformer_params():
    assert spec_for_param("layer0/attn/q_proj/kernel", TRANSFORMER_TP_RULES) == P(None, "model")
    assert spec_for_param("layer1/attn/o_proj/kernel", TRANSFORMER_TP_RULES) == P("model", None)
    assert spec_for_param("layer0/mlp_up/kernel", TRANSFORMER_TP_RULES) == P(None, "model")
    assert spec_for_param("layer0/mlp_down/kernel", TRANSFORMER_TP_RULES) == P("model", None)
    assert spec_for_param("embed/embedding", TRANSFORMER_TP_RULES) == P(None, "model")
    assert spec_for_param("lm_head/kernel", TRANSFORMER_TP_RULES) == P(None, "model")
    assert spec_for_param("layer0/ln1/scale", TRANSFORMER_TP_RULES) == P()
    assert spec_for_param("something/unmatched", TRANSFORMER_TP_RULES) == P()


# -- resnet DP --------------------------------------------------------------

def test_resnet_forward_shapes_and_dtypes():
    model = tiny_resnet()
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32  # head stays fp32


@pytest.mark.exhaustive
def test_resnet_dp_train_step_runs_and_learns():
    mesh = device_mesh({"data": -1})
    model = tiny_resnet()
    rng = jax.random.PRNGKey(0)
    images = jax.random.normal(rng, (16, 32, 32, 3), jnp.float32)
    labels = jnp.arange(16, dtype=jnp.int32) % 10
    state = create_train_state(model, rng, images)
    state, images, labels = place_resnet(state, (images, labels), mesh)
    # batch is really sharded over data
    assert images.sharding.spec == P("data")
    step = make_resnet_train_step(mesh, donate=False)
    losses = []
    for _ in range(3):
        state, loss = step(state, images, labels)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # same batch: loss must drop
    assert int(state.step) == 3


# -- scan-rolled resnet (the cold-compile flagship) -------------------------

def test_scan_resnet_param_parity_and_shapes():
    from kubegpu_tpu.models import ScanResNet

    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    plain = ResNet(stage_sizes=(2, 2), num_filters=8, num_classes=10)
    scan = ScanResNet(stage_sizes=(2, 2), num_filters=8, num_classes=10)
    vp = plain.init(jax.random.PRNGKey(0), x, train=False)
    vs = scan.init(jax.random.PRNGKey(0), x, train=False)
    n_plain = sum(p.size for p in jax.tree.leaves(vp["params"]))
    n_scan = sum(p.size for p in jax.tree.leaves(vs["params"]))
    assert n_plain == n_scan  # same network, params merely stacked
    logits = scan.apply(vs, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32
    # scanned body params carry a leading block axis of length count-1
    body = vs["params"]["stage1_body"]["block"]
    assert body["conv1"]["kernel"].shape[0] == 1


def test_scan_resnet_dp_train_step_runs_and_learns():
    from kubegpu_tpu.models import ScanResNet

    mesh = device_mesh({"data": -1})
    model = ScanResNet(stage_sizes=(2, 2), num_filters=8, num_classes=10)
    rng = jax.random.PRNGKey(0)
    images = jax.random.normal(rng, (16, 32, 32, 3), jnp.float32)
    labels = jnp.arange(16, dtype=jnp.int32) % 10
    state = create_train_state(model, rng, images)
    state, images, labels = place_resnet(state, (images, labels), mesh)
    step = make_resnet_train_step(mesh, donate=False)
    losses = []
    for _ in range(3):
        state, loss = step(state, images, labels)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]
    # batch-norm running stats were updated through the scan
    stats = jax.tree.leaves(state.batch_stats)
    assert any(float(jnp.max(jnp.abs(s))) > 0 for s in stats)


# -- input pipeline ---------------------------------------------------------

def test_prefetch_to_device_shards_and_preserves_order():
    from kubegpu_tpu.models import prefetch_to_device, synthetic_image_batches
    from kubegpu_tpu.parallel.sharding import batch_sharding

    mesh = device_mesh({"data": -1})
    src = synthetic_image_batches(16, size=8, num_classes=10, worker_id=0)
    host_first = next(synthetic_image_batches(16, size=8, num_classes=10, worker_id=0))
    it = prefetch_to_device(src, batch_sharding(mesh), depth=3)
    images, labels = next(it)
    assert images.sharding.spec == P("data")
    assert labels.shape == (16,)
    # deterministic per (seed, worker): first device batch == first host batch
    np.testing.assert_array_equal(np.asarray(labels), host_first[1])
    # successive batches differ (it is a stream, not a repeated constant)
    _, labels2 = next(it)
    assert not np.array_equal(np.asarray(labels), np.asarray(labels2))


def test_synthetic_batches_disjoint_per_worker():
    from kubegpu_tpu.models import synthetic_image_batches

    a = next(synthetic_image_batches(32, size=4, worker_id=0))[1]
    b = next(synthetic_image_batches(32, size=4, worker_id=1))[1]
    assert not np.array_equal(a, b)


def test_device_pool_batches_cycles_distinct_resident_batches():
    from kubegpu_tpu.models.data import device_pool_batches, synthetic_image_batches
    from kubegpu_tpu.parallel.sharding import batch_sharding

    mesh = device_mesh({"data": -1})
    it = device_pool_batches(
        synthetic_image_batches(16, size=4, num_classes=10),
        batch_sharding(mesh),
        pool=3,
    )
    first = [next(it) for _ in range(3)]
    labels = [np.asarray(l) for _, l in first]
    assert not np.array_equal(labels[0], labels[1])  # distinct batches
    # cycles: batch 4 IS batch 1 (same device buffer, no new transfer)
    again, _ = next(it)
    assert again is first[0][0]
    assert first[0][0].sharding.spec == P("data")


def test_prefetch_finite_iterator_drains_fully():
    from kubegpu_tpu.models import prefetch_to_device
    from kubegpu_tpu.parallel.sharding import batch_sharding

    mesh = device_mesh({"data": -1})
    src = [(jnp.ones((8, 4)), jnp.full((8,), i)) for i in range(5)]
    out = list(prefetch_to_device(iter(src), batch_sharding(mesh), depth=2))
    assert len(out) == 5
    assert [int(l[0]) for _, l in out] == [0, 1, 2, 3, 4]


@pytest.mark.exhaustive
def test_worker_main_smoke(capsys):
    from kubegpu_tpu.models import worker

    assert worker.main(["--model", "resnet-tiny", "--steps", "3",
                        "--batch-per-chip", "2"]) == 0
    out = capsys.readouterr().out
    assert "FIRST_STEP_DONE" in out
    assert "steady_state" in out


# -- transformer TP+SP ------------------------------------------------------

def test_lm_tp_placement_shards_params_and_moments():
    mesh = device_mesh({"data": 2, "model": 4})
    model = tiny_lm(tp=4)
    tokens = jnp.ones((4, 16), jnp.int32)
    state = create_train_state(model, jax.random.PRNGKey(0), tokens)
    state, tokens = place_lm(state, tokens, mesh)
    qk = state.params["layer0"]["attn"]["q_proj"]["kernel"]
    assert qk.sharding.spec == P(None, "model")
    ok = state.params["layer0"]["attn"]["o_proj"]["kernel"]
    assert ok.sharding.spec == P("model", None)
    # optimizer momentum mirrors the param sharding (sgd momentum trace)
    trace = state.opt_state[0].trace
    assert trace["layer0"]["attn"]["q_proj"]["kernel"].sharding.spec == P(None, "model")
    # shards are actually smaller than the global shape
    shard_shape = qk.sharding.shard_shape(qk.shape)
    assert shard_shape[1] == qk.shape[1] // 4


def test_lm_train_step_tp_sp():
    mesh = device_mesh({"data": 2, "model": 4})
    model = tiny_lm(tp=4, sp=True)
    tokens = (jnp.arange(4 * 17, dtype=jnp.int32) % 64).reshape(4, 17)
    state = create_train_state(model, jax.random.PRNGKey(0), tokens[:, :-1])
    state, tokens = place_lm(state, tokens, mesh)
    step = make_lm_train_step(mesh, donate=False)
    losses = []
    for _ in range(3):
        state, loss = step(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


# -- context-parallel LM (long context: ring/ulysses inside the model) ------

@pytest.mark.parametrize(
    "impl",
    [pytest.param("ring", marks=pytest.mark.exhaustive), "ulysses"],
)
def test_cp_lm_matches_single_device(impl):
    from kubegpu_tpu.models import place_cp_lm
    from kubegpu_tpu.models.train import lm_loss
    from kubegpu_tpu.parallel.sharding import current_mesh

    model = TransformerLM(
        vocab_size=64, num_layers=2, num_heads=4, hidden=32, max_seq=64,
        context_parallel=True, attn_impl=impl,
    )
    tokens = (jnp.arange(2 * 33, dtype=jnp.int32) % 64).reshape(2, 33)
    state = create_train_state(model, jax.random.PRNGKey(2), tokens[:, :-1])
    # single-device oracle: no ambient mesh -> falls back to local attention
    ref = float(lm_loss(state, state.params, tokens))

    mesh = device_mesh({"data": 2, "seq": 4})
    state, tok = place_cp_lm(state, tokens, mesh)
    step = make_lm_train_step(mesh, donate=False)
    state2, loss = step(state, tok)
    assert abs(float(loss) - ref) < 1e-2  # bf16 tolerance
    # a second step keeps learning (grads flowed through the CP attention)
    _, loss2 = step(state2, tok)
    assert float(loss2) < float(loss)


def test_cp_lm_activations_are_seq_sharded():
    from jax.sharding import NamedSharding
    from kubegpu_tpu.models import place_cp_lm
    from kubegpu_tpu.parallel.sharding import current_mesh

    model = TransformerLM(
        vocab_size=64, num_layers=1, num_heads=4, hidden=32, max_seq=64,
        context_parallel=True, attn_impl="ring",
    )
    tokens = jnp.ones((2, 32), jnp.int32)
    state = create_train_state(model, jax.random.PRNGKey(0), tokens)
    mesh = device_mesh({"data": 2, "seq": 4})
    state, tok = place_cp_lm(state, tokens, mesh)
    with current_mesh(mesh):
        logits = jax.jit(lambda p, t: state.apply_fn({"params": p}, t))(
            state.params, tok
        )
    # output keeps the (data, seq) layout — nothing gathered the sequence
    assert logits.sharding.spec[:2] == ("data", "seq")


@pytest.mark.parametrize(
    "impl",
    ["ring", pytest.param("ulysses", marks=pytest.mark.exhaustive)],
)
def test_3d_dp_tp_cp_lm_matches_single_device(impl):
    # the full composition: batch over "data", heads/kernels over "model"
    # (Megatron TP), sequence over "seq" (CP) — one mesh, one jit
    from kubegpu_tpu.models import place_lm
    from kubegpu_tpu.models.train import lm_loss

    model = TransformerLM(
        vocab_size=64, num_layers=2, num_heads=4, hidden=32, max_seq=64,
        context_parallel=True, attn_impl=impl,
    )
    tokens = (jnp.arange(2 * 33, dtype=jnp.int32) % 64).reshape(2, 33)
    state = create_train_state(model, jax.random.PRNGKey(3), tokens[:, :-1])
    ref = float(lm_loss(state, state.params, tokens))

    mesh = device_mesh({"data": 2, "model": 2, "seq": 2})
    state, tok = place_lm(state, tokens, mesh)  # params TP-sharded
    qk = state.params["layer0"]["attn"]["q_proj"]["kernel"]
    assert qk.sharding.spec == P(None, "model")
    step = make_lm_train_step(mesh, donate=False)
    state2, loss = step(state, tok)
    assert abs(float(loss) - ref) < 1e-2
    _, loss2 = step(state2, tok)
    assert float(loss2) < float(loss)


@pytest.mark.parametrize(
    "impl,heads,axes",
    [
        # heads (2) don't divide tp (4): must fall back to replicated heads
        ("ring", 2, {"data": 1, "model": 4, "seq": 2}),
        # local heads (4/2=2) don't divide seq (4): ulysses falls back too
        ("ulysses", 4, {"data": 1, "model": 2, "seq": 4}),
    ],
)
def test_cp_tp_indivisible_heads_fall_back_to_replication(impl, heads, axes):
    from kubegpu_tpu.models import place_lm
    from kubegpu_tpu.models.train import lm_loss

    model = TransformerLM(
        vocab_size=64, num_layers=1, num_heads=heads, hidden=32, max_seq=64,
        context_parallel=True, attn_impl=impl,
    )
    tokens = (jnp.arange(2 * 33, dtype=jnp.int32) % 64).reshape(2, 33)
    state = create_train_state(model, jax.random.PRNGKey(4), tokens[:, :-1])
    ref = float(lm_loss(state, state.params, tokens))
    mesh = device_mesh(axes)
    state, tok = place_lm(state, tokens, mesh)
    step = make_lm_train_step(mesh, donate=False)
    _, loss = step(state, tok)
    assert abs(float(loss) - ref) < 1e-2


def test_cp_lm_on_pure_cp_mesh():
    # no "data" axis at all: tokens replicate, activations shard over seq
    from kubegpu_tpu.models import place_cp_lm

    model = TransformerLM(
        vocab_size=64, num_layers=1, num_heads=4, hidden=32, max_seq=64,
        context_parallel=True, attn_impl="ring",
    )
    tokens = (jnp.arange(2 * 33, dtype=jnp.int32) % 64).reshape(2, 33)
    state = create_train_state(model, jax.random.PRNGKey(0), tokens[:, :-1])
    mesh = device_mesh({"seq": -1})
    state, tok = place_cp_lm(state, tokens, mesh)
    step = make_lm_train_step(mesh, donate=False)
    _, loss = step(state, tok)
    assert np.isfinite(float(loss))


def test_device_pool_short_source_cycles_and_empty_raises():
    from kubegpu_tpu.models.data import device_pool_batches
    from kubegpu_tpu.parallel.sharding import batch_sharding

    mesh = device_mesh({"data": -1})
    one = (jnp.ones((8, 4)), jnp.zeros((8,)))
    it = device_pool_batches(iter([one]), batch_sharding(mesh), pool=4)
    a, b = next(it), next(it)  # short source: cycles the single batch
    assert a[0] is b[0]
    with pytest.raises(ValueError, match="no batches"):
        next(device_pool_batches(iter([]), batch_sharding(mesh), pool=2))


@pytest.mark.exhaustive
def test_lm_tp_matches_single_device():
    # correctness of the sharded compute: TP loss == unsharded loss
    model = tiny_lm(tp=2, sp=True)
    tokens = (jnp.arange(2 * 17, dtype=jnp.int32) % 64).reshape(2, 17)
    rng = jax.random.PRNGKey(1)
    state_single = create_train_state(model, rng, tokens[:, :-1])
    from kubegpu_tpu.models.train import lm_loss

    ref = float(lm_loss(state_single, state_single.params, tokens))
    mesh = device_mesh({"data": 2, "model": 2}, devices=jax.devices()[:4])
    state, tok_sharded = place_lm(state_single, tokens, mesh)
    step = make_lm_train_step(mesh, donate=False)
    _, loss = step(state, tok_sharded)
    assert abs(float(loss) - ref) < 1e-2  # bf16 tolerance


def test_remat_blocks_grads_match_plain():
    """jax.checkpoint'd blocks (remat=True, the long-context memory knob)
    must be a pure memory/FLOPs trade: gradients identical to the plain
    model from the same variables."""
    import numpy as np

    from kubegpu_tpu.models import TransformerLM

    kw = dict(vocab_size=64, num_layers=2, num_heads=2, hidden=32,
              max_seq=64, dtype=jnp.float32)
    tokens = jnp.arange(2 * 32, dtype=jnp.int32).reshape(2, 32) % 50
    lm = TransformerLM(**kw)
    lm_r = TransformerLM(remat=True, **kw)
    variables = lm.init(jax.random.PRNGKey(0), tokens)

    g = jax.grad(lambda v: jnp.mean(lm.apply(v, tokens) ** 2))(variables)
    gr = jax.grad(lambda v: jnp.mean(lm_r.apply(v, tokens) ** 2))(variables)
    for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
