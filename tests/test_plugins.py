"""L1 plugin tests: fake provider, GKE discovery (fake env/devfs), advertiser."""

import json

from kubegpu_tpu.grpalloc import build_slice_views
from kubegpu_tpu.plugins import (
    Advertiser,
    ENV_VISIBLE_CHIPS,
    FakeSlice,
    GkeTpuProvider,
    visible_chips_env,
)
from kubegpu_tpu.types import RES_TPU, annotations
from kubegpu_tpu.types.info import ChipRef
from kubegpu_tpu.types.topology import TpuGeneration
from kubegpu_tpu.plugins.discovery import parse_accelerator_type, parse_topology
from kubegpu_tpu.utils import InMemoryApiServer


# -- fake provider ----------------------------------------------------------

def test_fake_provider_enumerate_and_allocate():
    fs = FakeSlice(mesh_shape=(4, 4), host_block=(2, 2))
    host = fs.hosts()[0]
    prov = fs.provider_for(host)
    frag = prov.enumerate()
    assert frag is not None and len(frag.chips) == 4
    node = frag.to_node_info()
    assert node.capacity.total("tpu") == 4
    chips = [ChipRef(host, ch.device_index, ch.chip_id, ch.coords) for ch in frag.chips[:2]]
    resp = prov.allocate(chips)
    assert resp.env[ENV_VISIBLE_CHIPS] == "0,1"
    assert resp.devices == ["/dev/accel0", "/dev/accel1"]


def test_fake_failure_injection():
    fs = FakeSlice(mesh_shape=(4, 4), host_block=(2, 2))
    victim = (0, 0)
    host = fs.topology.chips[victim].host_id
    fs.kill_chip(victim)
    frag = fs.provider_for(host).enumerate()
    healthy = [c for c in frag.chips if c.healthy]
    assert len(healthy) == 3
    fs.revive_chip(victim)
    frag = fs.provider_for(host).enumerate()
    assert all(c.healthy for c in frag.chips)


def test_visible_chips_env_sorted_deduped():
    refs = [ChipRef("h", 3, 3, (0, 0)), ChipRef("h", 1, 1, (0, 1)), ChipRef("h", 3, 3, (0, 0))]
    assert visible_chips_env(refs) == "1,3"


# -- GKE discovery ----------------------------------------------------------

GKE_ENV_V5E16_W0 = {
    "TPU_ACCELERATOR_TYPE": "v5litepod-16",
    "TPU_TOPOLOGY": "4x4",
    "TPU_WORKER_ID": "0",
    "TPU_WORKER_HOSTNAMES": "job-0.svc,job-1.svc,job-2.svc,job-3.svc",
    "NODE_NAME": "gke-node-0",
}


def fake_devfs4():
    return ["/dev/accel0", "/dev/accel1", "/dev/accel2", "/dev/accel3"]


def test_parse_helpers():
    assert parse_accelerator_type("v5litepod-16") == (TpuGeneration.V5E, 16)
    assert parse_accelerator_type("v4-8") == (TpuGeneration.V4, 4)
    assert parse_accelerator_type("") is None
    assert parse_accelerator_type("tpu") is None
    assert parse_topology("4x4") == (4, 4)
    assert parse_topology("2x2x2") == (2, 2, 2)
    assert parse_topology("abc") is None


def test_gke_discovery_worker0():
    prov = GkeTpuProvider(env=GKE_ENV_V5E16_W0, list_devfs=fake_devfs4)
    frag = prov.enumerate()
    assert frag is not None
    assert frag.generation == TpuGeneration.V5E
    assert frag.mesh_shape == (4, 4)
    assert len(frag.chips) == 4
    assert frag.node_name == "gke-node-0"
    # worker 0 owns the origin 2x2 block
    assert {c.coords for c in frag.chips} == {(0, 0), (0, 1), (1, 0), (1, 1)}


def test_gke_discovery_worker3_block_and_same_slice_id():
    env3 = dict(GKE_ENV_V5E16_W0, TPU_WORKER_ID="3", NODE_NAME="gke-node-3")
    frag0 = GkeTpuProvider(env=GKE_ENV_V5E16_W0, list_devfs=fake_devfs4).enumerate()
    frag3 = GkeTpuProvider(env=env3, list_devfs=fake_devfs4).enumerate()
    assert frag3.slice_id == frag0.slice_id  # same hostname set → same identity
    assert {c.coords for c in frag3.chips} == {(2, 2), (2, 3), (3, 2), (3, 3)}
    # fragments must tile without overlap
    assert not ({c.coords for c in frag0.chips} & {c.coords for c in frag3.chips})


def test_gke_discovery_all_workers_tile_slice():
    coords = set()
    for w in range(4):
        env = dict(GKE_ENV_V5E16_W0, TPU_WORKER_ID=str(w), NODE_NAME=f"gke-node-{w}")
        frag = GkeTpuProvider(env=env, list_devfs=fake_devfs4).enumerate()
        coords |= {c.coords for c in frag.chips}
    assert len(coords) == 16


def test_gke_discovery_non_tpu_host():
    prov = GkeTpuProvider(env={"PATH": "/usr/bin"}, list_devfs=lambda: [])
    assert prov.enumerate() is None


def test_gke_discovery_v4_3d():
    env = {
        "TPU_ACCELERATOR_TYPE": "v4-16",
        "TPU_TOPOLOGY": "2x2x2",
        "TPU_WORKER_ID": "1",
        "TPU_WORKER_HOSTNAMES": "a,b",
        "NODE_NAME": "n1",
    }
    frag = GkeTpuProvider(env=env, list_devfs=fake_devfs4).enumerate()
    assert frag is not None
    assert frag.mesh_shape == (2, 2, 2)
    assert len(frag.chips) == 4


def test_gke_discovery_degraded_devfs_marks_unhealthy():
    # broken driver: platform says 4 chips/host, devfs shows 2 — the host
    # must still advertise its full block, missing chips unhealthy
    env = dict(GKE_ENV_V5E16_W0)
    frag = GkeTpuProvider(env=env, list_devfs=lambda: ["/dev/accel0", "/dev/accel1"]).enumerate()
    assert frag is not None and len(frag.chips) == 4
    assert sum(1 for c in frag.chips if c.healthy) == 2


def test_gke_discovery_out_of_range_worker_refused():
    env = dict(GKE_ENV_V5E16_W0, TPU_WORKER_ID="9")
    assert GkeTpuProvider(env=env, list_devfs=fake_devfs4).enumerate() is None


def test_gke_allocate_missing_device_node_raises():
    import pytest

    prov = GkeTpuProvider(env=GKE_ENV_V5E16_W0, list_devfs=lambda: ["/dev/accel0"])
    with pytest.raises(ValueError, match="no device node"):
        prov.allocate([ChipRef("gke-node-0", 3, 3, (1, 1))])


def test_gke_empty_devfs_advertises_zero_capacity():
    # a host with no working device nodes must not look healthy
    frag = GkeTpuProvider(env=GKE_ENV_V5E16_W0, list_devfs=lambda: []).enumerate()
    assert frag is not None and len(frag.chips) == 4
    assert sum(1 for c in frag.chips if c.healthy) == 0


def test_gke_missing_low_device_does_not_shift_mapping():
    # /dev/accel0 gone: chip 0 (not chip 3) must be the unhealthy one, and
    # allocate(chip 2) must hand out /dev/accel2, not a neighbour's node
    devfs = lambda: ["/dev/accel1", "/dev/accel2", "/dev/accel3"]
    prov = GkeTpuProvider(env=GKE_ENV_V5E16_W0, list_devfs=devfs)
    frag = prov.enumerate()
    unhealthy = [c.device_index for c in frag.chips if not c.healthy]
    assert unhealthy == [0]
    resp = prov.allocate([ChipRef("gke-node-0", 2, 2, (1, 0))])
    assert resp.devices == ["/dev/accel2"]
    import pytest

    with pytest.raises(ValueError):
        prov.allocate([ChipRef("gke-node-0", 0, 0, (0, 0))])


def test_fake_accel_type_roundtrips_for_v4():
    from kubegpu_tpu.plugins.fake import FakeSlice

    fs = FakeSlice(generation=TpuGeneration.V4, mesh_shape=(2, 2, 2), host_block=(2, 2, 1))
    host = fs.hosts()[0]
    prov = fs.provider_for(host)
    frag = prov.enumerate()
    chips = [ChipRef(host, c.device_index, c.chip_id, c.coords) for c in frag.chips[:1]]
    resp = prov.allocate(chips)
    gen, n_chips = parse_accelerator_type(resp.env["TPU_ACCELERATOR_TYPE"])
    assert gen == TpuGeneration.V4 and n_chips == 8


# -- advertiser -------------------------------------------------------------

def test_advertiser_publishes_topology_and_capacity():
    api = InMemoryApiServer()
    fs = FakeSlice(mesh_shape=(4, 4), host_block=(2, 2))
    for host, prov in fs.providers().items():
        Advertiser(prov, api).advertise_once()
    nodes = api.list_nodes()
    assert len(nodes) == 4
    infos = [annotations.node_from_k8s(n) for n in nodes]
    views = build_slice_views(infos)
    assert len(views) == 1
    view = next(iter(views.values()))
    assert len(view.free) == 16
    for n in nodes:
        assert n["status"]["capacity"][RES_TPU] == "4"


def test_advertiser_health_propagates_to_cluster_view():
    api = InMemoryApiServer()
    fs = FakeSlice(mesh_shape=(4, 4), host_block=(2, 2))
    advs = {h: Advertiser(p, api) for h, p in fs.providers().items()}
    for a in advs.values():
        a.advertise_once()
    fs.kill_chip((0, 0))
    victim_host = fs.topology.chips[(0, 0)].host_id
    advs[victim_host].advertise_once()
    infos = [annotations.node_from_k8s(n) for n in api.list_nodes()]
    view = next(iter(build_slice_views(infos).values()))
    assert len(view.free) == 15
    assert api.get_node(victim_host)["status"]["capacity"][RES_TPU] == "3"


def test_advertiser_noop_on_cpu_host():
    api = InMemoryApiServer()
    prov = GkeTpuProvider(env={}, list_devfs=lambda: [])
    assert Advertiser(prov, api).advertise_once() is None
    assert api.list_nodes() == []
