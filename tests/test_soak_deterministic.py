"""Deterministic-interleaving soak: replayable concurrency testing.

Closes VERDICT r4 weak #5: the threaded soak (tests/test_soak.py) explores
real OS interleavings but cannot replay a failure it finds.  Here the SAME
logical tasks — two racing schedule sweeps, a pod/gang churner, a chip
killer firing watch-style node updates — run under
kubegpu_tpu.testing.interleave.Interleaver: one task executes at a time,
and at every lock acquire/release the controller picks who runs next from a
seeded RNG.  The interleaving is therefore a pure function of the seed:

  - a failing seed IS the reproduction (re-run the test with that seed);
  - the recorded decision list replays directly (Interleaver(schedule=...)),
    surviving even RNG-implementation drift;
  - genuine lock-ordering deadlocks surface as a deterministic
    DeadlockError with the holds/wants map, not a CI timeout.

The two soaks are complementary, per the r4 verdict's framing: threads find
schedules nobody thought to enumerate; the interleaver makes any schedule —
found or constructed — exactly reproducible.
"""

import json
import os
import random

import pytest

from kubegpu_tpu.testing.interleave import (
    DeadlockError,
    Interleaver,
    ReplayDivergenceError,
    preimport,
)
from kubegpu_tpu.testing.soak import Soak, settle_and_check
from kubegpu_tpu.types import annotations


def _unlink_dump(msg: str) -> None:
    """Remove the schedule dump an abnormal-exit test deliberately caused."""
    import re

    m = re.search(r"open\('([^']+)'\)", msg)
    if m:
        os.unlink(m.group(1))


def _snapshot(s: Soak) -> str:
    """Canonical digest of the durable cluster state (the API server is the
    only durable store — SURVEY §1's data-flow contract)."""
    pods = {}
    for obj in s.api.list_pods():
        ann = obj["metadata"].get("annotations") or {}
        pods[obj["metadata"]["name"]] = [
            (obj.get("spec") or {}).get("nodeName"),
            (obj.get("status") or {}).get("phase"),
            ann.get(annotations.POD_ASSIGNMENT),
        ]
    nodes = {
        n["metadata"]["name"]: (n["metadata"].get("annotations") or {})
        for n in s.api.list_nodes()
    }
    return json.dumps([pods, nodes], sort_keys=True)


def _run_soak(seed: int, schedule=None):
    """One deterministic soak run, settled to quiescence; returns
    (interleaver, soak).  Everything — run, settle, invariant checks —
    happens inside activate() so it all sees the one virtual clock."""
    preimport()
    iv = Interleaver(seed=seed, schedule=schedule)
    with iv.activate():
        s = Soak(1000 + seed)
        # steady workload to fight over (mirrors the threaded soak)
        for _ in range(4):
            s.op_create_gang()
        for _ in range(6):
            s.op_create_pod()

        churn_rng = random.Random(50 + seed)
        chaos_rng = random.Random(77 + seed)

        def sweeps(n):
            def run():
                for _ in range(n):
                    s.op_schedule_sweep()

            return run

        def churn(n):
            def run():
                for _ in range(n):
                    r = churn_rng.random()
                    if r < 0.3:
                        s.op_create_pod()
                    elif r < 0.5:
                        s.op_delete_pod()
                    elif r < 0.65:
                        s.op_create_gang()
                    elif r < 0.8:
                        s.op_recreate_member()
                    elif r < 0.9:
                        s.op_complete_pod()
                    else:
                        s.op_stale_delete_event()

            return run

        def chaos(n):
            def run():
                for _ in range(n):
                    if chaos_rng.random() < 0.5:
                        s.op_kill_chip()
                    else:
                        s.op_revive_chip()
                    # watch-style delivery: push fresh node objects straight
                    # into the scheduler, racing the sweeps
                    for obj in s.api.list_nodes():
                        s.sched.on_node_updated(obj)

            return run

        iv.task("sweepA", sweeps(8))
        iv.task("sweepB", sweeps(8))
        iv.task("churn", churn(18))
        iv.task("chaos", chaos(5))
        iv.run()
        settle_and_check(s, f"deterministic soak seed {seed}")
    return iv, s


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_deterministic_soak_invariants(seed):
    """The full chaos mix, serialized under a seeded schedule, settles to a
    state satisfying I1–I4 — for every seed, reproducibly."""
    iv, s = _run_soak(seed)
    assert len(iv.schedule) > 500, "schedule suspiciously short — tasks idle?"


def test_same_seed_replays_identically():
    """The determinism claim itself: same seed ⇒ same decision sequence ⇒
    byte-identical final cluster state."""
    iv1, s1 = _run_soak(1)
    iv2, s2 = _run_soak(1)
    assert iv1.schedule == iv2.schedule
    assert _snapshot(s1) == _snapshot(s2)


def test_recorded_schedule_replays():
    """A recorded decision list replays through the explicit-schedule path
    (the form a failure report would ship) and reproduces the same state."""
    iv1, s1 = _run_soak(2)
    iv2, s2 = _run_soak(2, schedule=iv1.schedule)
    assert iv2.schedule == iv1.schedule
    assert _snapshot(s1) == _snapshot(s2)


def test_different_seeds_explore_different_schedules():
    iv0, _ = _run_soak(0)
    iv1, _ = _run_soak(1)
    assert iv0.schedule != iv1.schedule


def test_deadlock_detected_deterministically():
    """The harness doubles as a deadlock finder: an AB/BA lock inversion,
    driven by the exact schedule that interleaves the two critical sections,
    raises DeadlockError with the holds/wants map — it does not hang."""
    import threading

    iv = Interleaver(schedule=["t1", "t2", "t1", "t2", "t1", "t2"])
    with iv.activate():
        a = threading.Lock()
        b = threading.Lock()

        def t1():
            with a:
                with b:
                    pass

        def t2():
            with b:
                with a:
                    pass

        iv.task("t1", t1)
        iv.task("t2", t2)
        with pytest.raises(DeadlockError) as exc:
            iv.run()
    msg = str(exc.value)
    assert "t1" in msg and "t2" in msg
    _unlink_dump(msg)


def test_replay_divergence_is_reported():
    """A schedule that names a non-runnable task fails loudly, not silently."""
    iv = Interleaver(schedule=["nope"])
    with iv.activate():
        import threading

        lk = threading.Lock()

        def t1():
            with lk:
                pass

        iv.task("t1", t1)
        with pytest.raises(ReplayDivergenceError) as exc:
            iv.run()
    _unlink_dump(str(exc.value))


@pytest.mark.exhaustive
@pytest.mark.parametrize("seed", range(4, 20))
def test_deterministic_soak_seed_sweep(seed):
    """Wider schedule exploration (exhaustive tier): 16 more seeds through
    the full chaos mix — every one must settle to an invariant-clean
    state, and every one is replayable by construction."""
    _run_soak(seed)


def test_failed_run_dumps_replayable_schedule():
    """A task failure persists the decision list to disk and names the
    file in the error — the failure report IS the reproduction."""
    import re
    import threading

    iv = Interleaver(seed=5)
    with iv.activate():
        lk = threading.Lock()

        def t1():
            with lk:
                pass
            raise RuntimeError("boom")

        iv.task("t1", t1)
        with pytest.raises(AssertionError, match="replay with") as exc:
            iv.run()
    path = re.search(r"open\('([^']+)'\)", str(exc.value)).group(1)
    try:
        with open(path) as f:
            sched = json.load(f)
    finally:
        os.unlink(path)
    assert sched == iv.schedule and sched
