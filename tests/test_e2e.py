"""Full-stack end-to-end over both wire protocols (SURVEY.md §3.4).

The integration coverage the reference lacked (SURVEY.md §4): one flow from
the north-star sample YAML through every process boundary the real cluster
has — advertiser → extender **HTTP** (filter/prioritize/bind as
kube-scheduler would call it) → assignment annotations → CRI **gRPC**
CreateContainer through the proxy — asserting the container config that
reaches "containerd" carries the full TPU + gang env.  Plus two gangs
racing through the threaded HTTP server for one slice (BASELINE config 5's
concurrency hazard: SURVEY.md §7 hard part (c))."""

import json
import pathlib
import threading
import urllib.request
from concurrent import futures

import grpc
import pytest
import yaml

from kubegpu_tpu.crishim import CriProxy, ShimDaemon
from kubegpu_tpu.crishim.proxy import CREATE_CONTAINER
from kubegpu_tpu.plugins import Advertiser, FakeSlice
from kubegpu_tpu.scheduler import Scheduler
from kubegpu_tpu.scheduler.server import ExtenderServer
from kubegpu_tpu.types import annotations, is_contiguous_submesh
from kubegpu_tpu.utils import InMemoryApiServer
from kubegpu_tpu.utils import protowire as pw

from test_crishim import FakeCriBackend, _call, make_create_request

SAMPLES = pathlib.Path(__file__).resolve().parent.parent / "samples"
MESH = (4, 4)


@pytest.fixture()
def stack():
    api = InMemoryApiServer()
    fs = FakeSlice(slice_id="v5e-16", mesh_shape=MESH, host_block=(2, 2))
    for prov in fs.providers().values():
        Advertiser(prov, api).advertise_once()
    server = ExtenderServer(Scheduler(api), listen=("127.0.0.1", 0))
    server.start()
    yield api, fs, server
    server.stop()


def http(server, method, path, obj=None):
    host, port = server.address
    req = urllib.request.Request(
        f"http://{host}:{port}{path}",
        data=None if obj is None else json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"},
        method=method,
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def schedule_over_http(server, api, pod_objs):
    nodes = sorted(n["metadata"]["name"] for n in api.list_nodes())
    for obj in pod_objs:
        http(server, "POST", "/pods", obj)
    out = {}
    for obj in pod_objs:
        name = obj["metadata"]["name"]
        f = http(server, "POST", "/filter", {"Pod": obj, "NodeNames": nodes})
        assert f["NodeNames"], (name, f["FailedNodes"])
        scores = {e["Host"]: e["Score"] for e in
                  http(server, "POST", "/prioritize", {"Pod": obj, "NodeNames": f["NodeNames"]})}
        best = max(f["NodeNames"], key=lambda n: (scores.get(n, 0), n))
        b = http(server, "POST", "/bind",
                 {"PodNamespace": "default", "PodName": name, "Node": best})
        assert not b["Error"], (name, b)
        out[name] = annotations.assignment_from_pod(api.get_pod("default", name))
    return out


@pytest.mark.parametrize(
    "sample,svc", [("jax-resnet.yaml", "jax-resnet"), ("jax-lm-tp.yaml", "jax-lm-tp")]
)
def test_north_star_sample_full_stack_over_wire(stack, sample, svc):
    # jax-resnet = the DP north star; jax-lm-tp = a non-ResNet workload
    # (TP/SP LM) through the identical extender→CRI→worker-env path
    api, fs, server = stack
    pods = [d for d in yaml.safe_load_all((SAMPLES / sample).read_text())
            if d and d.get("kind") == "Pod"]
    assigned = schedule_over_http(server, api, pods)

    union = {c.coords for a in assigned.values() for c in a.all_chips()}
    assert len(union) == 4 and is_contiguous_submesh(union, MESH)

    # one CRI proxy per node that received gang members, like the DaemonSet
    by_node = {}
    for name, a in assigned.items():
        by_node.setdefault(a.node, []).append(name)

    for node, names in by_node.items():
        backend = FakeCriBackend()
        upstream = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
        upstream.add_generic_rpc_handlers((backend,))
        up_port = upstream.add_insecure_port("127.0.0.1:0")
        upstream.start()
        daemon = ShimDaemon(api, fs.provider_for(node))
        proxy = CriProxy(upstream_target=f"127.0.0.1:{up_port}",
                         decide=daemon.decide, listen_target="127.0.0.1:0")
        proxy.start()
        channel = grpc.insecure_channel(f"127.0.0.1:{proxy.port}")
        try:
            for name in names:
                req = make_create_request("default", name, "worker")
                _call(channel, CREATE_CONTAINER, req)
                mutated = backend.requests[CREATE_CONTAINER][-1]
                config = bytes(pw.get_field(mutated, 2))
                env = pw.decode_string_map(pw.get_all(config, 6))
                assert env["TPU_VISIBLE_CHIPS"]
                assert env["JAX_NUM_PROCESSES"] == "4"
                assert env["TPU_WORKER_ID"] == env["JAX_PROCESS_ID"]
                assert f"{name}.{svc}.default.svc" in env["TPU_WORKER_HOSTNAMES"]
                # device nodes rode along with the env
                assert pw.get_all(config, 8), "no devices injected"
        finally:
            channel.close()
            proxy.stop(0)
            upstream.stop(0)


def _assert_chip_death_evicts(resync_interval_s, watch, fail_msg):
    """Shared harness for the two deployed failure-detection paths: place a
    pod over the wire, kill its chip, run the advertiser's health cycle,
    and require the RUNNING server (no direct calls) to evict the pod."""
    import time

    api = InMemoryApiServer()
    fs = FakeSlice(slice_id="v5e-16", mesh_shape=MESH, host_block=(2, 2))
    advs = {h: Advertiser(p, api) for h, p in fs.providers().items()}
    for a in advs.values():
        a.advertise_once()
    server = ExtenderServer(Scheduler(api), listen=("127.0.0.1", 0),
                            resync_interval_s=resync_interval_s, watch=watch)
    server.start()
    try:
        obj = {
            "metadata": {"name": "victim", "namespace": "default",
                         "annotations": {}},
            "spec": {"containers": [
                {"name": "main",
                 "resources": {"limits": {"google.com/tpu": "1"}}}]},
        }
        assigned = schedule_over_http(server, api, [obj])
        ref = assigned["victim"].all_chips()[0]
        fs.kill_chip(ref.coords)
        advs[ref.host].advertise_once()  # the DaemonSet's health cycle
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            try:
                api.get_pod("default", "victim")
            except Exception:  # noqa: BLE001 - NotFound
                return
            time.sleep(0.05)
        raise AssertionError(fail_msg)
    finally:
        server.stop()


def test_chip_death_evicts_via_live_resync_loop():
    # failure detection through the periodic resync sweep ALONE: the watch
    # fast path is disabled so only the 0.2s resync tick can evict
    _assert_chip_death_evicts(
        0.2, watch=False, fail_msg="resync sweep did not evict the pod"
    )


def test_chip_death_evicts_via_node_watch_event():
    # the event-driven fast path ALONE: resync is parked far in the future,
    # so only the node WATCH can deliver the advertiser's health patch
    _assert_chip_death_evicts(
        3600.0, watch=True,
        fail_msg="node-update event did not trigger eviction",
    )


def test_two_gangs_race_over_threaded_http(stack):
    api, fs, server = stack
    pods = [d for d in yaml.safe_load_all((SAMPLES / "multi-tenant.yaml").read_text())
            if d and d.get("kind") == "Pod"]
    gangs = {}
    for obj in pods:
        gangs.setdefault(
            obj["metadata"]["annotations"]["kubegpu-tpu/pod-group"], []
        ).append(obj)
    assert set(gangs) == {"tenant-a", "tenant-b"}
    for obj in pods:
        http(server, "POST", "/pods", obj)

    results, errors = {}, []

    def run_gang(gang, objs):
        try:
            nodes = sorted(n["metadata"]["name"] for n in api.list_nodes())
            for obj in objs:
                name = obj["metadata"]["name"]
                f = http(server, "POST", "/filter", {"Pod": obj, "NodeNames": nodes})
                assert f["NodeNames"], (name, f["FailedNodes"])
                b = http(server, "POST", "/bind",
                         {"PodNamespace": "default", "PodName": name,
                          "Node": f["NodeNames"][0]})
                assert not b["Error"], (name, b)
                results[name] = annotations.assignment_from_pod(
                    api.get_pod("default", name))
        except Exception as e:  # noqa: BLE001
            errors.append((gang, repr(e)))

    threads = [threading.Thread(target=run_gang, args=(g, objs))
               for g, objs in gangs.items()]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors

    per_gang = {}
    for obj in pods:
        name = obj["metadata"]["name"]
        gang = obj["metadata"]["annotations"]["kubegpu-tpu/pod-group"]
        per_gang.setdefault(gang, set()).update(
            c.coords for c in results[name].all_chips())
    assert all(len(v) == 8 for v in per_gang.values()), {
        k: len(v) for k, v in per_gang.items()}
    assert not (per_gang["tenant-a"] & per_gang["tenant-b"]), "double-allocated chips"
    for gang, coords in per_gang.items():
        assert is_contiguous_submesh(coords, MESH), f"{gang} fragmented"


def test_state_survives_extender_restart_over_http(stack):
    """§3.5 replay at the service level: a brand-new extender process built
    from the same API server reports the identical used-set."""
    api, fs, server = stack
    pods = [d for d in yaml.safe_load_all((SAMPLES / "four-chip.yaml").read_text())
            if d and d.get("kind") == "Pod"]
    schedule_over_http(server, api, pods)
    before = http(server, "GET", "/state")

    server2 = ExtenderServer(Scheduler(api), listen=("127.0.0.1", 0))
    server2.start()
    try:
        after = http(server2, "GET", "/state")
        assert after["slices"] == before["slices"]
    finally:
        server2.stop()
