"""Native discovery shim (native/tpu_discovery.cpp + plugins/native.py).

Mirrors how the reference isolates its NVML binding (SURVEY.md §4): the C++
library is probed against a fabricated devfs tree, then wired through
GkeTpuProvider so the enumerate/health path is exercised end-to-end off-TPU.
Tests skip (not fail) when the library hasn't been built — `make native`
builds it; the pure-Python fallback keeps the framework fully functional
without it and is covered by test_plugins.py.
"""

import os
import stat
import subprocess

import pytest

from kubegpu_tpu.plugins import native

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")


@pytest.fixture(scope="module", autouse=True)
def built_lib():
    if not os.path.exists(os.path.join(NATIVE_DIR, "libtpu_discovery.so")):
        try:
            subprocess.run(["make", "-C", NATIVE_DIR], check=True, capture_output=True)
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", b"") or b""
            pytest.skip(
                "native shim not buildable here: "
                f"{e} [{detail[-300:].decode(errors='replace')}]"
            )
    if native.load() is None:
        pytest.skip("libtpu_discovery.so not loadable")


def fake_devfs(tmp_path, names, unwritable=()):
    dev = tmp_path / "dev"
    dev.mkdir()
    for n in names:
        p = dev / n
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text("")
        if n in unwritable:
            os.chmod(p, stat.S_IRUSR)  # readable, not writable -> inaccessible
    return str(dev)


def test_version_string():
    assert native.version() == "kubegpu-tpu-discovery/1"


def test_probe_accel_nodes_sorted_and_sparse(tmp_path):
    # accel nodes keep their embedded chip index; a missing accel1 must not
    # shift accel2/accel3 (the neighbour-chip hazard discovery.py documents)
    root = fake_devfs(tmp_path, ["accel3", "accel0", "accel2"])
    p = native.probe(root)
    assert [c.index for c in p.chips] == [0, 2, 3]
    assert [os.path.basename(c.path) for c in p.chips] == ["accel0", "accel2", "accel3"]
    assert all(c.accessible for c in p.chips)


def test_probe_empty_devfs_is_cpu_host(tmp_path):
    root = fake_devfs(tmp_path, [])
    p = native.probe(root)
    assert p is not None and p.chips == []


def test_probe_vfio_fallback_dense_numeric_order(tmp_path):
    # vfio group ids are not chip ids: sorted numerically (10 after 2) and
    # re-indexed densely
    root = fake_devfs(tmp_path, ["vfio/2", "vfio/10", "vfio/1"])
    p = native.probe(root)
    assert [c.index for c in p.chips] == [0, 1, 2]
    assert [os.path.basename(c.path) for c in p.chips] == ["1", "2", "10"]


def test_probe_accel_wins_over_vfio(tmp_path):
    root = fake_devfs(tmp_path, ["accel0", "vfio/0"])
    p = native.probe(root)
    assert [os.path.basename(c.path) for c in p.chips] == ["accel0"]


@pytest.mark.skipif(os.geteuid() == 0, reason="root bypasses permission bits")
def test_probe_reports_unwritable_node_inaccessible(tmp_path):
    root = fake_devfs(tmp_path, ["accel0", "accel1"], unwritable={"accel1"})
    p = native.probe(root)
    by_idx = {c.index: c for c in p.chips}
    assert by_idx[0].accessible and not by_idx[1].accessible


def test_gke_provider_uses_native_probe(tmp_path, monkeypatch):
    from kubegpu_tpu.plugins.discovery import GkeTpuProvider

    root = fake_devfs(tmp_path, ["accel0", "accel1", "accel2", "accel3"])
    env = {
        "TPU_ACCELERATOR_TYPE": "v5litepod-4",
        "TPU_TOPOLOGY": "2x2",
        "NODE_NAME": "host0",
    }
    prov = GkeTpuProvider(env=env)
    # route the provider's native probes at the fabricated tree
    monkeypatch.setattr(prov, "_native_probe", lambda: native.probe(root))
    frag = prov.enumerate()
    assert frag is not None and len(frag.chips) == 4
    assert all(ch.healthy for ch in frag.chips)
    assert prov.healthy_device_indices() == [0, 1, 2, 3]
    resp = prov.allocate([c for c in _refs(frag)][:2])
    assert resp.env["TPU_VISIBLE_CHIPS"] == "0,1"
    assert [os.path.basename(d) for d in resp.devices] == ["accel0", "accel1"]


def test_gke_provider_native_health_drops_missing_node(tmp_path, monkeypatch):
    from kubegpu_tpu.plugins.discovery import GkeTpuProvider

    root = fake_devfs(tmp_path, ["accel0", "accel1", "accel3"])  # chip 2 dead
    env = {
        "TPU_ACCELERATOR_TYPE": "v5litepod-4",
        "TPU_TOPOLOGY": "2x2",
        "NODE_NAME": "host0",
    }
    prov = GkeTpuProvider(env=env)
    monkeypatch.setattr(prov, "_native_probe", lambda: native.probe(root))
    frag = prov.enumerate()
    unhealthy = [ch.device_index for ch in frag.chips if not ch.healthy]
    assert unhealthy == [2]
    assert prov.healthy_device_indices() == [0, 1, 3]


def _refs(frag):
    from kubegpu_tpu.types.info import ChipRef

    return [
        ChipRef(host=frag.node_name, chip_id=c.chip_id, coords=c.coords,
                device_index=c.device_index)
        for c in frag.chips
    ]
