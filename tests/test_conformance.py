"""Extender-contract conformance through the REAL deploy config.

A FakeKubeScheduler (kubegpu_tpu.testing) parses the production
``deploy/scheduler-config.yaml`` — the exact KubeSchedulerConfiguration a
real kube-scheduler mounts via --config — and drives a live ExtenderServer
with kube-scheduler's genuine wire shapes: managedResources gating,
NodeNames-only args (nodeCacheCapable), weighted HostPriorityList,
delegated bind, and the advisory preemption verb with scheduler-performed
evictions.  The highest-fidelity off-cluster check of SURVEY.md §3.1 this
harness can run (VERDICT r2 missing #4)."""

import os

import pytest

from kubegpu_tpu.plugins import Advertiser, FakeSlice
from kubegpu_tpu.scheduler import ExtenderServer, Scheduler
from kubegpu_tpu.testing import FakeKubeScheduler, load_scheduler_config
from kubegpu_tpu.types import RES_TPU, annotations, is_contiguous_submesh
from kubegpu_tpu.utils import InMemoryApiServer
from kubegpu_tpu.utils.metrics import Metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIG = os.path.join(REPO, "deploy", "scheduler-config.yaml")


def make_pod(name, chips, group=None, size=1, priority=0):
    ann = {}
    if group:
        ann[annotations.POD_GROUP] = group
        ann[annotations.POD_GROUP_SIZE] = str(size)
    if priority:
        ann[annotations.POD_PRIORITY] = str(priority)
    return {
        "metadata": {
            "name": name, "namespace": "default", "uid": f"uid-{name}",
            "annotations": ann,
        },
        "spec": {
            "priority": priority,
            "containers": [
                {"name": "main", "resources": {"limits": {RES_TPU: str(chips)}}}
            ],
        },
    }


@pytest.fixture()
def cluster(tmp_path):
    """The deployed shape: the extender serves HTTPS (the config's
    enableHTTPS) and the fake kube-scheduler verifies it against the
    signing CA via tlsConfig — the production scheduler-config.yaml is
    consumed as-is, with only the cluster-local host and CA paths
    retargeted at the live server and freshly-minted cert."""
    pytest.importorskip("cryptography")  # optional TLS test dependency
    from kubegpu_tpu.testing.tlsutil import make_self_signed

    api = InMemoryApiServer()
    fs = FakeSlice(slice_id="s0", mesh_shape=(4, 4), host_block=(2, 2))
    for host, prov in fs.providers().items():
        Advertiser(prov, api).advertise_once()
    cert, key = make_self_signed(str(tmp_path))
    srv = ExtenderServer(
        Scheduler(api, metrics=Metrics()),
        listen=("127.0.0.1", 0),
        tls_cert=cert,
        tls_key=key,
    )
    srv.start()
    exts = load_scheduler_config(CONFIG)
    # the production file points at cluster DNS and in-cluster CA paths;
    # retarget ONLY those at the live server — every other knob (verbs,
    # weight, managedResources, nodeCacheCapable, timeout, enableHTTPS)
    # is used exactly as deployed
    for e in exts:
        assert e.enable_https, "deployed config must say enableHTTPS"
        e.url_prefix = f"https://{srv.address[0]}:{srv.address[1]}"
        e.tls_ca_file = cert
    ksched = FakeKubeScheduler(api, exts)
    yield api, srv, ksched
    srv.stop()


def test_config_file_carries_the_deployed_contract():
    exts = load_scheduler_config(CONFIG)
    assert len(exts) == 1
    e = exts[0]
    assert (e.filter_verb, e.prioritize_verb, e.bind_verb, e.preempt_verb) == (
        "filter", "prioritize", "bind", "preemption"
    )
    assert e.managed_resources == [RES_TPU]
    assert e.ignored_resources == [RES_TPU]
    assert e.node_cache_capable is True
    assert e.weight == 10
    assert e.http_timeout_s == 10.0
    assert e.enable_https is True
    assert e.tls_ca_file.endswith("ca.crt")


def test_passthrough_pod_never_touches_extender(cluster):
    """BASELINE config 1 via managedResources gating: a pod with no TPU
    request is bound by the scheduler itself — zero extender calls."""
    api, srv, ksched = cluster
    api.create_pod(make_pod("web", 0))
    bound = ksched.run_until_settled()
    assert "default/web" in bound
    assert ksched.extender_calls == []
    assert api.get_pod("default", "web")["spec"]["nodeName"]


def test_chip_pods_flow_filter_prioritize_bind(cluster):
    """Configs 2-3: TPU pods go through the extender's verbs in order and
    come out bound with an assignment annotation and contiguous chips."""
    api, srv, ksched = cluster
    api.create_pod(make_pod("one", 1))
    api.create_pod(make_pod("quad", 4))
    bound = ksched.run_until_settled()
    assert set(bound) == {"default/one", "default/quad"}
    for name in ("one", "quad"):
        verbs = [v for v, p in ksched.extender_calls if p == name]
        assert verbs == ["filter", "prioritize", "bind"], verbs
        stored = api.get_pod("default", name)
        a = annotations.assignment_from_pod(stored)
        assert a is not None and stored["spec"]["nodeName"] == a.node
    quad = annotations.assignment_from_pod(api.get_pod("default", "quad"))
    assert is_contiguous_submesh({c.coords for c in quad.all_chips()}, (4, 4))


def test_gang_schedules_whole_through_conformance_loop(cluster):
    """Config 4: the 4-pod DP gang lands whole, ICI-contiguous, entirely
    through the one-pod-at-a-time extender flow the real scheduler runs."""
    api, srv, ksched = cluster
    for i in range(4):
        api.create_pod(make_pod(f"g{i}", 1, group="dp", size=4))
    bound = ksched.run_until_settled()
    assert len(bound) == 4
    coords = set()
    for i in range(4):
        a = annotations.assignment_from_pod(api.get_pod("default", f"g{i}"))
        coords.update(c.coords for c in a.all_chips())
    assert len(coords) == 4
    assert is_contiguous_submesh(coords, (4, 4))


def test_active_preemption_admits_vip_without_scheduler_help(cluster):
    """Default mode: the extender evicts lower-priority victims inside its
    own filter and admits the VIP in one cycle — the scheduler never needs
    the preemption verb."""
    api, srv, ksched = cluster
    for i in range(4):
        api.create_pod(make_pod(f"low{i}", 4, priority=1))
    assert len(ksched.run_until_settled()) == 4
    api.create_pod(make_pod("vip", 4, priority=9))
    bound = ksched.run_until_settled()
    assert "default/vip" in bound
    assert ("preemption", "vip") not in ksched.extender_calls
    survivors = {p["metadata"]["name"] for p in api.list_pods()}
    assert len([s for s in survivors if s.startswith("low")]) == 3


def test_preemption_verb_evicts_and_admits_high_priority():
    """Config 5 in the ADVISORY division of labor (active_preemption off —
    what the config's preemptVerb exists for): filter reports zero
    feasible nodes, the scheduler calls the preemption verb, performs the
    nominated evictions itself (upstream semantics), and admits the
    high-priority pod on the freed chips next pass."""
    api = InMemoryApiServer()
    fs = FakeSlice(slice_id="s0", mesh_shape=(4, 4), host_block=(2, 2))
    for host, prov in fs.providers().items():
        Advertiser(prov, api).advertise_once()
    srv = ExtenderServer(
        Scheduler(api, metrics=Metrics(), active_preemption=False),
        listen=("127.0.0.1", 0),
    )
    srv.start()
    try:
        exts = load_scheduler_config(CONFIG)
        for e in exts:
            e.url_prefix = f"http://{srv.address[0]}:{srv.address[1]}"
        ksched = FakeKubeScheduler(api, exts)
        for i in range(4):
            api.create_pod(make_pod(f"low{i}", 4, priority=1))
        assert len(ksched.run_until_settled()) == 4

        api.create_pod(make_pod("vip", 4, priority=9))
        # settle time: the eviction lands in the extender's cache via its
        # pod watch (event-driven), then the next pass admits the vip
        bound = ksched.run_until_settled(settle_s=0.3)
        assert "default/vip" in bound
        assert ("preemption", "vip") in ksched.extender_calls
        vip = annotations.assignment_from_pod(api.get_pod("default", "vip"))
        assert vip is not None and len(vip.all_chips()) == 4
        survivors = {p["metadata"]["name"] for p in api.list_pods()}
        assert "vip" in survivors
        assert len([s for s in survivors if s.startswith("low")]) == 3
    finally:
        srv.stop()


def test_bearer_token_gates_privileged_verbs(tmp_path):
    """Optional authn hardening: with --auth-token-file, /bind and
    /preemption refuse 401 without the bearer token and work with it,
    while /filter and /prioritize (read-only advice) stay open — all over
    HTTPS, driven through the conformance client."""
    import json as _json
    import ssl
    import urllib.error
    import urllib.request

    pytest.importorskip("cryptography")  # optional TLS test dependency
    from kubegpu_tpu.testing import ExtenderConfig
    from kubegpu_tpu.testing.tlsutil import make_self_signed

    api = InMemoryApiServer()
    fs = FakeSlice(slice_id="s0", mesh_shape=(4, 4), host_block=(2, 2))
    for host, prov in fs.providers().items():
        Advertiser(prov, api).advertise_once()
    cert, key = make_self_signed(str(tmp_path))
    token_file = tmp_path / "token"
    token_file.write_text("sekret\n")
    srv = ExtenderServer(
        Scheduler(api, metrics=Metrics()),
        listen=("127.0.0.1", 0),
        tls_cert=cert,
        tls_key=key,
        auth_token="sekret",
    )
    srv.start()
    try:
        base = f"https://{srv.address[0]}:{srv.address[1]}"
        ctx = ssl.create_default_context(cafile=cert)
        pod = make_pod("p0", 1)
        api.create_pod(pod)
        nodes = sorted(n["metadata"]["name"] for n in api.list_nodes())

        def raw_post(path, payload, auth=None):
            headers = {"Content-Type": "application/json"}
            if auth:
                headers["Authorization"] = auth
            req = urllib.request.Request(
                base + path, data=_json.dumps(payload).encode(), headers=headers
            )
            with urllib.request.urlopen(req, timeout=10, context=ctx) as r:
                return r.status, _json.loads(r.read())

        # read-only advice stays open without a token
        code, body = raw_post("/filter", {"Pod": pod, "NodeNames": nodes})
        assert code == 200 and body["NodeNames"]
        target = body["NodeNames"][0]
        # privileged verbs 401 without the token...
        with pytest.raises(urllib.error.HTTPError) as ei:
            raw_post("/bind", {"PodNamespace": "default", "PodName": "p0",
                               "Node": target})
        assert ei.value.code == 401
        with pytest.raises(urllib.error.HTTPError) as ei:
            raw_post("/preemption", {"Pod": pod})
        assert ei.value.code == 401
        # ...and a wrong token is refused too
        with pytest.raises(urllib.error.HTTPError) as ei:
            raw_post("/bind", {"PodNamespace": "default", "PodName": "p0",
                               "Node": target}, auth="Bearer wrong")
        assert ei.value.code == 401
        # the conformance client with auth_token_file set binds fine
        ext = ExtenderConfig(
            url_prefix=base, filter_verb="filter", prioritize_verb="prioritize",
            bind_verb="bind", preempt_verb="preemption", weight=1,
            node_cache_capable=True, managed_resources=[RES_TPU],
            tls_ca_file=cert, auth_token_file=str(token_file),
        )
        ksched = FakeKubeScheduler(api, [ext])
        bound = ksched.run_until_settled()
        assert bound == {"default/p0": bound["default/p0"]}
        assert api.get_pod("default", "p0")["spec"]["nodeName"]
    finally:
        srv.stop()
