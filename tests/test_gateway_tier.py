"""Gateway tier (ISSUE 12): N-gateway scale-out without a SPOF.

Layers under test:

1. the consistent-hash ring — stability and bounded key movement under
   join/leave (the property that makes membership churn cheap);
2. ``ConsistentHashRouter`` — two gateway instances route every session
   identically with zero shared state, and mispinned sessions restore
   their KV instead of cold-prefilling;
3. ``StreamRelay`` — token-prefix dedup: each token index delivered
   exactly once whichever attempt (primary, hedge twin, sibling-retry
   continuation) supplies it;
4. ``GatewayTier`` — a gateway crash mid-stream is survivable: the
   client retries on a sibling, the stream resumes at the watermark,
   nothing is lost or double-served, and the span trees all close;
5. the shared workload harness — deterministic scenario mix, follow
   turns materialized from parents' results;
6. GatewaySoak's multi-gateway chaos lane, in-memory and HTTP.
"""

import threading
import time

import pytest

from kubegpu_tpu.gateway import (
    ConsistentHashRing,
    ConsistentHashRouter,
    FailoverPolicy,
    GatewayRequest,
    GatewayTier,
    InMemoryReplicaClient,
    SessionKVStore,
    SimBatcher,
    StreamRelay,
)
from kubegpu_tpu.testing.fake_serving import build_fake_serving_stack
from kubegpu_tpu.utils.metrics import Metrics


def _wait(predicate, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# 1. consistent-hash ring properties
# ---------------------------------------------------------------------------

def test_ring_stability_and_bounded_movement():
    """The failover story in two properties: removing a node moves ONLY
    the keys it owned; adding a node steals a bounded fraction and
    nothing else moves anywhere but onto the new node."""
    nodes = [f"n{i}" for i in range(5)]
    ring = ConsistentHashRing(nodes)
    keys = [f"session-{i}" for i in range(1000)]
    before = {k: ring.lookup(k) for k in keys}
    owned = [k for k, n in before.items() if n == "n2"]
    assert owned, "n2 owns nothing — vnode spread is broken"

    ring.rebuild([n for n in nodes if n != "n2"])
    after_leave = {k: ring.lookup(k) for k in keys}
    moved = [k for k in keys if before[k] != after_leave[k]]
    assert sorted(moved) == sorted(owned), (
        "a leave moved keys its owner never held"
    )

    ring.rebuild(nodes + ["n5"])
    after_join = {k: ring.lookup(k) for k in keys}
    for k in keys:
        if before[k] != after_join[k]:
            assert after_join[k] == "n5", (
                f"join moved {k} to {after_join[k]}, not the joiner"
            )
    stolen = sum(1 for k in keys if after_join[k] == "n5")
    # expectation is len(keys)/6 ≈ 167; allow generous vnode variance
    assert 0 < stolen < len(keys) / 2, stolen


def test_ring_exclude_walks_clockwise_and_preference_order():
    ring = ConsistentHashRing(["a", "b", "c"])
    key = "some-session"
    order = ring.preference(key)
    assert sorted(order) == ["a", "b", "c"]
    assert ring.lookup(key) == order[0]
    assert ring.lookup(key, exclude=frozenset({order[0]})) == order[1]
    assert ring.lookup(
        key, exclude=frozenset({order[0], order[1]})
    ) == order[2]
    assert ring.lookup(key, exclude=frozenset(order)) is None
    # determinism across instances (the cross-gateway agreement)
    assert ConsistentHashRing(["c", "a", "b"]).preference(key) == order


def test_ring_empty_and_vnode_validation():
    assert ConsistentHashRing([]).lookup("x") is None
    assert ConsistentHashRing([]).preference("x") == []
    with pytest.raises(ValueError):
        ConsistentHashRing(["a"], vnodes=0)


# ---------------------------------------------------------------------------
# 2. ConsistentHashRouter
# ---------------------------------------------------------------------------

class _Req:
    def __init__(self, session=None):
        self.session = session


def _replicas(stack):
    stack.registry.refresh()
    return stack.registry.routable()


def test_consistent_hash_router_agrees_across_instances():
    """Two routers (two gateways) with no shared state route every
    session identically — and the exclude set walks both to the SAME
    next replica."""
    stack = build_fake_serving_stack(4)
    replicas = _replicas(stack)
    r1, r2 = ConsistentHashRouter(), ConsistentHashRouter()
    for i in range(50):
        s = f"sess{i}"
        a = r1.pick(_Req(s), replicas, {})
        b = r2.pick(_Req(s), replicas, {})
        assert a is not None and a.key == b.key
        ex = frozenset({a.key})
        a2 = r1.pick(_Req(s), replicas, {}, ex)
        b2 = r2.pick(_Req(s), replicas, {}, ex)
        assert a2.key == b2.key != a.key


def test_consistent_hash_router_sessionless_falls_back_by_load():
    stack = build_fake_serving_stack(3)
    replicas = _replicas(stack)
    router = ConsistentHashRouter()
    outstanding = {replicas[0].key: 5, replicas[1].key: 0,
                   replicas[2].key: 3}
    pick = router.pick(_Req(None), replicas, outstanding)
    assert pick.key == replicas[1].key


def test_consistent_hash_router_counts_movement_as_repin():
    stack = build_fake_serving_stack(4)
    replicas = _replicas(stack)
    m = Metrics()
    router = ConsistentHashRouter(metrics=m)
    # find a session owned by a specific replica, then remove that
    # replica from the candidate list: the ring MUST move the session
    # (counted), and re-offering the full list moves it back (counted)
    session = next(
        f"s{i}" for i in range(200)
        if router.pick(_Req(f"s{i}"), replicas, {}).key == replicas[0].key
    )
    m2 = Metrics()
    router = ConsistentHashRouter(metrics=m2)
    assert router.pick(_Req(session), replicas, {}).key == replicas[0].key
    shrunk = [r for r in replicas if r.key != replicas[0].key]
    moved = router.pick(_Req(session), shrunk, {})
    assert moved.key != replicas[0].key
    assert m2.get("gateway_session_repin_total") == 1


def test_mispinned_session_restores_before_dispatch():
    """The tier's 'any gateway can route any session' guarantee: a
    session whose KV home differs from the routed target — even with
    the home ALIVE (ring moved it) — gets its sealed export imported
    into the target before the attempt opens."""

    class _FakeClient:
        def __init__(self):
            self.imports = []

        def import_sealed(self, key, payload):
            self.imports.append((key, payload["blob"]))
            return True

    store = SessionKVStore()
    client = _FakeClient()
    store.record("sess", "replica-A", [1, 2, 3])
    assert store.set_payload("sess", {"blob": "kv"})
    req = _Req("sess")
    # dispatch to the home: no-op
    assert not store.restore_for(req, "replica-A", client)
    # dispatch elsewhere (mispin): restore fires and re-homes
    assert store.restore_for(req, "replica-B", client)
    assert client.imports == [("replica-B", "kv")]
    assert store.entry("sess")["replica"] == "replica-B"


# ---------------------------------------------------------------------------
# 3. StreamRelay dedup
# ---------------------------------------------------------------------------

class _Attempt:
    def __init__(self, base=0):
        self.stream_base = base


def test_stream_relay_dedups_overlapping_twin_streams():
    m = Metrics()
    relay = StreamRelay(m, dedup=True)
    primary, hedge = _Attempt(0), _Attempt(3)
    relay.on_tokens(primary, [10, 11, 12])          # abs 0..2
    relay.on_tokens(hedge, [13, 14])                # abs 3..4 (fast-fwd)
    relay.on_tokens(primary, [13, 14, 15])          # abs 3..5: 13,14 dup
    relay.on_tokens(hedge, [15, 16])                # abs 5..6: 15 dup
    assert relay.drain() == [10, 11, 12, 13, 14, 15, 16]
    assert relay.emitted() == 7
    assert m.get("gateway_stream_dedup_tokens_total") == 3


def test_stream_relay_pin_mode_for_sampled_streams():
    relay = StreamRelay(dedup=False)
    a, b = _Attempt(), _Attempt()
    relay.on_tokens(a, [1, 2])
    relay.on_tokens(b, [9, 9])      # a different sampled stream: dropped
    relay.on_tokens(a, [3])
    assert relay.drain() == [1, 2, 3]


# ---------------------------------------------------------------------------
# 4. GatewayTier
# ---------------------------------------------------------------------------

def _build_tier(n_replicas=3, n_gateways=2, step_delay_s=0.001,
                metrics=None):
    stack = build_fake_serving_stack(n_replicas)
    client = InMemoryReplicaClient(
        batcher_factory=lambda key: SimBatcher(slots=8),
        step_delay_s=step_delay_s,
    )
    stack.registry.subscribe(client.sync_live)
    tier = GatewayTier(
        stack.registry, client, n_gateways=n_gateways,
        metrics=metrics or Metrics(),
        policy=FailoverPolicy(
            deadline_s=30.0, hedge_after_s=0.05, max_attempts=6,
            retry_budget_ratio=1.0, budget_floor=100,
        ),
    )
    stack.registry.refresh()
    tier.start()
    return stack, client, tier


def test_tier_any_gateway_routes_a_session_to_the_same_replica():
    stack, client, tier = _build_tier(n_replicas=4, n_gateways=3)
    try:
        homes = set()
        for i, gid in enumerate(sorted(tier.gateways)):
            _, p = tier.submit(GatewayRequest(
                prompt=[1, 2, 3], max_new_tokens=4,
                request_id=f"r-{gid}-{i}", session="shared-session",
            ), via=gid)
            assert p.wait(20) and p.result().status == "ok", p.result()
            homes.add(p.result().replica)
        assert len(homes) == 1, (
            f"the same session landed on {sorted(homes)} via different "
            "gateways — the consistent-hash agreement is broken"
        )
    finally:
        tier.stop()
        client.stop()


def test_tier_death_mid_stream_sibling_resumes_exactly_once():
    """The acceptance flow: a greedy stream's home gateway is killed
    while tokens flow; the client retries the SAME request_id on the
    sibling with the relay's watermark.  The caller's stream is the
    full token list exactly once, and the final result matches it."""
    metrics = Metrics()
    stack, client, tier = _build_tier(
        n_replicas=3, n_gateways=2, step_delay_s=0.004, metrics=metrics,
    )
    try:
        relay = StreamRelay(metrics, dedup=True)
        request = GatewayRequest(
            prompt=[7, 8, 9], max_new_tokens=40, request_id="mig",
            session="sess-f",
        )
        request.on_tokens = relay.on_tokens
        request.stream_watermark = relay.emitted
        request.no_hedge = False
        gid, pending = tier.submit(request)
        _wait(lambda: relay.emitted() >= 3, msg="first streamed tokens")
        tier.kill(gid)
        assert pending.wait(20), "dead gateway never resolved the handle"
        first = pending.result()
        assert first.status == "error", first
        # the client contract: retry on the sibling (clone carries the
        # relay + watermark)
        clone = GatewayTier._clone(request)
        gid2, pending2 = tier.submit(clone)
        assert gid2 != gid
        assert pending2.wait(30) and pending2.result().status == "ok", (
            pending2.result()
        )
        result = pending2.result()
        assert len(result.tokens) == 40
        # drain any late deltas, then judge: exactly once, no gaps
        time.sleep(0.05)
        delivered = relay.drain()
        assert delivered == result.tokens, (
            f"stream across the failover delivered {len(delivered)} "
            f"tokens vs result {len(result.tokens)}"
        )
        assert metrics.get("gateway_tier_deaths_total") == 1
        # no double-serve: the replica-side duplicate-id eviction means
        # at most one decode DELIVERY credited per request id
        assert client.decodes.get("mig", 0) >= 1
    finally:
        tier.stop()
        client.stop()


def test_tier_submit_and_wait_retries_on_dead_gateway():
    metrics = Metrics()
    stack, client, tier = _build_tier(
        n_replicas=3, n_gateways=3, step_delay_s=0.004, metrics=metrics,
    )
    try:
        request = GatewayRequest(
            prompt=[2, 4, 6], max_new_tokens=30, request_id="saw",
            session="sess-w",
        )
        gid = tier.gateway_for(request)
        box = {}

        def call():
            box["result"] = tier.submit_and_wait(request, timeout=30.0)

        t = threading.Thread(target=call, daemon=True)
        t.start()
        _wait(
            lambda: tier.gateways[gid].in_flight() > 0
            or "result" in box,
            msg="request in flight",
        )
        tier.kill(gid)
        t.join(30.0)
        assert not t.is_alive(), "submit_and_wait hung across the kill"
        result = box["result"]
        assert result.status == "ok", result
        assert len(result.tokens) == 30
        assert metrics.get("gateway_tier_retries_total") >= 1
        assert metrics.get("gateway_tier_deaths_total") == 1
    finally:
        tier.stop()
        client.stop()


def test_submit_racing_kill_resolves_retryable_not_rejected():
    """A submit that loses the race with kill() (admission queue already
    closed) must resolve with the RETRYABLE death error — surfacing it
    as 'rejected' would make the tier client hand the caller a spurious
    backpressure answer while a sibling sits idle."""
    from kubegpu_tpu.gateway import is_gateway_death

    stack, client, tier = _build_tier(n_replicas=2, n_gateways=2)
    try:
        gid = sorted(tier.gateways)[0]
        tier.kill(gid)
        _, p = tier.submit(GatewayRequest(
            prompt=[1], max_new_tokens=2, request_id="race",
        ), via=gid)
        assert p.wait(10)
        assert is_gateway_death(p.result(), tier.gateways[gid]), p.result()
        # the client contract then lands it on the sibling
        result = tier.submit_and_wait(GatewayRequest(
            prompt=[1], max_new_tokens=2, request_id="race2",
        ), timeout=20.0)
        assert result.status == "ok", result
    finally:
        tier.stop()
        client.stop()


def test_tier_revive_replaces_the_corpse_and_serves_again():
    stack, client, tier = _build_tier(n_replicas=2, n_gateways=2)
    try:
        gid = sorted(tier.gateways)[0]
        tier.kill(gid)
        assert tier.alive_ids() == [sorted(tier.gateways)[1]]
        tier.revive(gid)
        assert sorted(tier.alive_ids()) == sorted(tier.gateways)
        _, p = tier.submit(GatewayRequest(
            prompt=[5], max_new_tokens=3, request_id="post-revive",
        ), via=gid)
        assert p.wait(20) and p.result().status == "ok", p.result()
    finally:
        tier.stop()
        client.stop()


def test_hedged_greedy_stream_beats_straggler_and_dedups():
    """A straggling primary provokes a hedge; the twin's stream (fast-
    forwarded past the watermark) completes the caller's stream — each
    token exactly once, and the hedge was COUNTED as a streaming
    hedge."""
    metrics = Metrics()
    stack, client, tier = _build_tier(
        n_replicas=2, n_gateways=1, metrics=metrics,
    )
    try:
        keys = [r.key for r in stack.registry.routable()]
        relay = StreamRelay(metrics, dedup=True)
        request = GatewayRequest(
            prompt=[3, 1, 4], max_new_tokens=24, request_id="hst",
        )
        request.on_tokens = relay.on_tokens
        request.stream_watermark = relay.emitted
        request.no_hedge = False
        # whichever replica takes the primary, it straggles: slow BOTH
        # down asymmetrically after routing is load-based... simpler:
        # slow one replica hard; if the primary lands there the hedge
        # rescues TTLT, if not the request just finishes fast — so pin
        # the outcome by slowing the one the router will pick first
        # (deterministic: least-outstanding breaks ties by name)
        client.set_step_delay(sorted(keys)[0], 0.2)
        _, pending = tier.submit(request)
        assert pending.wait(30) and pending.result().status == "ok", (
            pending.result()
        )
        result = pending.result()
        time.sleep(0.05)
        delivered = relay.drain()
        assert delivered == result.tokens
        assert metrics.get("gateway_hedges_total") >= 1
        assert metrics.get("gateway_stream_hedges_total") >= 1
    finally:
        tier.stop()
        client.stop()


# ---------------------------------------------------------------------------
# 5. workload harness
# ---------------------------------------------------------------------------

def test_workload_generator_deterministic_scenario_mix():
    from kubegpu_tpu.testing.workload import WorkloadGenerator

    a = WorkloadGenerator(seed=3, prompt_cap=12).generate(200)
    b = WorkloadGenerator(seed=3, prompt_cap=12).generate(200)
    assert [(i.request_id, i.offset_s, i.prompt, i.scenario)
            for i in a] == \
           [(i.request_id, i.offset_s, i.prompt, i.scenario)
            for i in b]
    scenarios = {i.scenario for i in a}
    assert scenarios == {"burst", "agent", "rag", "bestofn"}
    offsets = [i.offset_s for i in a]
    assert offsets == sorted(offsets)
    ids = [i.request_id for i in a]
    assert len(ids) == len(set(ids))
    by_id = {i.request_id: i for i in a}
    for item in a:
        assert len(item.prompt) <= 12
        if item.follow_of is not None:
            assert item.scenario == "agent" and item.salt
            parent = by_id.get(item.follow_of)
            # parents precede children in arrival order (ids are
            # allocation-ordered; a missing parent means the list was
            # truncated mid-chain, which generate() never does)
            assert parent is not None
            assert parent.session == item.session
        if item.scenario == "rag":
            assert len(item.prompt) == 12
    groups = {}
    for item in a:
        if item.fanout_of:
            groups.setdefault(item.fanout_of, []).append(item)
    assert groups, "no best-of-n groups generated"
    for members in groups.values():
        assert len({tuple(m.prompt) for m in members}) == 1
        assert len({m.request_id for m in members}) == len(members)


def test_workload_stream_gates_follows_on_parent_results():
    from kubegpu_tpu.testing.workload import (
        WorkloadGenerator, WorkloadStream, materialize_follow,
    )

    class _R:
        def __init__(self, status, tokens=()):
            self.status = status
            self.tokens = list(tokens)

    gen = WorkloadGenerator(seed=11, prompt_cap=10,
                            mix={"agent": 1})
    items = gen.generate(8)
    stream = WorkloadStream(items, prompt_cap=10)
    results = {}
    handed = {}
    # first drain: only opening turns come out
    for item, prompt in stream.next_ready(50, results):
        assert item.follow_of is None
        handed[item.request_id] = (item, prompt)
    assert stream.pending_follows() > 0
    # complete one parent: exactly its children unblock, with the
    # documented materialization
    rid, (item, prompt) = next(iter(handed.items()))
    results[rid] = _R("ok", [41, 42, 43])
    ready = stream.next_ready(50, results)
    for child, child_prompt in ready:
        assert child.follow_of == rid
        assert child_prompt == materialize_follow(
            prompt, [41, 42, 43], child.salt, 10
        )
        assert len(child_prompt) <= 10
    # a FAILED parent ends its conversation: the turn is dropped
    rid2 = next(r for r in handed if r != rid)
    results[rid2] = _R("error")
    before = stream.pending_follows()
    stream.next_ready(50, results)
    assert stream.pending_follows() < before or before == 0


# ---------------------------------------------------------------------------
# 6. the multi-gateway chaos lanes
# ---------------------------------------------------------------------------

def test_gateway_soak_tier_inmemory_lane():
    """Tier-wide I5 under combined gateway+replica chaos: gateway
    kills mid-everything, hedged greedy streams, mid-stream gateway
    failovers, replica kills/stragglers — every request's final handle
    ok/rejected, every ok stream delivered exactly once."""
    from kubegpu_tpu.testing.soak import GatewaySoak

    soak = GatewaySoak(seed=101, n_replicas=4, gateways=3)
    soak.run(60)
    assert soak._streams, "the schedule never exercised a stream"


def test_gateway_soak_tier_http_lane():
    """The same tier chaos ACROSS THE WIRE: SimBatcher replicas behind
    real loopback ReplicaServers, gateway kills cancel their streams
    wire-level, sibling retries meet the replica-side duplicate-id
    eviction."""
    from kubegpu_tpu.testing.soak import GatewaySoak

    soak = GatewaySoak(seed=103, n_replicas=4, gateways=2, http=True)
    soak.run(45)


@pytest.mark.slow
def test_gateway_soak_tier_paged_kill_schedule():
    """The acceptance schedule with REAL paged batchers: 2 gateways ×
    2 replicas (speculation + fp32 decode-page sealing + migration
    verbs), gateway kills, mispinned sessions (ring movement under
    replica churn), hedged streams and mid-stream failovers — at
    quiescence ``assert_page_accounting`` balances on every replica
    and I5 holds tier-wide."""
    import jax
    import jax.numpy as jnp

    from kubegpu_tpu.models import TransformerLM
    from kubegpu_tpu.models.paging import PagedContinuousBatcher
    from kubegpu_tpu.testing.soak import GatewaySoak

    tiny = dict(vocab_size=61, num_layers=1, num_heads=2, hidden=16,
                max_seq=32)
    params = TransformerLM(dtype=jnp.float32, **tiny).init(
        jax.random.PRNGKey(1), jnp.ones((1, 4), jnp.int32)
    )["params"]
    soak = GatewaySoak(
        seed=107, n_replicas=2, gateways=2, multiturn=True,
        follow_prompt_cap=12, migration=True,
        batcher_factory=lambda key: PagedContinuousBatcher(
            params, slots=4, prompt_pad=12, page_size=4, pool_pages=48,
            station_slots=2, token_budget=8, dtype=jnp.float32,
            decode_page_cache="fp32",
            draft_params=params, speculate_k=2, draft_window=16,
            draft_num_layers=tiny["num_layers"],
            draft_num_heads=tiny["num_heads"],
            draft_hidden=tiny["hidden"], **tiny,
        ),
    )
    soak.run(steps=20)
