"""Worker launch path for every workload family (SURVEY §2.2 / §3.4).

The samples' pod commands must actually train: each --model mode is run
in-process on the 8-device CPU mesh (tiny dims) and must print the
FIRST_STEP_DONE line the e2e latency probe greps for, with a finite loss.
"""

import math
import re

import pytest

from kubegpu_tpu.models import worker

pytestmark = pytest.mark.slow  # JAX compile-heavy; run with -m slow

TINY = [
    "--steps", "2", "--batch-per-chip", "2",
    "--vocab", "128", "--layers", "1", "--heads", "8",
    "--hidden", "32", "--seq", "64", "--data-pool", "2",
]


def run_worker(capsys, argv):
    rc = worker.main(argv + TINY)
    out = capsys.readouterr().out
    assert rc == 0
    m = re.search(r"FIRST_STEP_DONE seconds=\S+ loss=(\S+)", out)
    assert m, out
    assert math.isfinite(float(m.group(1))), out
    return out


@pytest.mark.parametrize(
    "argv",
    [
        pytest.param(["--model", "resnet-tiny"],
                     marks=pytest.mark.exhaustive),
        ["--model", "lm", "--tp", "4"],
        ["--model", "lm-cp", "--cp", "4", "--attn-impl", "ring"],
        ["--model", "lm-cp", "--cp", "4", "--attn-impl", "ulysses"],
        ["--model", "moe", "--ep", "4"],
        pytest.param(["--model", "moe", "--ep", "2", "--tp", "2"],
                     marks=pytest.mark.exhaustive),
        pytest.param(["--model", "pp", "--microbatches", "2"],
                     marks=pytest.mark.exhaustive),
        pytest.param(["--model", "pp", "--pp-rounds", "2",
                      "--microbatches", "8"],
                     marks=pytest.mark.exhaustive),
    ],
    ids=["resnet-tiny", "lm-tp", "lm-cp-ring", "lm-cp-ulysses", "moe",
         "moe-ep-tp", "pp", "pp-circular"],
)
def test_worker_mode_trains(capsys, argv):
    out = run_worker(capsys, argv)
    if argv[1].startswith("lm") or argv[1] in ("moe", "pp"):
        assert "tokens_per_sec" in out
    else:
        assert "images_per_sec" in out


def test_worker_rejects_indivisible_split():
    with pytest.raises(SystemExit):
        worker.main(["--model", "lm", "--tp", "3"] + TINY)


def test_worker_resident_mode_runs_constant_batch(capsys):
    run_worker(capsys, ["--model", "lm", "--tp", "4", "--data", "resident"])


@pytest.mark.parametrize(
    "argv",
    [
        pytest.param(["--model", "resnet-tiny"],
                     marks=pytest.mark.exhaustive),
        ["--model", "lm", "--tp", "4"],
    ],
    ids=["resnet-tiny", "lm-tp"],
)
def test_worker_checkpoint_resume(capsys, tmp_path, argv):
    """The pod-restart story at the worker surface: a second invocation
    with the same --ckpt-dir restores the saved step and says so on
    stdout (the line a human/probe greps for)."""
    ck = ["--ckpt-dir", str(tmp_path), "--ckpt-every", "2"]
    out1 = run_worker(capsys, argv + ck)
    assert "CHECKPOINT_SAVED step=2" in out1
    assert "RESUMED" not in out1
    out2 = run_worker(capsys, argv + ck)
    assert "RESUMED step=2" in out2
    assert "CHECKPOINT_SAVED step=4" in out2


def test_mesh_token_source_seeds_per_data_shard():
    """Single-process view of the gang data contract: shards draw disjoint
    streams, and the rows for a given shard do not depend on how many
    shards this process generates."""
    import numpy as np

    from kubegpu_tpu.models.data import synthetic_token_batches_for_mesh
    from kubegpu_tpu.parallel import device_mesh

    mesh_dp = device_mesh({"data": 4, "model": 2})
    full = next(synthetic_token_batches_for_mesh(8, 16, 97, mesh_dp))
    assert full.shape == (8, 16)
    shards = full.reshape(4, 2, 16)
    for i in range(4):
        for j in range(i + 1, 4):
            assert not np.array_equal(shards[i], shards[j])

    # a pure-TP mesh (dp=1) must reproduce shard 0's stream exactly
    mesh_tp = device_mesh({"data": 1, "model": 8})
    rep = next(synthetic_token_batches_for_mesh(2, 16, 97, mesh_tp))
    np.testing.assert_array_equal(rep, shards[0])


@pytest.mark.exhaustive
def test_train_then_serve_decode_restores_checkpoint(capsys, tmp_path):
    """The training->serving handoff at the CLI surface: `--model lm`
    trains and checkpoints; `--model decode` restores that checkpoint
    (shared param contract) and serves KV-cached greedy decode."""
    # run_worker appends TINY (which wins in argparse), so the checkpoint
    # is written with TINY's shapes — the decode call must match them
    run_worker(capsys, [
        "--model", "lm", "--tp", "4",
        "--ckpt-dir", str(tmp_path), "--ckpt-every", "2",
    ])
    rc = worker.main([
        "--model", "decode", "--steps", "8", "--batch-per-chip", "2",
        "--vocab", "128", "--layers", "1", "--heads", "8", "--hidden", "32",
        "--seq", "64", "--prompt-len", "4", "--ckpt-dir", str(tmp_path),
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "RESTORED_FOR_SERVING step=2" in out
    assert "DECODE_DONE tokens_per_sec=" in out


def test_decode_mode_serves_fresh_weights_without_ckpt(capsys):
    rc = worker.main([
        "--model", "decode", "--steps", "4", "--batch-per-chip", "2",
        "--vocab", "64", "--layers", "1", "--heads", "2", "--hidden", "16",
        "--seq", "16", "--prompt-len", "4",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "DECODE_DONE" in out and "RESTORED_FOR_SERVING" not in out


def test_decode_mode_serves_int8(capsys):
    rc = worker.main([
        "--model", "decode", "--steps", "4", "--batch-per-chip", "2",
        "--vocab", "64", "--layers", "1", "--heads", "2", "--hidden", "16",
        "--seq", "16", "--prompt-len", "4", "--int8",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "SERVING_INT8" in out and "DECODE_DONE" in out


def test_decode_rejects_oversized_request():
    with pytest.raises(SystemExit):
        worker.main([
            "--model", "decode", "--steps", "64", "--seq", "16",
            "--prompt-len", "4", "--vocab", "64", "--layers", "1",
            "--heads", "2", "--hidden", "16",
        ])


@pytest.mark.parametrize("serving", ["continuous", "paged", "speculative"])
def test_decode_mode_serves_batched_strategies(capsys, serving):
    """--serving continuous|paged: the slot batchers behind the worker CLI
    serve a mixed wave and report throughput/steps/admits."""
    rc = worker.main([
        "--model", "decode", "--steps", "4", "--batch-per-chip", "2",
        "--vocab", "64", "--layers", "1", "--heads", "2", "--hidden", "16",
        "--seq", "16", "--prompt-len", "4", "--serving", serving,
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert f"serving={serving}" in out and "DECODE_DONE" in out
    assert "admits=4" in out  # 2 slots x 2 = 4 requests through the wave
