"""ops/attention numerics: flash kernel and ring attention against the
einsum oracle.  Runs on the 8-device virtual CPU mesh (conftest); the flash
kernel runs in pallas interpret mode off-TPU by design."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubegpu_tpu.ops import (
    flash_attention,
    reference_attention,
    ring_attention_sharded,
)

pytestmark = pytest.mark.slow  # JAX compile-heavy; run with -m slow


def qkv(b=2, s=128, h=2, d=32, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_reference(causal):
    q, k, v = qkv()
    out = flash_attention(q, k, v, causal, 32, 32)
    ref = reference_attention(q, k, v, causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_single_block():
    q, k, v = qkv(s=64)
    out = flash_attention(q, k, v, True, 64, 64)
    ref = reference_attention(q, k, v, True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_pads_uneven_lengths(causal):
    q, k, v = qkv(s=100)  # not a multiple of the 32-blocks
    out = flash_attention(q, k, v, causal, 32, 32)
    ref = reference_attention(q, k, v, causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_causal_rejects_mismatched_lengths():
    q, _, _ = qkv(s=64)
    _, k, v = qkv(s=128)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, True, 32, 32)


def test_flash_bf16_close_to_fp32_oracle():
    q, k, v = qkv(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, True, 32, 32)
    ref = reference_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32), True
    )
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(out.astype(np.float32), ref, atol=3e-2, rtol=3e-2)


def test_flash_gradients_match_reference():
    q, k, v = qkv(s=64)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, 32, 32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)


def test_flash_gradients_match_reference_noncausal_and_padded():
    # uneven lengths exercise the backward kernels' seq_q/seq_k masking
    # (padded rows/cols must contribute exactly zero gradient)
    q, _, _ = qkv(s=40)
    _, k, v = qkv(s=56)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, False, 32, 32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, False) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)


def test_flash_backward_is_linear_memory():
    # the residuals saved for backward must be O(seq): q,k,v,out (seq x d
    # each) + lse/delta (seq) — NOT the s x s score matrix.  Checked via
    # the jaxpr: no intermediate of shape (..., s, s) is saved or formed
    # outside the kernels.
    s = 256
    q, k, v = qkv(s=s, h=1, d=16)
    jaxpr = jax.make_jaxpr(
        jax.grad(lambda q, k, v: flash_attention(q, k, v, True, 64, 64).sum())
    )(q, k, v)
    for eqn in jaxpr.eqns:
        for var in eqn.outvars:
            shape = getattr(var.aval, "shape", ())
            assert not (
                len(shape) >= 2 and shape[-1] == s and shape[-2] == s
            ), f"O(s^2) intermediate {shape} in {eqn.primitive}"


def test_flash_under_jit_and_grad():
    q, k, v = qkv(s=64)
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, True, 32, 32).sum())
    g = jax.jit(jax.grad(lambda q, k, v: flash_attention(q, k, v, True, 32, 32).sum()))
    assert np.isfinite(float(f(q, k, v)))
    assert all(bool(jnp.all(jnp.isfinite(x))) for x in [g(q, k, v)])


# -- ring attention ---------------------------------------------------------

@pytest.fixture(scope="module")
def mesh():
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8])
    if devs.size < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    return Mesh(devs, ("sp",))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(mesh, causal):
    q, k, v = qkv(b=2, s=8 * 16, h=2, d=16)
    out = ring_attention_sharded(q, k, v, mesh, "sp", causal)
    ref = reference_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_attention_under_jit_with_sharded_inputs(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    q, k, v = qkv(b=1, s=8 * 8, h=2, d=16)
    sh = NamedSharding(mesh, P(None, "sp", None, None))
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))
    f = jax.jit(lambda q, k, v: ring_attention_sharded(q, k, v, mesh, "sp", True))
    out = f(q, k, v)
    ref = reference_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
    # output keeps the sequence sharding (no gather materialized)
    assert out.sharding.spec == P(None, "sp", None, None)


@pytest.mark.exhaustive
def test_ring_attention_grads_finite(mesh):
    q, k, v = qkv(b=1, s=8 * 8, h=2, d=16)

    def loss(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh, "sp", True) ** 2)

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    ref_grads = jax.grad(
        lambda q, k, v: jnp.sum(reference_attention(q, k, v, True) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


def _iter_eqns_outside_kernels(jaxpr):
    """Walk every equation including sub-jaxprs (scan/switch/custom_vjp
    bodies) but NOT pallas kernel bodies — block-shaped score tiles inside a
    kernel live in VMEM, not HBM, and are exactly what flash is for."""
    for eqn in jaxpr.eqns:
        yield eqn
        if "pallas" in eqn.primitive.name:
            continue
        stack = list(eqn.params.values())
        while stack:
            v = stack.pop()
            if isinstance(v, (list, tuple)):
                stack.extend(v)
            elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
                yield from _iter_eqns_outside_kernels(v.jaxpr)
            elif hasattr(v, "eqns"):
                yield from _iter_eqns_outside_kernels(v)


def _assert_no_quadratic_seq(jaxpr, s):
    for eqn in _iter_eqns_outside_kernels(jaxpr):
        for var in eqn.outvars:
            shape = getattr(var.aval, "shape", ())
            assert not (
                len(shape) >= 2 and shape[-1] == s and shape[-2] == s
            ), f"O(s^2) intermediate {shape} in {eqn.primitive}"


def test_ring_flash_linear_memory_in_seq(mesh):
    # the long-context claim: NO (s_loc, s_loc) or (s, s) array outside the
    # pallas kernels, in forward OR backward — at every shard size
    for s_loc in (64, 256):
        s = 8 * s_loc
        q, k, v = qkv(b=1, s=s, h=2, d=32)

        def loss(q, k, v):
            return jnp.sum(ring_attention_sharded(q, k, v, mesh, "sp", True) ** 2)

        jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
        _assert_no_quadratic_seq(jaxpr, s_loc)
        _assert_no_quadratic_seq(jaxpr, s)


def test_ulysses_flash_linear_memory_in_seq(mesh):
    from kubegpu_tpu.ops import ulysses_attention_sharded

    s = 8 * 64
    q, k, v = qkv(b=1, s=s, h=8, d=32)

    def loss(q, k, v):
        return jnp.sum(ulysses_attention_sharded(q, k, v, mesh, "sp", True) ** 2)

    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    _assert_no_quadratic_seq(jaxpr, s)


def test_ring_einsum_fallback_for_untileable_shards(mesh):
    # s_loc = 160 (> 128, not a multiple) can't tile into flash blocks; the
    # dispatcher must take the einsum path and stay correct
    q, k, v = qkv(b=1, s=8 * 160, h=2, d=16)
    out = ring_attention_sharded(q, k, v, mesh, "sp", True)
    ref = reference_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.exhaustive
def test_ring_flash_grads_match_reference_noncausal(mesh):
    q, k, v = qkv(b=1, s=8 * 16, h=2, d=16)

    def loss(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh, "sp", False) ** 2)

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    ref_grads = jax.grad(
        lambda q, k, v: jnp.sum(reference_attention(q, k, v, False) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


# -- ulysses attention (all-to-all sequence parallelism) --------------------

@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_reference(mesh, causal):
    from kubegpu_tpu.ops import ulysses_attention_sharded

    q, k, v = qkv(b=2, s=8 * 16, h=8, d=16)  # heads == axis size
    out = ulysses_attention_sharded(q, k, v, mesh, "sp", causal)
    ref = reference_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ulysses_under_jit_keeps_seq_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kubegpu_tpu.ops import ulysses_attention_sharded

    q, k, v = qkv(b=1, s=8 * 8, h=16, d=16)  # heads a multiple of axis size
    sh = NamedSharding(mesh, P(None, "sp", None, None))
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))
    f = jax.jit(lambda q, k, v: ulysses_attention_sharded(q, k, v, mesh, "sp", True))
    out = f(q, k, v)
    ref = reference_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
    assert out.sharding.spec == P(None, "sp", None, None)


def test_ulysses_grads_match_reference(mesh):
    from kubegpu_tpu.ops import ulysses_attention_sharded

    q, k, v = qkv(b=1, s=8 * 8, h=8, d=16)

    def loss(q, k, v):
        return jnp.sum(ulysses_attention_sharded(q, k, v, mesh, "sp", True) ** 2)

    grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    ref_grads = jax.grad(
        lambda q, k, v: jnp.sum(reference_attention(q, k, v, True) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


def test_ulysses_rejects_indivisible_heads(mesh):
    from kubegpu_tpu.ops import ulysses_attention_sharded

    q, k, v = qkv(b=1, s=8 * 8, h=6, d=16)  # 6 heads over 8 devices
    with pytest.raises(ValueError, match="divisible"):
        ulysses_attention_sharded(q, k, v, mesh, "sp", True)


# -- model integration ------------------------------------------------------

def test_transformer_flash_impl_matches_einsum():
    from kubegpu_tpu.models import TransformerLM

    tokens = jnp.arange(2 * 64, dtype=jnp.int32).reshape(2, 64) % 50
    kw = dict(vocab_size=64, num_layers=1, num_heads=2, hidden=32, max_seq=64,
              dtype=jnp.float32)
    lm_e = TransformerLM(attn_impl="einsum", **kw)
    lm_f = TransformerLM(attn_impl="flash", **kw)
    variables = lm_e.init(jax.random.PRNGKey(0), tokens)
    out_e = lm_e.apply(variables, tokens)
    out_f = lm_f.apply(variables, tokens)
    np.testing.assert_allclose(out_e, out_f, atol=1e-4, rtol=1e-4)
