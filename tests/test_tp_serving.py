"""Tensor-parallel paged serving (ISSUE 9).

The whole ``PagedContinuousBatcher`` hot loop runs over a "model" mesh:
KV page pool / prefill station / draft ring sharded on HEADS, page
tables / lengths / positions / active masks replicated, the paged
kernels per head-shard under shard_map, and the Megatron one-all-reduce-
per-block discipline in the projections (TRANSFORMER_TP_RULES).  The
sharding must be INVISIBLE in the output — greedy fp32 token-identical
to the single-device batcher across TP widths x page sizes x
speculation x prefix-cache hits x multi-turn sealing x pipeline_decode
on/off — while the pool genuinely rests 1/tp of its bytes per device
(the capacity payoff), accounting (including the sharded-layout leg)
balances under churn and kill schedules, and every program still
compiles exactly once per TP width.

The 8 CPU devices come from conftest.py's forced
``--xla_force_host_platform_device_count=8``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubegpu_tpu.models import TransformerLM, greedy_generate
from kubegpu_tpu.models.paging import PagedContinuousBatcher
from kubegpu_tpu.parallel import device_mesh
from kubegpu_tpu.utils.metrics import Metrics

# vocab and heads divisible by every tested TP width (lm_head is
# column-parallel over the vocab; the pool shards whole heads)
CFG = dict(vocab_size=64, num_layers=2, num_heads=8, hidden=32, max_seq=32)


@pytest.fixture(scope="module")
def params():
    model = TransformerLM(dtype=jnp.float32, **CFG)
    return model.init(jax.random.PRNGKey(0), jnp.ones((2, 8), jnp.int32))[
        "params"
    ]


def oracle(params, prompt, n):
    out = greedy_generate(
        params, jnp.asarray(prompt)[None, :], n, dtype=jnp.float32, **CFG
    )
    return list(np.asarray(out)[0, len(prompt):])


def tp_mesh(tp):
    if jax.device_count() < tp:
        pytest.skip(f"need {tp} devices, have {jax.device_count()}")
    return device_mesh({"model": tp}, devices=jax.devices()[:tp])


def make_paged(params, tp=1, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("prompt_pad", 20)
    kw.setdefault("page_size", 4)
    kw.setdefault("pool_pages", 40)
    mesh = tp_mesh(tp) if tp > 1 else None
    return PagedContinuousBatcher(
        params, dtype=jnp.float32, mesh=mesh, **CFG, **kw
    )


def spec_kw(params, k=2, **kw):
    return dict(
        draft_params=params, speculate_k=k,
        draft_num_layers=CFG["num_layers"],
        draft_num_heads=CFG["num_heads"], draft_hidden=CFG["hidden"],
        **kw,
    )


def traffic(seed=1, n_req=6):
    rng = np.random.RandomState(seed)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=n), np.int32)
        for n in (1, 3, 5, 8, 13)[:n_req]
    ]
    prompts.append(prompts[-1].copy())  # in-burst duplicate: prefix hit
    budgets = [5, 4, 6, 3, 5, 4][: len(prompts)]
    return prompts, budgets


# ---------------------------------------------------------------------------
# Fast (tier-1): TP=2 parity + the capacity claim + validation errors
# ---------------------------------------------------------------------------

def test_tp2_token_identity_and_pool_genuinely_sharded(params):
    """TP=2 emits exactly the single-device tokens (which are the
    per-sequence oracle's), while the pool/station REST half their
    bytes per device — the claim the page math stands on — and
    accounting (incl. the sharded-layout leg) balances."""
    prompts, budgets = traffic()
    ref = make_paged(params).run(prompts, budgets)
    for i, p in enumerate(prompts[:2]):
        assert ref[i] == oracle(params, p, budgets[i])
    cb = make_paged(params, tp=2)
    got = cb.run(prompts, budgets)
    assert got == ref
    cb.assert_page_accounting()
    for kp, vp in cb.pools:
        for arr in (kp, vp):
            assert arr.addressable_shards[0].data.nbytes * 2 == arr.nbytes
    for ck, cv in cb._station:
        assert ck.addressable_shards[0].data.nbytes * 2 == ck.nbytes
    assert cb.stats["prefix_hit_tokens"] > 0  # the duplicate hit


def test_tp_mesh_validation_dies_at_construction(params):
    """Malformed TP geometry fails crisply at construction, never as a
    reshape/sharding traceback mid-serve-loop."""
    mesh2 = tp_mesh(2)
    with pytest.raises(ValueError, match="model"):
        # a mesh without a "model" axis cannot tensor-parallel
        bad = device_mesh({"data": 2}, devices=jax.devices()[:2])
        PagedContinuousBatcher(
            params, dtype=jnp.float32, mesh=bad, **CFG,
            slots=2, prompt_pad=8, page_size=4, pool_pages=8,
        )
    with pytest.raises(ValueError, match="num_heads"):
        PagedContinuousBatcher(
            params, dtype=jnp.float32, mesh=tp_mesh(8),
            **{**CFG, "num_heads": 4}, slots=2, prompt_pad=8,
            page_size=4, pool_pages=8,
        )
    with pytest.raises(ValueError, match="vocab_size"):
        PagedContinuousBatcher(
            params, dtype=jnp.float32, mesh=mesh2,
            **{**CFG, "vocab_size": 61}, slots=2, prompt_pad=8,
            page_size=4, pool_pages=8,
        )
    with pytest.raises(ValueError, match="draft_num_heads"):
        PagedContinuousBatcher(
            params, dtype=jnp.float32, mesh=mesh2, **CFG,
            slots=2, prompt_pad=8, page_size=4, pool_pages=8,
            draft_params=params, speculate_k=2,
            draft_num_layers=2, draft_num_heads=3, draft_hidden=30,
        )


def test_tp_ledger_and_metrics_report_per_device_economy(params):
    """The ledger's per-iteration rows carry the TP economy — width,
    modeled collective wire bytes, resting pool bytes per device — and
    the serve_tp_* gauges/counter mirror them; at TP=1 the collective
    column is exactly zero."""
    prompts, budgets = traffic(seed=3, n_req=3)
    m = Metrics()
    cb = make_paged(params, tp=2, metrics=m)
    cb.run(prompts, budgets)
    rows = cb.ledger_rows()
    assert rows and all(r["tp"] == 2 for r in rows)
    assert any(r["collective_bytes"] > 0 for r in rows)
    total_pool = sum(
        kp.nbytes + vp.nbytes for kp, vp in cb.pools
    )
    assert all(
        r["pool_bytes_per_device"] == total_pool // 2 for r in rows
    )
    assert m.gauge("serve_tp_devices") == 2.0
    assert m.gauge("serve_tp_pool_bytes_per_device") == total_pool // 2
    assert m.get("serve_tp_collective_bytes_total") > 0
    # aggregate page gauges stay the mesh-wide counts (satellite: the
    # per-device half of the economy is the BYTES column)
    assert m.gauge("serve_pool_pages_free") <= cb.pool_pages - 1

    m1 = Metrics()
    cb1 = make_paged(params, metrics=m1)
    cb1.run(prompts, budgets)
    assert all(r["collective_bytes"] == 0 for r in cb1.ledger_rows())
    assert all(r["tp"] == 1 for r in cb1.ledger_rows())
    assert m1.gauge("serve_tp_devices") == 1.0


def test_sim_batcher_tp_contract_and_advertisement():
    """The gateway side of the plumbing: SimBatcher validates the tp
    contract at construction (a bad width dies replica-side, like the
    other serving knobs), and the data-plane client advertises each
    wired batcher's width for /debug/state's replica_mesh."""
    from kubegpu_tpu.gateway.client import (
        InMemoryReplicaClient, SimBatcher, _ReplicaWorker,
    )

    with pytest.raises(ValueError, match="tp"):
        SimBatcher(tp=0)
    assert SimBatcher(tp=4).tp == 4
    client = InMemoryReplicaClient(batcher_factory=lambda key: SimBatcher())
    w = _ReplicaWorker("r1", SimBatcher(tp=8), 0.0)
    try:
        with client._lock:
            client._workers["r1"] = w
        assert client.advertised() == {"r1": {"tp": 8}}
    finally:
        w.kill()


# ---------------------------------------------------------------------------
# Slow tier: the width x feature matrix, compile stability, soak
# ---------------------------------------------------------------------------

tp_matrix = pytest.mark.slow


@tp_matrix
@pytest.mark.parametrize("tp", [2, 4, 8])
def test_tp_matrix_token_identity_plain_and_spec(params, tp):
    """Every TP width x {plain, speculative} x {pipelined, synchronous}
    on mixed-length traffic with an in-burst duplicate: token-identical
    to the single-device batcher, accounting balanced."""
    prompts, budgets = traffic()
    for extra in (dict(), spec_kw(params, k=2)):
        ref = make_paged(params, pipeline_decode=False, **extra).run(
            prompts, budgets
        )
        for pipeline in (True, False):
            cb = make_paged(
                params, tp=tp, pipeline_decode=pipeline, **extra
            )
            got = cb.run(prompts, budgets)
            assert got == ref, (tp, pipeline, bool(extra))
            cb.assert_page_accounting()


@tp_matrix
@pytest.mark.parametrize("page_size", [4, 8])
def test_tp_page_sizes_multiturn_sealing_identity(params, page_size):
    """Page-size sweep with decode-page sealing: turn 2 through a TP=4
    batcher's sealed chain matches a cold single-device batcher, and
    the hits actually came from decode pages."""
    rng = np.random.RandomState(7)
    turn1 = np.array(rng.randint(0, CFG["vocab_size"], size=6), np.int32)
    cb = make_paged(
        params, tp=4, page_size=page_size, prompt_pad=24,
        decode_page_cache="fp32",
    )
    out1 = cb.run([turn1], [8])[0]
    ref1 = make_paged(
        params, page_size=page_size, prompt_pad=24,
        decode_page_cache="fp32",
    ).run([turn1], [8])[0]
    assert out1 == ref1
    assert cb.stats["decode_pages_sealed"] > 0
    turn2 = np.concatenate([
        turn1, np.asarray(out1, np.int32), np.array([9, 1, 4], np.int32),
    ])
    cold = make_paged(
        params, page_size=page_size, prompt_pad=24, prefix_cache=False
    )
    expected = cold.run([turn2], [6])[0]
    got = cb.run([turn2], [6])[0]
    assert got == expected
    assert cb.stats["prefix_hit_tokens_decode"] > 0
    cb.assert_page_accounting()


@tp_matrix
@pytest.mark.parametrize("tp", [2, 4])
def test_tp_compile_stability_fixed_jit_cache(params, tp):
    """40 steps of cancels, prefix hits, speculation and station churn
    per TP width: exactly ONE compiled entry per program — the TP
    shardings must not mint per-schedule recompiles."""
    rng = np.random.RandomState(6)
    cb = make_paged(
        params, tp=tp, station_slots=3, token_budget=11, prefill_chunk=8,
        pipeline_decode=True, **spec_kw(params, k=2),
    )
    seq, live = 0, []
    for _ in range(40):
        roll = rng.rand()
        if roll < 0.5:
            n = int(rng.randint(1, 13))
            max_new = int(rng.randint(0, 5))
            prompt = (
                np.arange(n, dtype=np.int32) % 7 if roll < 0.15
                else np.array(
                    rng.randint(0, CFG["vocab_size"], size=n), np.int32
                )
            )
            cb.submit(seq, prompt, max_new)
            live.append(seq)
            seq += 1
        elif roll < 0.6 and live:
            cb.cancel(live.pop(rng.randint(len(live))))
        else:
            for s in cb.serve_step():
                live.remove(s)
    while cb.has_work():
        for s in cb.serve_step():
            live.remove(s)
    cb.assert_page_accounting()
    # a speculative batcher's decode is draft+verify — the plain _step
    # program never dispatches (its stability is covered by the
    # identity matrix running plain-mode batchers at every width)
    for name in ("_spec_draft", "_spec_verify", "_draft_admit", "_chunk"):
        assert getattr(cb, name)._cache_size() == 1, (
            f"tp={tp} {name}: {getattr(cb, name)._cache_size()} entries"
        )
    for w, fn in {**cb._write_pages, **cb._gather_pages}.items():
        assert fn._cache_size() == 1, f"tp={tp} page width {w} recompiled"


@tp_matrix
def test_gateway_soak_tp_kill_schedule(params):
    """The acceptance soak, sharded: GatewaySoak's kill/revive/hedge
    schedule with multi-turn sessions over TP=2 paged batchers with
    pipelining, speculation AND decode-page sealing — invariant I5 plus
    page accounting (incl. the sharded-pool layout leg) on every
    surviving replica at quiescence."""
    from kubegpu_tpu.testing.soak import GatewaySoak

    mesh = tp_mesh(2)
    soak = GatewaySoak(
        seed=31, n_replicas=2, multiturn=True, follow_prompt_cap=12,
        batcher_factory=lambda key: PagedContinuousBatcher(
            params, slots=4, prompt_pad=12, page_size=4, pool_pages=48,
            station_slots=2, token_budget=8, dtype=jnp.float32,
            decode_page_cache="fp32", pipeline_decode=True, mesh=mesh,
            draft_params=params, speculate_k=2, draft_window=16,
            draft_num_layers=CFG["num_layers"],
            draft_num_heads=CFG["num_heads"],
            draft_hidden=CFG["hidden"], **CFG,
        ),
    )
    soak.run(steps=20)
