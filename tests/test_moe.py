"""MoE layer + expert parallelism tests (models/moe.py).

All on the virtual 8-device CPU mesh from conftest; fp32 so routing and
dispatch equivalences are exact to float tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubegpu_tpu.models import (
    MoEMLP,
    MoeTransformerLM,
    create_train_state,
    make_moe_train_step,
    place_moe,
)
from kubegpu_tpu.parallel import MOE_EP_RULES, device_mesh, param_shardings
from kubegpu_tpu.parallel.sharding import spec_for_param

pytestmark = pytest.mark.slow  # JAX compile-heavy; run with -m slow


def test_moe_matches_dense_mlp_with_identical_experts():
    """With no capacity drops and all experts holding the SAME weights, the
    MoE output must equal gate_prob * dense_mlp(x) — routing can't matter."""
    e, d, ratio = 4, 16, 2
    layer = MoEMLP(num_experts=e, capacity_factor=float(e), mlp_ratio=ratio,
                   dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, d), jnp.float32)
    params = layer.init(jax.random.PRNGKey(1), x)["params"]

    w1 = jax.random.normal(jax.random.PRNGKey(2), (d, d * ratio)) * 0.1
    w2 = jax.random.normal(jax.random.PRNGKey(3), (d * ratio, d)) * 0.1
    params = dict(params)
    params["w_up"] = jnp.broadcast_to(w1, (e,) + w1.shape)
    params["w_down"] = jnp.broadcast_to(w2, (e,) + w2.shape)

    out = layer.apply({"params": params}, x)

    xf = x.reshape(-1, d)
    gates = jax.nn.softmax(xf @ params["router"]["kernel"], axis=-1)
    gate = jnp.max(gates, axis=-1)  # top-1 prob (argmax gate)
    expected = (gate[:, None] * (jax.nn.gelu(xf @ w1) @ w2)).reshape(x.shape)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=1e-5, atol=1e-5)


def test_moe_capacity_drops_overflow_tokens():
    """num_experts=1 routes every token to expert 0; per-row capacity 4 of
    8 tokens → the first 4 of the row are processed, the rest are zero."""
    d = 8
    layer = MoEMLP(num_experts=1, capacity_factor=0.5, mlp_ratio=2,
                   dtype=jnp.float32)
    # two rows: capacity is per routing group (= batch row), so EACH row
    # keeps its first 4 tokens — proof the cumsum never crosses rows
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, d), jnp.float32)
    params = layer.init(jax.random.PRNGKey(1), x)["params"]
    out = np.asarray(layer.apply({"params": params}, x))

    for row in range(2):
        assert np.abs(out[row, :4]).sum() > 0, "kept tokens must produce output"
        np.testing.assert_allclose(out[row, 4:], 0.0, atol=1e-7)


def test_moe_aux_loss_sown_and_near_one_when_balanced():
    e, d = 4, 16
    layer = MoEMLP(num_experts=e, capacity_factor=2.0, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 16, d), jnp.float32)
    params = layer.init(jax.random.PRNGKey(1), x)["params"]
    _, mut = layer.apply({"params": params}, x, mutable=["intermediates"])
    inter = mut["intermediates"]
    (aux,) = jax.tree_util.tree_leaves(inter["aux_loss"])
    aux = float(aux)
    # Switch aux loss is exactly 1.0 at perfect balance; a freshly
    # initialized (near-uniform) router should sit close to it.
    assert 0.8 < aux < 2.0, aux
    # the drop-rate diagnostic is sown alongside and is a valid fraction
    (drop,) = jax.tree_util.tree_leaves(inter["drop_rate"])
    assert 0.0 <= float(drop) <= 1.0, drop


def test_moe_top2_matches_dense_mlp_with_identical_experts():
    """Top-2 renormalized gates sum to 1, so with every expert holding the
    SAME weights and capacity ample, the output must equal dense_mlp(x)
    EXACTLY — no gate factor at all (the two-way split cancels)."""
    e, d, ratio = 4, 16, 2
    layer = MoEMLP(num_experts=e, capacity_factor=float(e), mlp_ratio=ratio,
                   dtype=jnp.float32, router_type="top2")
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, d), jnp.float32)
    params = layer.init(jax.random.PRNGKey(1), x)["params"]
    w1 = jax.random.normal(jax.random.PRNGKey(2), (d, d * ratio)) * 0.1
    w2 = jax.random.normal(jax.random.PRNGKey(3), (d * ratio, d)) * 0.1
    params = dict(params)
    params["w_up"] = jnp.broadcast_to(w1, (e,) + w1.shape)
    params["w_down"] = jnp.broadcast_to(w2, (e,) + w2.shape)
    out = layer.apply({"params": params}, x)
    xf = x.reshape(-1, d)
    expected = (jax.nn.gelu(xf @ w1) @ w2).reshape(x.shape)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=1e-5, atol=1e-5)


def test_moe_top2_drops_fewer_tokens_than_top1_under_imbalance():
    """A router that sends EVERY token to expert 0 first overflows top-1
    at capacity_factor 1 (75% of tokens dropped with e=4); top-2's second
    choices spread over the remaining experts and recover most of them.
    The drop metric is TOKEN drop (no surviving expert), the
    quality-relevant event."""
    e, d, s = 4, 16, 32
    x = jnp.abs(jax.random.normal(jax.random.PRNGKey(0), (2, s, d))) + 0.1

    def drop_of(router_type):
        layer = MoEMLP(num_experts=e, capacity_factor=1.0, mlp_ratio=2,
                       dtype=jnp.float32, router_type=router_type)
        params = layer.init(jax.random.PRNGKey(1), x)["params"]
        params = dict(params)
        # column 0 dominates: all-positive activations x a large positive
        # first column => expert 0 is every token's first choice; the
        # runner-up stays data-dependent, so second choices spread
        kernel = np.asarray(params["router"]["kernel"]).copy()
        kernel[:, 0] = 5.0
        params["router"] = {"kernel": jnp.asarray(kernel)}
        _, mut = layer.apply({"params": params}, x, mutable=["intermediates"])
        (drop,) = jax.tree_util.tree_leaves(mut["intermediates"]["drop_rate"])
        return float(drop)

    d1, d2 = drop_of("top1"), drop_of("top2")
    assert d1 > 0.7, f"top1 should overflow hard here, got {d1}"
    # second choices are data-dependent and may themselves concentrate,
    # so the guarantee is a material reduction, not elimination
    assert d2 < d1 - 0.2, f"top2 token-drop {d2} not well below top1 {d1}"


def test_moe_expert_choice_is_dropless_by_construction():
    """Expert-choice: every expert fills exactly `capacity` slots, so
    capacity overflow cannot exist; the sown drop rate counts only tokens
    NO expert picked, which at capacity_factor >= num_experts (total
    slots >= tokens e-fold) stays small; aux loss is structurally 1."""
    e, d, s = 4, 16, 32
    layer = MoEMLP(num_experts=e, capacity_factor=float(e), mlp_ratio=2,
                   dtype=jnp.float32, router_type="expert_choice")
    x = jax.random.normal(jax.random.PRNGKey(0), (2, s, d), jnp.float32)
    params = layer.init(jax.random.PRNGKey(1), x)["params"]
    out, mut = layer.apply({"params": params}, x, mutable=["intermediates"])
    assert out.shape == x.shape
    inter = mut["intermediates"]
    (drop,) = jax.tree_util.tree_leaves(inter["drop_rate"])
    (aux,) = jax.tree_util.tree_leaves(inter["aux_loss"])
    # capacity = s here (cf = e), so every token is picked by its best
    # expert: structurally zero drops at this configuration
    assert float(drop) == 0.0, drop
    assert float(aux) == 1.0, aux


def test_moe_router_type_validated():
    layer = MoEMLP(num_experts=2, dtype=jnp.float32, router_type="topk")
    x = jnp.ones((1, 4, 8), jnp.float32)
    with pytest.raises(ValueError, match="router_type"):
        layer.init(jax.random.PRNGKey(0), x)


def test_moe_ep_rules_shard_expert_dim_only():
    rules = MOE_EP_RULES
    assert spec_for_param("layer0/moe_mlp/w_up", rules)[0] == "expert"
    assert spec_for_param("layer0/moe_mlp/w_down", rules)[0] == "expert"
    assert spec_for_param("layer0/moe_mlp/router/kernel", rules) == ()


@pytest.mark.exhaustive
@pytest.mark.parametrize(
    "router_type,dispatch_impl",
    [
        ("top1", "einsum"),
        ("top2", "einsum"),
        ("expert_choice", "einsum"),
        ("top1", "gather"),
        ("top2", "gather"),
    ],
)
def test_moe_ep_sharded_step_matches_single_device(router_type, dispatch_impl):
    """One DP x EP train step on a (data=2, expert=4) mesh must produce the
    same loss as the unsharded single-device step from the same init —
    for EVERY router AND both dispatch implementations: routing only
    changes the dispatch/combine arithmetic, never the sharding contract
    (the gather path's [b, e, c, d] tensor crosses the expert axis the
    same way the einsum's does)."""
    model = MoeTransformerLM(
        vocab_size=64, num_layers=2, num_heads=2, hidden=16,
        num_experts=4, capacity_factor=4.0, max_seq=32, dtype=jnp.float32,
        router_type=router_type, dispatch_impl=dispatch_impl,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(0), (4, 17), 0, 64)
    rng = jax.random.PRNGKey(1)

    mesh = device_mesh({"data": 2, "expert": 4})
    state = create_train_state(model, rng, tokens[:, :-1])
    state, ptokens = place_moe(state, tokens, mesh)
    step = make_moe_train_step(mesh, donate=False)
    _, loss_sharded, aux_sharded = step(state, ptokens)

    mesh1 = device_mesh({"data": 1, "expert": 1}, devices=jax.devices()[:1])
    state1 = create_train_state(model, rng, tokens[:, :-1])
    state1, tokens1 = place_moe(state1, tokens, mesh1)
    step1 = make_moe_train_step(mesh1, donate=False)
    _, loss_single, aux_single = step1(state1, tokens1)

    np.testing.assert_allclose(float(loss_sharded), float(loss_single),
                               rtol=1e-4)
    np.testing.assert_allclose(float(aux_sharded), float(aux_single),
                               rtol=1e-4)


def test_moe_ep_tp_sharded_step_matches_single_device():
    """EP x TP composition (VERDICT r1 #7): one step on a
    (data=2, expert=2, model=2) mesh — expert FFNs Megatron-sharded inside
    their expert shard, attention TP-sharded — must reproduce the
    single-device loss from the same init."""
    model = MoeTransformerLM(
        vocab_size=64, num_layers=2, num_heads=2, hidden=16,
        num_experts=2, capacity_factor=4.0, max_seq=32, dtype=jnp.float32,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(0), (4, 17), 0, 64)
    rng = jax.random.PRNGKey(1)

    mesh = device_mesh({"data": 2, "expert": 2, "model": 2})
    state = create_train_state(model, rng, tokens[:, :-1])
    state, ptokens = place_moe(state, tokens, mesh)
    # the EP x TP rules actually landed on the state
    from kubegpu_tpu.parallel import MOE_EP_TP_RULES
    from kubegpu_tpu.parallel.sharding import spec_for_param as sfp
    from jax.sharding import PartitionSpec as P

    assert sfp("params/layer0/moe_mlp/w_up", MOE_EP_TP_RULES) == P("expert", None, "model")
    assert sfp("params/layer0/moe_mlp/w_down", MOE_EP_TP_RULES) == P("expert", "model", None)
    assert sfp("params/layer0/attn/q_proj/kernel", MOE_EP_TP_RULES) == P(None, "model")
    step = make_moe_train_step(mesh, donate=False)
    _, loss_sharded, aux_sharded = step(state, ptokens)

    mesh1 = device_mesh({"data": 1, "expert": 1}, devices=jax.devices()[:1])
    state1 = create_train_state(model, rng, tokens[:, :-1])
    state1, tokens1 = place_moe(state1, tokens, mesh1)
    step1 = make_moe_train_step(mesh1, donate=False)
    _, loss_single, aux_single = step1(state1, tokens1)

    np.testing.assert_allclose(float(loss_sharded), float(loss_single), rtol=1e-4)
    np.testing.assert_allclose(float(aux_sharded), float(aux_single), rtol=1e-4)


@pytest.mark.exhaustive
def test_moe_train_step_learns_and_router_gets_gradient():
    model = MoeTransformerLM(
        vocab_size=32, num_layers=1, num_heads=2, hidden=16,
        num_experts=2, capacity_factor=2.0, max_seq=16, dtype=jnp.float32,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 9), 0, 32)
    mesh = device_mesh({"data": 2, "expert": 2}, devices=jax.devices()[:4])
    state = create_train_state(model, jax.random.PRNGKey(1), tokens[:, :-1])
    state, tokens = place_moe(state, tokens, mesh)
    step = make_moe_train_step(mesh, donate=False)

    from kubegpu_tpu.models.train import moe_loss

    grads = jax.grad(
        lambda p: moe_loss(state, p, tokens, 0.01)[0]
    )(state.params)
    router_grad = grads["layer0"]["moe_mlp"]["router"]["kernel"]
    assert float(jnp.abs(router_grad).sum()) > 0, "router must receive gradient"

    losses = []
    for _ in range(5):
        state, loss, _aux = step(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


@pytest.mark.exhaustive
def test_moe_remat_grads_match_plain():
    """remat=True must be a pure memory/FLOPs trade for the MoE LM too:
    gradients (and the sown aux loss path) identical to the plain model."""
    kw = dict(vocab_size=64, num_layers=2, num_heads=2, hidden=16,
              num_experts=2, capacity_factor=4.0, max_seq=32,
              dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 17), 0, 64)
    m = MoeTransformerLM(**kw)
    m_r = MoeTransformerLM(remat=True, **kw)

    from kubegpu_tpu.models.train import moe_loss

    state = create_train_state(m, jax.random.PRNGKey(1), tokens[:, :-1])
    state_r = state.replace(apply_fn=m_r.apply)

    def loss(st):
        return lambda p: moe_loss(st, p, tokens, 0.01)[0]

    g = jax.grad(loss(state))(state.params)
    gr = jax.grad(loss(state_r))(state_r.params)
    for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("router_type", ["top1", "top2"])
def test_moe_gather_dispatch_matches_einsum(router_type):
    """Index-form (scatter/gather) dispatch is the SAME arithmetic as the
    dense one-hot einsums, minus the O(s^2) zero-multiplies: outputs,
    sown routing metrics, and gradients must match to fp32 tolerance —
    including under real capacity overflow (capacity_factor 1.0 forces
    drops, so the dropped-token scatter path is exercised too)."""
    e, d = 4, 16
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 12, d), jnp.float32)
    kw = dict(num_experts=e, capacity_factor=1.0, mlp_ratio=2,
              dtype=jnp.float32, router_type=router_type,
              fast_dispatch=False)
    dense = MoEMLP(dispatch_impl="einsum", **kw)
    gather = MoEMLP(dispatch_impl="gather", **kw)
    params = dense.init(jax.random.PRNGKey(1), x)["params"]

    out_d, mut_d = dense.apply({"params": params}, x, mutable=["intermediates"])
    out_g, mut_g = gather.apply({"params": params}, x, mutable=["intermediates"])
    np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_g),
                               rtol=1e-6, atol=1e-6)
    for key in ("aux_loss", "drop_rate"):
        (a,) = jax.tree_util.tree_leaves(mut_d["intermediates"][key])
        (b,) = jax.tree_util.tree_leaves(mut_g["intermediates"][key])
        np.testing.assert_allclose(float(a), float(b), rtol=1e-6, atol=1e-7)

    def grads(layer):
        def f(p):
            return jnp.sum(layer.apply({"params": p}, x) ** 2)
        return jax.grad(f)(params)

    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5
        ),
        grads(dense),
        grads(gather),
    )


def test_moe_gather_dispatch_ec_falls_back_to_dense():
    """expert_choice + gather runs the dense path (its combine scatter-adds
    duplicate token targets, which IS the one-hot einsum) — outputs match
    the einsum config exactly rather than erroring."""
    e, d = 4, 16
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, d), jnp.float32)
    kw = dict(num_experts=e, capacity_factor=2.0, dtype=jnp.float32,
              router_type="expert_choice", fast_dispatch=False)
    a = MoEMLP(dispatch_impl="einsum", **kw)
    b = MoEMLP(dispatch_impl="gather", **kw)
    params = a.init(jax.random.PRNGKey(1), x)["params"]
    np.testing.assert_allclose(
        np.asarray(a.apply({"params": params}, x)),
        np.asarray(b.apply({"params": params}, x)),
        rtol=0, atol=0,
    )


def test_moe_dispatch_impl_validated():
    layer = MoEMLP(num_experts=2, dtype=jnp.float32, dispatch_impl="sorted")
    x = jnp.zeros((1, 4, 8), jnp.float32)
    with pytest.raises(ValueError, match="dispatch_impl"):
        layer.init(jax.random.PRNGKey(0), x)
