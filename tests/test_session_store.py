"""Crash-durable session-KV store (ISSUE 13).

Layers under test:

- the backend contract — versioned CAS puts (a stale capture loses to
  a newer seal), per-session leases on a fake clock, the byte-bounded
  payload LRU (oldest payloads drop, stream records stay), and the
  session-count bound;
- the standalone ``StoreServer`` — wire protocol round-trips (get /
  put / 409 / list / mark / delete / healthz / metrics) and the
  numpy↔base64 payload codec through ``HttpStoreClient``;
- the failure discipline — bounded retry with exponential backoff +
  jitter and the circuit breaker, unit-tested on a fake clock with a
  fake transport (a dead store costs one fast-fail per op, never a
  deadline per request);
- equivalence — the SAME capture/restore sequence against the
  in-process backend and the HTTP store yields the same
  ``restore_for`` outcomes and byte-identical restored payloads;
- ``SessionKVStore`` semantics — async write-through captures (bounded
  queue, drop-oldest, per-session dedup), degradation accounting
  (``gateway_session_store_degraded_total{reason}`` mirrors the
  degraded-event log), restore into the SAME pod name after a cold
  restart, and insurance surviving a gateway instance's death;
- the gateway lifecycle — /readyz per-instance readiness and graceful
  shutdown: a drain flips /readyz to 503 and refuses new admissions
  with the retryable error while a LIVE STREAM runs to completion;
- the store-outage soak — ``GatewaySoak(store_chaos=True)`` in the
  in-memory and HTTP lanes (and a slow paged multiturn lane): kills /
  revives of the store, forced CAS conflicts and lease expiry must
  all resolve as counted cold degradations with I5 intact.
"""

import http.client
import json
import random
import threading
import time

import numpy as np
import pytest

from kubegpu_tpu.gateway import (
    CircuitBreaker,
    GatewayRequest,
    HttpStoreClient,
    InProcessStoreBackend,
    SessionKVStore,
    StoreServer,
)
from kubegpu_tpu.gateway.sessionstore import (
    DEGRADE_REASONS,
    payload_bytes,
)
from kubegpu_tpu.utils.metrics import Metrics


class _Req:
    def __init__(self, session):
        self.session = session


class _FakeReplicaClient:
    """The sealed-chain client surface: exports a canned payload,
    records imports."""

    def __init__(self, payload=None):
        self.payload = payload if payload is not None else {"blob": "kv"}
        self.imports = []

    def export_sealed(self, key, stream):
        return dict(self.payload, exported_from=key,
                    stream_len=len(stream))

    def import_sealed(self, key, payload):
        self.imports.append((key, payload))
        return True


# ---------------------------------------------------------------------------
# 1. backend: CAS, leases, byte bound
# ---------------------------------------------------------------------------

def entry(replica="rA", stream=(1, 2, 3), payload=None, lost=False):
    return {"replica": replica, "stream": list(stream),
            "payload": payload, "lost": lost}


def test_backend_versions_and_cas():
    b = InProcessStoreBackend()
    assert b.get("s").status == "absent"
    r1 = b.put("s", entry())
    assert (r1.status, r1.version) == ("ok", 1)
    # unconditional put supersedes (a new turn)
    r2 = b.put("s", entry(stream=[1, 2, 3, 4]))
    assert (r2.status, r2.version) == ("ok", 2)
    # a CAS against the superseded version LOSES — the stale-capture race
    assert b.put("s", entry(payload={"old": 1}),
                 if_version=1).status == "conflict"
    got = b.get("s")
    assert got.entry["payload"] is None and got.version == 2
    # the CURRENT version wins
    r3 = b.put("s", entry(payload={"new": 1}), if_version=2)
    assert (r3.status, r3.version) == ("ok", 3)
    # marks bump versions: a capture racing a lost-mark must lose too
    b.mark_lost("rA")
    got = b.get("s")
    assert got.entry["lost"] and got.version == 4
    assert b.put("s", entry(), if_version=3).status == "conflict"
    # CAS against an absent session is a conflict, not a create
    assert b.put("zzz", entry(), if_version=1).status == "conflict"
    assert b.get("zzz").status == "absent"


def test_backend_lease_expiry_on_fake_clock():
    now = [0.0]
    m = Metrics()
    b = InProcessStoreBackend(lease_s=10.0, clock=lambda: now[0],
                              metrics=m)
    b.put("s", entry())
    now[0] = 9.9
    assert b.get("s").status == "ok"
    # every put RENEWS the lease
    b.put("s", entry(stream=[1]))
    now[0] = 19.0
    assert b.get("s").status == "ok"
    now[0] = 30.0
    assert b.get("s").status == "expired"
    assert m.get("session_store_lease_expired_total") == 1
    # expired is terminal: the entry is gone, a fresh put recreates at v1
    assert b.get("s").status == "absent"
    assert b.put("s", entry()).version == 1
    # chaos knob: expire_all lapses every lease now
    b.expire_all()
    assert b.get("s").status == "expired"


def test_backend_byte_bound_drops_oldest_payloads_property():
    rng = random.Random(7)
    m = Metrics()
    cap = 4000
    b = InProcessStoreBackend(max_payload_bytes=cap, metrics=m)
    live_payloads = {}
    for i in range(120):
        s = f"s{rng.randrange(30)}"
        size = rng.randrange(0, 900)
        payload = (
            {"layers": [{"k": "x" * size, "v": "y" * size}]}
            if size else None
        )
        b.put(s, entry(stream=[i], payload=payload))
        live_payloads[s] = payload
        # invariant: retained payload bytes within budget, and every
        # entry's STREAM record survived whatever was evicted
        total = 0
        for sess in list(live_payloads):
            got = b.get(sess)
            assert got.status == "ok"
            assert got.entry["stream"], sess
            total += payload_bytes(got.entry["payload"])
        assert total <= cap
        # the entry just written keeps its payload (evict-OLDEST)
        assert payload_bytes(b.get(s).entry["payload"]) == \
            payload_bytes(payload)
    assert m.get("session_store_payloads_dropped_total") > 0


def test_backend_session_count_bound():
    b = InProcessStoreBackend(max_sessions=5)
    for i in range(9):
        b.put(f"s{i}", entry(stream=[i]))
    assert b.get("s0").status == "absent"
    assert b.get("s8").status == "ok"
    assert b.stats()["sessions"] == 5


# ---------------------------------------------------------------------------
# 2. the HTTP store
# ---------------------------------------------------------------------------

@pytest.fixture()
def store():
    srv = StoreServer(lease_s=None).start()
    try:
        yield srv
    finally:
        srv.stop()


def test_store_server_wire_roundtrip(store):
    c = HttpStoreClient(store.url)
    assert c.healthy()
    assert c.get("s").status == "absent"
    r = c.put("s", entry(replica="rA"))
    assert (r.status, r.version) == ("ok", 1)
    got = c.get("s")
    assert got.status == "ok" and got.entry["replica"] == "rA"
    assert c.put("s", entry(payload={"x": 1}),
                 if_version=99).status == "conflict"
    assert c.put("s", entry(replica="rB"), if_version=1).status == "ok"
    assert c.sessions_on("rB") == ["s"]
    assert c.sessions_on("rA") == []
    assert c.mark_lost("rB")
    assert c.get("s").entry["lost"] is True
    assert c.sync_live(["rC"])           # rB not live -> stays lost
    assert c.delete("s").status == "ok"
    assert c.get("s").status == "absent"
    # server-side metrics render (the store pod's own /metrics)
    conn = http.client.HTTPConnection(*store.address, timeout=5)
    conn.request("GET", "/metrics")
    text = conn.getresponse().read().decode()
    conn.close()
    assert "session_store_requests_total" in text
    assert "session_store_cas_conflicts_total" in text


def test_http_payload_codec_roundtrips_numpy(store):
    c = HttpStoreClient(store.url)
    k = np.arange(24, dtype=np.float32).reshape(2, 12)
    v = (k * 2).astype(np.float32)
    payload = {
        "kind": "sealed", "page_keys": ["a", "b"],
        "geometry": {"dtype": "float32"},
        "layers": [(k, v)],
    }
    assert c.put("np", entry(payload=payload)).status == "ok"
    got = c.get("np").entry["payload"]
    assert got["page_keys"] == ["a", "b"]
    gk, gv = got["layers"][0]
    np.testing.assert_array_equal(np.asarray(gk), k)
    np.testing.assert_array_equal(np.asarray(gv), v)
    # an ALREADY-wire payload (the HttpReplicaClient export shape)
    # relays opaquely — no double-encode
    wire = {"kind": "sealed", "layers": [{"k": "QUJD", "v": "REVG",
                                          "shape": [1, 3]}]}
    assert c.put("wire", entry(payload=wire)).status == "ok"
    assert c.get("wire").entry["payload"]["layers"][0]["k"] == "QUJD"


def test_store_lease_expiry_degrades_restore():
    srv = StoreServer(lease_s=0.05).start()
    try:
        m = Metrics()
        kv = SessionKVStore(backend=HttpStoreClient(srv.url), metrics=m)
        client = _FakeReplicaClient()
        kv.record("s", "rA", [1, 2, 3])
        assert kv.capture(client, "s")
        time.sleep(0.15)
        assert not kv.restore_for(_Req("s"), "rB", client)
        assert kv.degraded_log == [("s", "lease_expired")]
        assert m.get("gateway_session_store_degraded_total",
                     reason="lease_expired") == 1
        assert client.imports == []
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# 3. breaker + backoff on a fake clock
# ---------------------------------------------------------------------------

def _fake_client(fail_plan, now, sleeps, **kw):
    """HttpStoreClient with a scripted transport: each _do call pops
    the next plan item — an Exception to raise or a (status, payload)
    to return."""
    kw.setdefault("timeout_s", 0.1)
    kw.setdefault("backoff_base_s", 0.05)
    kw.setdefault("backoff_cap_s", 0.4)
    c = HttpStoreClient(
        "http://127.0.0.1:1", clock=lambda: now[0],
        sleep=sleeps.append, rng=random.Random(3), **kw
    )
    calls = []

    def do(method, path, body=None):
        calls.append((method, path))
        action = fail_plan.pop(0)
        if isinstance(action, Exception):
            raise action
        return action

    c._do = do
    c._calls = calls
    return c


def test_retry_backoff_shape_and_jitter():
    now, sleeps = [0.0], []
    plan = [OSError("down")] * 4
    c = _fake_client(plan, now, sleeps, retries=3, breaker_threshold=99)
    assert c.get("s").status == "unreachable"
    assert len(c._calls) == 4          # 1 try + 3 retries, bounded
    assert len(sleeps) == 3
    # exponential shape with jitter in [0.5, 1.5)x of base * 2^k
    for k, s in enumerate(sleeps):
        base = min(0.05 * 2 ** k, 0.4)
        assert 0.5 * base <= s < 1.5 * base, (k, s)


def test_breaker_opens_fastfails_and_half_opens():
    now, sleeps = [0.0], []
    m = Metrics()
    plan = [OSError("down")] * 3 + [(200, {"version": 1})]
    c = _fake_client(plan, now, sleeps, retries=0, breaker_threshold=3,
                     breaker_cooldown_s=5.0, metrics=m)
    for _ in range(3):
        assert c.put("s", entry()).status == "unreachable"
    assert c.breaker.open and c.breaker.trips == 1
    n_calls = len(c._calls)
    # open window: fast-fail, the transport is NOT touched
    for _ in range(5):
        assert c.get("s").status == "unreachable"
    assert len(c._calls) == n_calls
    assert m.get("gateway_session_store_fastfail_total") == 5
    # past the cooldown: one half-open trial; success closes
    now[0] = 6.0
    assert c.put("s", entry()).status == "ok"
    assert not c.breaker.open and c.breaker.failures == 0


def test_breaker_reopens_on_failed_half_open_trial():
    now, sleeps = [0.0], []
    plan = [OSError("down")] * 4
    c = _fake_client(plan, now, sleeps, retries=0, breaker_threshold=3,
                     breaker_cooldown_s=5.0)
    for _ in range(3):
        c.get("s")
    assert c.breaker.open
    now[0] = 5.5
    assert c.get("s").status == "unreachable"   # trial fails
    assert c.breaker.open and c.breaker.trips == 2


def test_breaker_half_open_admits_exactly_one_trial():
    """At cooldown expiry only ONE op may probe the store; the rest
    keep fast-failing until the trial reports back — N dispatcher
    threads must not all stall an op deadline against a hung store."""
    now = [0.0]
    b = CircuitBreaker(threshold=1, cooldown_s=5.0, clock=lambda: now[0])
    b.failure()
    assert b.open and not b.allow()
    now[0] = 6.0
    assert b.allow()              # the single half-open trial
    assert not b.allow()          # concurrent callers: fast-fail
    b.failure()                   # trial failed: re-open a full window
    assert not b.allow()
    now[0] = 12.0
    assert b.allow()
    b.success()                   # trial succeeded: closed
    assert b.allow() and b.allow()


def test_retries_do_not_burn_time_once_breaker_opens():
    now, sleeps = [0.0], []
    plan = [OSError("down")] * 2
    c = _fake_client(plan, now, sleeps, retries=5, breaker_threshold=2,
                     breaker_cooldown_s=60.0)
    assert c.get("s").status == "unreachable"
    # the 2nd failure tripped the breaker mid-retry-loop: the remaining
    # retries are abandoned instead of sleeping through 4 more backoffs
    assert len(c._calls) == 2
    assert len(sleeps) == 1


# ---------------------------------------------------------------------------
# 4. HTTP-vs-in-process equivalence
# ---------------------------------------------------------------------------

def _capture_restore_script(kv, client):
    """The same capture/restore life a gateway drives, as data."""
    out = []
    kv.record("sess", "rA", [1, 2, 3])
    out.append(("capture", kv.capture(client, "sess")))
    # healthy home: no restore
    out.append(("home", kv.restore_for(_Req("sess"), "rA", client)))
    # away-dispatch (ring mispin): restore + re-home
    out.append(("mispin", kv.restore_for(_Req("sess"), "rB", client)))
    out.append(("rehomed", kv.entry("sess")["replica"]))
    # plain-LB mode: a healthy-home bounce must NOT ship the payload
    kv.record("s2", "rA", [4, 5])
    out.append(("cap2", kv.capture(client, "s2")))
    out.append(("lb", kv.restore_for(_Req("s2"), "rB", client,
                                     mispin_restore=False)))
    # ... but a LOST home restores even under a plain LB
    kv.mark_lost("rA")
    out.append(("lost", kv.restore_for(_Req("s2"), "rB", client,
                                       mispin_restore=False)))
    # unknown session / payload-less session: clean no-ops
    out.append(("unknown", kv.restore_for(_Req("nope"), "rB", client)))
    kv.record("s3", "rC", [6])
    out.append(("no-payload", kv.restore_for(_Req("s3"), "rB", client)))
    out.append(("sessions_on", sorted(kv.sessions_on("rB"))))
    return out


def test_http_vs_inprocess_backend_equivalence():
    k = np.arange(8, dtype=np.float32).reshape(1, 8)
    payload = {
        "kind": "sealed", "page_keys": ["p0"],
        "geometry": {"dtype": "float32"}, "layers": [(k, k + 1)],
    }
    in_client = _FakeReplicaClient(payload)
    kv_in = SessionKVStore()
    script_in = _capture_restore_script(kv_in, in_client)
    srv = StoreServer(lease_s=None).start()
    try:
        http_client = _FakeReplicaClient(payload)
        kv_http = SessionKVStore(backend=HttpStoreClient(srv.url))
        script_http = _capture_restore_script(kv_http, http_client)
        assert script_in == script_http, (
            "the HTTP store and the in-process backend diverged on the "
            f"same sequence:\n{script_in}\nvs\n{script_http}"
        )
        assert len(in_client.imports) == len(http_client.imports)
        for (k1, p1), (k2, p2) in zip(in_client.imports,
                                      http_client.imports):
            assert k1 == k2
            np.testing.assert_array_equal(
                np.asarray(p1["layers"][0][0]),
                np.asarray(p2["layers"][0][0]),
            )
        assert kv_in.degraded_log == kv_http.degraded_log == []
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# 5. SessionKVStore semantics
# ---------------------------------------------------------------------------

def test_async_capture_is_bounded_drop_oldest_and_deduped():
    m = Metrics()
    kv = SessionKVStore(metrics=m, capture_queue=2)
    gate = threading.Event()
    captured = []

    class _SlowClient:
        def export_sealed(self, key, stream):
            gate.wait(5.0)
            captured.append(key)
            return {"blob": key}

        def import_sealed(self, key, payload):
            return True

    client = _SlowClient()
    for i in range(5):
        kv.record(f"s{i}", f"r{i}", [i])
        kv.capture_async(client, f"s{i}")
    # dedup: re-queueing a session folds, not grows
    kv.capture_async(client, "s4")
    gate.set()
    assert kv.flush_captures(10.0)
    # bounded at 2: the OLDEST queued captures dropped (the first may
    # already be in flight when the queue clamps — so at least 2 drops)
    assert m.get("gateway_session_store_capture_drops_total") >= 2
    # the NEWEST sessions' insurance landed
    assert kv.entry("s4")["payload"] == {"blob": "r4"}
    kv.close()


def test_restore_fires_into_same_pod_name_after_loss():
    """A replica that cold-restarts under the SAME name (pod restart,
    same Service endpoint) lost its cache: a LOST entry must restore
    even when the routed target equals the recorded home."""
    kv = SessionKVStore()
    client = _FakeReplicaClient()
    kv.record("s", "rA", [1, 2])
    assert kv.capture(client, "s")
    # healthy home: no-op (the replica has its own cache)
    assert not kv.restore_for(_Req("s"), "rA", client)
    kv.sync_live(["rB"])          # rA left the live set (died)...
    kv.sync_live(["rA", "rB"])    # ...and came back, cold
    assert kv.restore_for(_Req("s"), "rA", client)
    assert client.imports and client.imports[0][0] == "rA"
    # restored: the entry is no longer lost, the next turn is a no-op
    assert not kv.restore_for(_Req("s"), "rA", client)


def test_restore_noop_is_metadata_only_and_restores_fetch_full():
    """restore_for runs on the dispatch hot path for EVERY sessionful
    request — three cost tiers, cheapest first: a HINTED healthy home
    (the turn just completed here) skips the store entirely; an
    unhinted healthy-home no-op decides on a metadata read (no payload
    bytes); only an actual restore pays the full fetch."""
    calls = []

    class _Spy(InProcessStoreBackend):
        def get(self, session, meta=False):
            calls.append(meta)
            return super().get(session, meta=meta)

    kv = SessionKVStore(backend=_Spy())
    client = _FakeReplicaClient()
    kv.record("s", "rA", [1, 2])
    assert kv.capture(client, "s")
    calls.clear()
    # record() just learned the healthy home: the hint makes repeat
    # dispatches to rA free — zero store round-trips
    assert not kv.restore_for(_Req("s"), "rA", client)
    assert calls == [], "hinted healthy home must not touch the store"
    # ring movement drops every hint; the next healthy-home dispatch
    # decides on ONE metadata read and re-arms the hint
    kv.sync_live(["rA", "rB"])
    assert not kv.restore_for(_Req("s"), "rA", client)
    assert calls == [True], "unhinted no-op must be metadata-only"
    calls.clear()
    assert not kv.restore_for(_Req("s"), "rA", client)
    assert calls == [], "the no-op must re-arm the hint"
    calls.clear()
    assert kv.restore_for(_Req("s"), "rB", client)
    assert calls == [True, False], "restore must re-read the full entry"


def test_hint_cache_invalidates_on_restore_degrade_and_movement():
    """A stale hint may only ever cost one skipped mispin-restore — so
    every event that could move a session's KV drops it: the restore
    itself (the entry re-homed), any degrade (the entry's state is in
    doubt), and ring movement (mark_lost / sync_live)."""
    calls = []

    class _Spy(InProcessStoreBackend):
        def get(self, session, meta=False):
            calls.append(meta)
            return super().get(session, meta=meta)

    backend = _Spy()
    kv = SessionKVStore(backend=backend)
    client = _FakeReplicaClient()
    kv.record("s", "rA", [1, 2])
    assert kv.capture(client, "s")
    # restore away re-homes to rB — the rA hint must NOT survive it
    assert kv.restore_for(_Req("s"), "rB", client)
    calls.clear()
    assert not kv.restore_for(_Req("s"), "rB", client)
    assert calls == [True], (
        "post-restore dispatch must re-verify via the store once"
    )
    # mark_lost (a drain/death) drops hints: the next dispatch to the
    # SAME key must consult the store and see the loss
    kv.mark_lost("rB")
    calls.clear()
    assert kv.restore_for(_Req("s"), "rB", client)
    assert calls and calls[0] is True
    # a degrade drops the session's hint too
    kv._hints["s"] = "rB"
    kv._degrade("s", "unreachable")
    assert "s" not in kv._hints


def test_meta_get_strips_payload_on_both_backends(store):
    payload = {"layers": [{"k": "x" * 64, "v": "y" * 64}]}
    for backend in (InProcessStoreBackend(), HttpStoreClient(store.url)):
        backend.put("s", entry(payload=payload))
        got = backend.get("s", meta=True)
        assert got.status == "ok" and got.version == 1
        assert got.entry["payload"] is None
        assert got.entry["payload_present"] is True
        full = backend.get("s")
        assert full.entry["payload"] == payload
        assert "payload_present" not in full.entry


def test_capture_cas_conflict_counts_and_keeps_newer_entry():
    m = Metrics()
    backend = InProcessStoreBackend()
    kv = SessionKVStore(backend=backend, metrics=m)
    client = _FakeReplicaClient()
    kv.record("s", "rA", [1, 2, 3])
    backend.force_conflicts = 1
    assert not kv.capture(client, "s")
    assert kv.degraded_log == [("s", "cas_conflict")]
    assert m.get("gateway_session_store_degraded_total",
                 reason="cas_conflict") == 1
    assert kv.entry("s")["payload"] is None
    # the next capture (no conflict) lands
    assert kv.capture(client, "s")
    assert kv.entry("s")["payload"] is not None


def test_unreachable_store_degrades_and_counts():
    m = Metrics()
    kv = SessionKVStore(
        backend=HttpStoreClient(
            "http://127.0.0.1:9", timeout_s=0.2, retries=0,
            breaker_threshold=2, breaker_cooldown_s=60.0, metrics=m,
        ),
        metrics=m,
    )
    client = _FakeReplicaClient()
    kv.record("s", "rA", [1])            # degrade 1 (unreachable)
    assert not kv.restore_for(_Req("s"), "rB", client)   # degrade 2
    assert not kv.capture(client, "s")                   # degrade 3
    assert [r for _, r in kv.degraded_log] == ["unreachable"] * 3
    total = sum(
        m.get("gateway_session_store_degraded_total", reason=r)
        for r in DEGRADE_REASONS
    )
    assert total == len(kv.degraded_log) == 3
    # the breaker opened after 2 failures: later ops fast-failed
    assert m.get("gateway_session_store_fastfail_total") >= 1
    assert client.imports == []


def test_insurance_survives_gateway_instance_death():
    """Two SessionKVStore INSTANCES (two gateway pods) over one
    external store: pod A records + captures, pod A 'dies' (its store
    object is simply dropped), pod B restores the session — the whole
    point of the external store."""
    srv = StoreServer(lease_s=None).start()
    try:
        client = _FakeReplicaClient()
        kv_a = SessionKVStore(backend=HttpStoreClient(srv.url))
        kv_a.record("s", "rA", [1, 2, 3])
        assert kv_a.capture(client, "s")
        del kv_a                       # the pod is gone
        kv_b = SessionKVStore(backend=HttpStoreClient(srv.url))
        kv_b.sync_live(["rB"])         # rA died with its pages
        assert kv_b.restore_for(_Req("s"), "rB", client)
        assert client.imports and client.imports[0][0] == "rB"
        assert kv_b.entry("s")["replica"] == "rB"
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# 6. gateway lifecycle: per-instance /readyz + graceful shutdown
# ---------------------------------------------------------------------------

def _gateway_server(step_delay_s=0.01):
    from kubegpu_tpu.gateway import (
        Gateway, GatewayServer, InMemoryReplicaClient, SimBatcher,
    )
    from kubegpu_tpu.testing.fake_serving import build_fake_serving_stack

    stack = build_fake_serving_stack(2)
    client = InMemoryReplicaClient(
        batcher_factory=lambda key: SimBatcher(slots=8),
        step_delay_s=step_delay_s,
    )
    stack.registry.subscribe(client.sync_live)
    gw = Gateway(stack.registry, client, metrics=Metrics(),
                 dispatchers=4)
    server = GatewayServer(gw, listen=("127.0.0.1", 0), watch=False)
    server.start()
    return stack, client, gw, server


def _get(port, path, timeout=10):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def test_graceful_shutdown_finishes_live_stream_and_flips_readyz():
    stack, client, gw, server = _gateway_server()
    host, port = server.address
    try:
        assert _get(port, "/readyz")[0] == 200
        # open a live greedy stream
        conn = http.client.HTTPConnection(host, port, timeout=30)
        conn.request(
            "POST", "/v1/generate",
            json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 40,
                        "stream": True}),
            {"Content-Type": "application/json"},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        # wait for first tokens so the drain provably crosses a LIVE
        # stream
        got, done_payload = [], None
        event = data = ""
        saw_tokens = threading.Event()

        def read_stream():
            nonlocal done_payload, event, data
            while True:
                line = resp.readline()
                if not line:
                    return
                line = line.strip().decode()
                if line.startswith("event:"):
                    event = line[6:].strip()
                elif line.startswith("data:"):
                    data = line[5:].strip()
                elif not line and event:
                    payload = json.loads(data) if data else {}
                    if event == "tokens":
                        got.extend(payload["tokens"])
                        saw_tokens.set()
                    else:
                        done_payload = (event, payload)
                        return
                    event, data = "", ""

        reader = threading.Thread(target=read_stream, daemon=True)
        reader.start()
        assert saw_tokens.wait(20.0), "no tokens before the drain"

        done = threading.Event()
        server.begin_graceful_shutdown(grace_s=30.0, done=done)
        # draining: /readyz 503, new admissions refuse RETRYABLY
        assert gw.draining and not gw.accepting
        status, body = _get(port, "/readyz")
        assert status == 503 and b"draining" in body
        conn2 = http.client.HTTPConnection(host, port, timeout=10)
        conn2.request(
            "POST", "/v1/generate",
            json.dumps({"prompt": [9], "max_new_tokens": 2}),
            {"Content-Type": "application/json"},
        )
        r2 = conn2.getresponse()
        refused = json.loads(r2.read())
        conn2.close()
        assert r2.status == 502
        assert "shutting down" in refused["error"]
        # the live stream FINISHES across the drain
        reader.join(30.0)
        assert done_payload is not None and done_payload[0] == "done", (
            done_payload,
        )
        assert len(done_payload[1]["tokens"]) == 40
        assert got == done_payload[1]["tokens"]
        conn.close()
        assert done.wait(30.0), "graceful shutdown never completed"
        assert not gw.alive
    finally:
        client.stop()
        if gw.alive:
            server.stop()


def test_readyz_reports_draining_before_replica_state():
    stack, client, gw, server = _gateway_server()
    port = server.address[1]
    try:
        assert _get(port, "/readyz")[0] == 200
        gw.begin_drain()
        status, body = _get(port, "/readyz")
        assert (status, body) == (503, b"draining")
        # draining refuses with the tier-retryable error
        res = gw.submit_and_wait(GatewayRequest(
            prompt=[1], max_new_tokens=1, request_id="late",
        ))
        assert res.status == "error" and "shutting down" in res.error
        from kubegpu_tpu.gateway import is_gateway_death

        assert is_gateway_death(res)
    finally:
        server.stop()
        client.stop()


# ---------------------------------------------------------------------------
# 7. the store-outage soak (both lanes; paged lane slow)
# ---------------------------------------------------------------------------

def test_gateway_soak_store_chaos_inmemory_tier():
    from kubegpu_tpu.testing.soak import GatewaySoak

    GatewaySoak(seed=1103, gateways=2, store_chaos=True).run(40)


def test_gateway_soak_store_chaos_http():
    from kubegpu_tpu.testing.soak import GatewaySoak

    GatewaySoak(seed=1104, http=True, store_chaos=True).run(30)


@pytest.mark.slow
def test_gateway_soak_store_chaos_paged_multiturn():
    import jax
    import jax.numpy as jnp

    from kubegpu_tpu.models import TransformerLM
    from kubegpu_tpu.models.paging import PagedContinuousBatcher
    from kubegpu_tpu.testing.soak import GatewaySoak

    cfg = dict(vocab_size=64, num_layers=1, num_heads=2, hidden=16,
               max_seq=64)
    params = TransformerLM(dtype=jnp.float32, **cfg).init(
        jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32)
    )["params"]

    def factory(key):
        return PagedContinuousBatcher(
            params, dtype=jnp.float32, slots=4, prompt_pad=16,
            page_size=4, pool_pages=48, decode_page_cache="fp32", **cfg,
        )

    GatewaySoak(
        seed=1105, n_replicas=2, batcher_factory=factory,
        multiturn=True, follow_prompt_cap=16, store_chaos=True,
    ).run(25)


# ---------------------------------------------------------------------------
# 8. deployment manifests
# ---------------------------------------------------------------------------

def test_deploy_manifests_wire_the_store():
    from pathlib import Path

    deploy = Path(__file__).resolve().parent.parent / "deploy"
    store_yaml = (deploy / "session-store.yaml").read_text()
    assert "kubegpu_tpu.gateway.sessionstore" in store_yaml
    assert "/healthz" in store_yaml
    gateway_yaml = (deploy / "gateway.yaml").read_text()
    assert "--session-store" in gateway_yaml
    assert "replicas: 2" in gateway_yaml
    # the entrypoint is a real module with a main()
    from kubegpu_tpu.gateway import sessionstore

    assert callable(sessionstore.main)
