"""Serving gateway: discovery, admission, routing, failover — in-memory.

Every cluster dependency is the InMemoryApiServer and every data-plane
dependency is the InMemoryReplicaClient, so the whole front door runs in
one process: replica pods are REALLY scheduled (advertiser → filter →
bind writes the assignment annotation the registry discovers), chip
deaths REALLY propagate (FakeSlice.kill_chip → advertise → node
annotation → registry drain), and requests REALLY decode (SimBatcher
token mill, or an actual ContinuousBatcher in the e2e test).
"""

import threading
import time

import pytest

from kubegpu_tpu.gateway import (
    AdmissionQueue,
    FailoverPolicy,
    Gateway,
    GatewayRequest,
    GatewayServer,
    InMemoryReplicaClient,
    LeastOutstandingRouter,
    QueueFull,
    ReplicaInfo,
    SessionAffinityRouter,
    SimBatcher,
)
from kubegpu_tpu.testing.fake_serving import build_fake_serving_stack
from kubegpu_tpu.types import RES_TPU, annotations
from kubegpu_tpu.utils.metrics import Metrics

MESH = (4, 4)


def req(prompt=(1, 2, 3), max_new=4, **kw):
    return GatewayRequest(prompt=list(prompt), max_new_tokens=max_new, **kw)


def make_serving_cluster(n_replicas=3, group="decode", pin_slices=None):
    """Fake 2-slice cluster with n scheduled single-chip decode replicas
    (the shared builder; ``pin_slices`` forces a known slice spread)."""
    return build_fake_serving_stack(
        n_replicas, group=group, pin_slices=pin_slices
    )


def advertise_all(c):
    for a in c.advs.values():
        a.advertise_once()


def kill_replica(c, replica: ReplicaInfo):
    """Chip death under a replica: the hardware event, then the advertise
    cycle that publishes it."""
    for coords in replica.coords:
        c.slices[replica.slice_id].kill_chip(coords)
    advertise_all(c)
    c.registry.refresh()


# ---------------------------------------------------------------------------
# Admission queue
# ---------------------------------------------------------------------------

def test_queue_bounded_with_explicit_backpressure():
    q = AdmissionQueue(capacity=3)
    for i in range(3):
        q.put(req(request_id=f"r{i}"))
    with pytest.raises(QueueFull):
        q.put(req(request_id="r3"))
    assert q.depth() == 3
    # FIFO within one tenant
    assert [q.get(0.01).request_id for _ in range(3)] == ["r0", "r1", "r2"]
    assert q.get(0.01) is None


def test_queue_per_tenant_fairness():
    q = AdmissionQueue(capacity=64)
    for i in range(6):
        q.put(req(request_id=f"a{i}", tenant="a"))
    for i in range(2):
        q.put(req(request_id=f"b{i}", tenant="b"))
    order = [q.get(0.01).request_id for _ in range(8)]
    # round-robin: b's two requests are NOT stuck behind a's backlog
    assert order[:4] == ["a0", "b0", "a1", "b1"]
    assert order[4:] == ["a2", "a3", "a4", "a5"]


def test_queue_per_tenant_cap():
    q = AdmissionQueue(capacity=64, per_tenant_cap=2)
    q.put(req(request_id="a0", tenant="a"))
    q.put(req(request_id="a1", tenant="a"))
    with pytest.raises(QueueFull, match="tenant"):
        q.put(req(request_id="a2", tenant="a"))
    q.put(req(request_id="b0", tenant="b"))  # other tenants unaffected


# ---------------------------------------------------------------------------
# Routers
# ---------------------------------------------------------------------------

def replica(key, slice_id="sa", coords=((0, 0),)):
    return ReplicaInfo(
        key=key, pod=key, namespace="default", group="g", node="n",
        slice_id=slice_id, coords=frozenset(coords),
    )


def test_least_outstanding_picks_min_then_slice_locality():
    r = LeastOutstandingRouter()
    reps = [replica("a", "sa"), replica("b", "sb"), replica("c", "sb")]
    assert r.pick(req(), reps, {"a": 3, "b": 1, "c": 2}).key == "b"
    # tie on load → the request's preferred slice wins
    hinted = req()
    hinted.preferred_slice = "sb"
    assert r.pick(hinted, reps, {}).key == "b"  # name-tiebreak inside sb
    # exclude set honored (hedge/retry must go elsewhere)
    assert r.pick(req(), reps, {}, frozenset({"a"})).key in ("b", "c")
    assert r.pick(req(), [], {}) is None


def test_least_outstanding_mesh_distance_tiebreak():
    r = LeastOutstandingRouter()
    near = replica("z-near", "sa", coords=((1, 1),))
    far = replica("a-far", "sa", coords=((3, 3),))
    anchor = replica("anchor", "sa", coords=((0, 0),))
    hinted = req()
    hinted.preferred_replica = "anchor"
    hinted.preferred_slice = "sa"
    # equal load, same slice: ICI distance to the anchor decides, beating
    # the name order (a-far sorts first)
    assert r.pick(hinted, [far, near, anchor], {"anchor": 9}).key == "z-near"


def test_session_affinity_sticky_then_same_slice_failover():
    m = Metrics()
    router = SessionAffinityRouter(metrics=m)
    reps = [replica("a1", "sa"), replica("a2", "sa"), replica("b1", "sb")]
    first = router.pick(req(session="s1"), reps, {})
    for load in ({first.key: 5}, {first.key: 9}):
        again = router.pick(req(session="s1"), reps, load)
        assert again.key == first.key  # sticky even when loaded
    # the initial pin is NOT a re-pin: no KV was lost
    assert m.get("gateway_session_repin_total") == 0
    # pinned replica drains: replacement prefers the SAME slice (KV
    # locality), the session re-pins to it, and the KV-loss event is
    # counted — prefix_hit_tokens on the new replica start from zero
    survivors = [r for r in reps if r.key != first.key]
    moved = router.pick(req(session="s1"), survivors, {})
    assert moved.slice_id == first.slice_id
    assert m.get("gateway_session_repin_total") == 1
    assert router.pick(req(session="s1"), survivors, {}).key == moved.key
    assert m.get("gateway_session_repin_total") == 1  # sticky != re-pin
    # no session → pure fallback
    assert router.pick(req(), reps, {"a1": 1, "a2": 0, "b1": 1}).key == "a2"


def test_session_repin_counts_exclusion_reroutes_too():
    """A hedge/retry exclude set that forces a pinned session elsewhere
    is the same KV-loss event as a death — counted identically."""
    m = Metrics()
    router = SessionAffinityRouter(metrics=m)
    reps = [replica("a1", "sa"), replica("a2", "sa")]
    first = router.pick(req(session="s2"), reps, {})
    rerouted = router.pick(
        req(session="s2"), reps, {}, exclude=frozenset({first.key})
    )
    assert rerouted.key != first.key
    assert m.get("gateway_session_repin_total") == 1


def test_gateway_wires_metrics_into_router():
    """A SessionAffinityRouter handed to Gateway without its own
    registry reports re-pins into the gateway's /metrics registry."""
    c = make_serving_cluster(1)
    client = InMemoryReplicaClient(batcher_factory=lambda k: SimBatcher())
    m = Metrics()
    router = SessionAffinityRouter()
    gw = Gateway(c.registry, client, router=router, metrics=m, dispatchers=0)
    try:
        assert router.metrics is m
        own = Metrics()
        router2 = SessionAffinityRouter(metrics=own)
        gw2 = Gateway(
            c.registry, client, router=router2, metrics=m, dispatchers=0
        )
        try:
            assert router2.metrics is own  # explicit registry wins
        finally:
            gw2.stop()
    finally:
        gw.stop()
        client.stop()


# ---------------------------------------------------------------------------
# Registry: discovery + advertiser-health drain
# ---------------------------------------------------------------------------

def test_registry_discovers_bound_replicas():
    c = make_serving_cluster(3)
    c.registry.refresh()
    live = c.registry.live()
    assert [r.key for r in live] == [
        "default/dec-0", "default/dec-1", "default/dec-2"
    ]
    for r in live:
        assert r.slice_id in ("sa", "sb")
        assert len(r.coords) == 1
        assert r.node


def test_registry_ignores_unbound_and_foreign_pods():
    c = make_serving_cluster(1)
    # a serving pod that never scheduled: visible but not routable
    c.api.create_pod({
        "metadata": {"name": "limbo", "namespace": "default",
                     "annotations": {annotations.POD_SERVING_GROUP: "decode"}},
        "spec": {"containers": [
            {"name": "s", "resources": {"limits": {RES_TPU: "1"}}}]},
    })
    # a pod without the serving-group key: not the gateway's business
    c.api.create_pod({
        "metadata": {"name": "train", "namespace": "default"},
        "spec": {"containers": [
            {"name": "s", "resources": {"limits": {RES_TPU: "1"}}}]},
    })
    c.registry.refresh()
    assert [r.key for r in c.registry.live()] == ["default/dec-0"]
    limbo = c.registry.get("default/limbo")
    assert limbo is not None and not limbo.healthy
    assert "unscheduled" in limbo.reason
    assert c.registry.get("default/train") is None


def test_registry_drains_replica_on_chip_death_and_recovers():
    c = make_serving_cluster(3)
    c.registry.refresh()
    events = []
    c.registry.subscribe(lambda live: events.append(set(live)))
    victim = c.registry.live()[0]
    kill_replica(c, victim)
    live = {r.key for r in c.registry.live()}
    assert victim.key not in live and len(live) == 2
    assert "dead chips" in c.registry.get(victim.key).reason
    assert events and victim.key not in events[-1]
    # hardware comes back → next advertise cycle restores the replica
    for coords in victim.coords:
        c.slices[victim.slice_id].revive_chip(coords)
    advertise_all(c)
    c.registry.refresh()
    assert victim.key in {r.key for r in c.registry.live()}
    assert victim.key in events[-1]


def test_registry_drains_on_pod_deletion_and_terminal_phase():
    c = make_serving_cluster(2)
    c.registry.refresh()
    c.api.delete_pod("default", "dec-0")
    c.registry.refresh()
    assert [r.key for r in c.registry.live()] == ["default/dec-1"]
    with c.api._lock:
        c.api._pods["default/dec-1"]["status"] = {"phase": "Failed"}
    c.registry.refresh()
    assert c.registry.live() == []
    assert "terminal" in c.registry.get("default/dec-1").reason


def test_registry_watch_drains_same_cycle_as_advertise():
    """Event-driven drain: the advertiser's node patch lands as a watch
    event and the replica leaves the live set without any polling."""
    c = make_serving_cluster(2)
    c.registry.refresh()
    stop = threading.Event()
    c.registry.start_watches(stop)
    try:
        victim = c.registry.live()[0]
        for coords in victim.coords:
            c.slices[victim.slice_id].kill_chip(coords)
        advertise_all(c)  # the patch IS the notification
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if victim.key not in c.registry.live_keys():
                break
            time.sleep(0.01)
        assert victim.key not in c.registry.live_keys()
    finally:
        stop.set()


# ---------------------------------------------------------------------------
# Metrics: gauge type + exposition format
# ---------------------------------------------------------------------------

def test_metrics_gauge_and_prometheus_text_format():
    m = Metrics()
    m.inc("gateway_requests_total", outcome="ok")
    m.set_gauge("gateway_queue_depth", 7)
    m.set_gauge("gateway_queue_depth", 3)          # gauges overwrite
    m.set_gauge("gateway_live_replicas", 2, group="decode")
    m.observe("gateway_ttft_seconds", 0.25)
    m.observe("gateway_ttft_seconds", 0.75)
    assert m.gauge("gateway_queue_depth") == 3
    assert m.gauge("gateway_live_replicas", group="decode") == 2
    text = m.render()
    lines = text.splitlines()
    assert 'gateway_requests_total{outcome="ok"} 1.0' in lines
    assert "# TYPE gateway_queue_depth gauge" in lines
    assert "gateway_queue_depth 3" in lines
    assert 'gateway_live_replicas{group="decode"} 2' in lines
    # TYPE line precedes its samples (Prometheus text format contract)
    assert lines.index("# TYPE gateway_queue_depth gauge") \
        < lines.index("gateway_queue_depth 3")
    assert "gateway_ttft_seconds_count 2" in lines
    assert "gateway_ttft_seconds_sum 1.0" in lines
    assert any(l.startswith('gateway_ttft_seconds{quantile="0.5"}')
               for l in lines)
    assert text.endswith("\n")


# ---------------------------------------------------------------------------
# SimBatcher: the serving-API contract the workers rely on
# ---------------------------------------------------------------------------

def test_sim_batcher_contract():
    b = SimBatcher(slots=2)
    for seq in range(3):
        b.submit(seq, [1], 3)
    b.submit(3, [1], 0)  # zero budget completes instantly, no slot held
    done = {}
    while b.has_work():
        done.update(b.serve_step())
    assert set(done) == {0, 1, 2, 3}
    assert done[3] == [] and all(len(done[s]) == 3 for s in (0, 1, 2))
    # deterministic per-seq stream, independent of slot scheduling
    assert done[1] == [(31 + i) % 256 for i in range(3)]
    b2 = SimBatcher(slots=2)
    b2.submit(0, [1], 5)
    b2.submit(1, [1], 5)
    b2.serve_step()
    assert b2.cancel(0) and not b2.cancel(0)
    done2 = {}
    while b2.has_work():
        done2.update(b2.serve_step())
    assert set(done2) == {1}


def test_sim_batcher_token_budget_step_cap():
    """token_budget caps per-step advances (round-robin, none starves)
    and leaves every per-sequence stream byte-identical."""
    b = SimBatcher(slots=4, token_budget=1)
    b.submit(0, [1], 2)
    b.submit(1, [1], 2)
    done = {}
    steps = 0
    while b.has_work():
        done.update(b.serve_step())
        steps += 1
        assert steps <= 8
    assert steps == 4  # 4 tokens owed at 1/step
    unbounded = SimBatcher(slots=4)
    unbounded.submit(0, [1], 2)
    unbounded.submit(1, [1], 2)
    done_ub = {}
    while unbounded.has_work():
        done_ub.update(unbounded.serve_step())
    assert done == done_ub
    with pytest.raises(ValueError, match="token_budget"):
        SimBatcher(token_budget=0)


def test_sim_batcher_cancel_resubmit_keeps_budget_fair():
    """Cancelling an active seq must drop its budget-ring entry: a
    resubmitted seq_id otherwise holds TWO ring slots forever, double-
    drawing the budget while a neighbor starves."""
    b = SimBatcher(slots=4, token_budget=2)
    b.submit(1, [1], 9)
    b.submit(2, [1], 9)
    b.serve_step()
    assert b.cancel(1)
    b.submit(1, [1], 9)
    b.serve_step()  # re-admits seq 1
    b.submit(1, [1], 9)  # re-submit while ACTIVE: restart, no extra ring slot
    for _ in range(4):
        b.serve_step()
        lens = {s: len(t) for s, (t, _) in b._active.items()}
        # budget 2, two active seqs: EVERY step advances both exactly once
        assert abs(lens[1] - lens[2]) <= 2, lens
    assert list(b._rr).count(1) == 1, list(b._rr)


def test_sim_batcher_speculation_model():
    """speculate_k models multi-token verify steps: per-seq streams stay
    BYTE-IDENTICAL to the one-token mill (speculation is lossless), the
    request drains in strictly fewer steps, and under a token budget a
    speculative sequence bills its whole k+1-row window."""
    plain = SimBatcher(slots=4)
    spec = SimBatcher(slots=4, speculate_k=3)
    for b in (plain, spec):
        b.submit(0, [1], 11)
        b.submit(1, [1], 7)
    done_p, done_s = {}, {}
    while plain.has_work():
        done_p.update(plain.serve_step())
    while spec.has_work():
        done_s.update(spec.serve_step())
    assert done_s == done_p  # lossless: identical streams
    assert spec.stats["steps"] < plain.stats["steps"]
    # budget accounting: k=3 bills 4 rows/seq, so budget 4 advances ONE
    # sequence per step (and budget below a window still advances one —
    # the can't-starve floor)
    for budget in (4, 2):
        b = SimBatcher(slots=4, token_budget=budget, speculate_k=3)
        b.submit(0, [1], 8)
        b.submit(1, [1], 8)
        b.serve_step()
        advanced = sum(
            1 for _, (t, _n) in b._active.items() if len(t) > 0
        )
        assert advanced == 1, (budget, advanced)
    with pytest.raises(ValueError, match="speculate_k"):
        SimBatcher(speculate_k=0)


def test_server_speculate_k_argparse_validation(tmp_path):
    """--speculate-k dies at argparse time (the --token-budget pattern):
    below 1, without --draft-checkpoint, or with a checkpoint path that
    does not exist (a typo'd path must not reach deployment)."""
    from kubegpu_tpu.gateway import server

    ckpt = str(tmp_path)
    for argv in (
        ["--fake-cluster", "v5e-16", "--speculate-k", "0",
         "--draft-checkpoint", ckpt],
        ["--fake-cluster", "v5e-16", "--speculate-k", "-2",
         "--draft-checkpoint", ckpt],
        ["--fake-cluster", "v5e-16", "--speculate-k", "2"],
        ["--fake-cluster", "v5e-16", "--speculate-k", "2",
         "--draft-checkpoint", str(tmp_path / "no-such-dir")],
    ):
        with pytest.raises(SystemExit):
            server.main(argv)


# ---------------------------------------------------------------------------
# Failover: retries, hedging, deadlines
# ---------------------------------------------------------------------------

def make_gateway(c, metrics=None, router=None, policy=None, dispatchers=4,
                 step_delay_s=0.0, queue=None, slots=8):
    client = InMemoryReplicaClient(
        batcher_factory=lambda key: SimBatcher(slots=slots),
        step_delay_s=step_delay_s,
    )
    c.registry.subscribe(client.sync_live)
    gw = Gateway(
        c.registry, client, router=router, queue=queue,
        policy=policy or FailoverPolicy(deadline_s=10.0),
        metrics=metrics or Metrics(), dispatchers=dispatchers,
    )
    c.registry.refresh()
    gw.start()
    return gw, client


def test_retry_on_replica_crash_completes_elsewhere():
    c = make_serving_cluster(2)
    m = Metrics()
    gw, client = make_gateway(
        c, metrics=m,
        policy=FailoverPolicy(deadline_s=10.0, hedge_after_s=60.0,
                              max_attempts=3),
    )
    try:
        # dec-0 is the deterministic first pick (all-zero outstanding →
        # name order); make it slow enough to still be decoding at kill
        client.set_step_delay("default/dec-0", 0.05)
        pending = gw.submit(req(max_new=40, request_id="crash-victim"))
        time.sleep(0.15)  # let it land on dec-0
        client.fail_replica("default/dec-0")  # the pod's process dies
        assert pending.wait(10.0)
        result = pending.result()
        assert result.status == "ok"
        assert result.replica == "default/dec-1"
        assert result.attempts >= 2
        assert m.get("gateway_retries_total") >= 1
    finally:
        gw.stop()
        client.stop()


def test_hedged_dispatch_straggler_first_win_cancels():
    c = make_serving_cluster(3)
    m = Metrics()
    gw, client = make_gateway(
        c, metrics=m,
        policy=FailoverPolicy(deadline_s=10.0, hedge_after_s=0.05),
    )
    try:
        client.set_step_delay("default/dec-0", 0.5)  # straggler = 1st pick
        result = gw.submit_and_wait(req(max_new=5, request_id="hedged"))
        assert result.status == "ok"
        assert result.hedged
        assert result.replica != "default/dec-0"  # the hedge won
        assert m.get("gateway_hedges_total") == 1
        # exactly-once delivery: the straggler's eventual completion (or
        # cancellation) must never surface as a second result
        assert m.get("gateway_duplicate_results_total") == 0
        assert gw.drain(5.0)
    finally:
        gw.stop()
        client.stop()


def test_hedge_budget_bounds_amplification():
    c = make_serving_cluster(2)
    m = Metrics()
    gw, client = make_gateway(
        c, metrics=m, dispatchers=2,
        policy=FailoverPolicy(deadline_s=5.0, hedge_after_s=0.01,
                              hedge_budget_ratio=0.0, budget_floor=2),
    )
    try:
        for key in client.replicas():
            client.set_step_delay(key, 0.05)  # everyone "straggles"
        results = [
            gw.submit(req(max_new=3, request_id=f"h{i}")) for i in range(12)
        ]
        assert gw.drain(20.0)
        assert all(p.wait(1) and p.result().status == "ok" for p in results)
        # floor=2, ratio=0: at most 2 hedges ever issued
        assert m.get("gateway_hedges_total") <= 2
    finally:
        gw.stop()
        client.stop()


def test_deadline_exceeded_is_explicit():
    c = make_serving_cluster(1)
    gw, client = make_gateway(
        c, step_delay_s=0.2,
        policy=FailoverPolicy(deadline_s=0.3, hedge_after_s=60.0),
    )
    try:
        result = gw.submit_and_wait(req(max_new=500, request_id="too-slow"))
        assert result.status == "timeout"
        assert "deadline" in result.error
    finally:
        gw.stop()
        client.stop()


def test_queue_full_resolves_as_rejected():
    c = make_serving_cluster(1)
    client = InMemoryReplicaClient(batcher_factory=lambda k: SimBatcher())
    c.registry.refresh()
    gw = Gateway(
        c.registry, client, queue=AdmissionQueue(capacity=2),
        metrics=Metrics(), dispatchers=0,  # nobody drains: queue fills
    )
    try:
        first = [gw.submit(req(request_id=f"q{i}")) for i in range(2)]
        overflow = gw.submit(req(request_id="q-over"))
        assert overflow.wait(0.1)
        assert overflow.result().status == "rejected"
        assert "capacity" in overflow.result().error
        assert all(not p.wait(0) for p in first)  # admitted ones still queued
        assert gw.metrics.get("gateway_requests_total", outcome="rejected") == 1
    finally:
        gw.stop()
        client.stop()


def test_duplicate_request_id_refused():
    c = make_serving_cluster(1)
    gw, client = make_gateway(c)
    try:
        gw.submit_and_wait(req(request_id="dup"))
        with pytest.raises(ValueError, match="duplicate"):
            gw.submit(req(request_id="dup"))
    finally:
        gw.stop()
        client.stop()


# ---------------------------------------------------------------------------
# HTTP frontend
# ---------------------------------------------------------------------------

def test_gateway_http_server_end_to_end():
    import http.client
    import json

    c = make_serving_cluster(2)
    client = InMemoryReplicaClient(batcher_factory=lambda k: SimBatcher())
    c.registry.subscribe(client.sync_live)
    gw = Gateway(c.registry, client, metrics=Metrics(), dispatchers=2)
    server = GatewayServer(gw, listen=("127.0.0.1", 0), watch=False)
    server.start()
    host, port = server.address
    try:
        def call(method, path, body=None):
            conn = http.client.HTTPConnection(host, port, timeout=10)
            conn.request(
                method, path,
                body=json.dumps(body) if body is not None else None,
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            raw = resp.read()
            conn.close()
            return resp.status, raw

        status, raw = call("POST", "/v1/generate",
                           {"prompt": [1, 2], "max_new_tokens": 4,
                            "tenant": "t0", "session": "s0"})
        assert status == 200
        payload = json.loads(raw)
        assert payload["status"] == "ok" and len(payload["tokens"]) == 4
        assert payload["replica"].startswith("default/dec-")

        status, raw = call("GET", "/healthz")
        assert (status, raw) == (200, b"ok")
        status, _ = call("GET", "/readyz")
        assert status == 200
        status, raw = call("GET", "/metrics")
        assert status == 200
        text = raw.decode()
        assert 'gateway_requests_total{outcome="ok"} 1.0' in text
        assert "# TYPE gateway_queue_depth gauge" in text
        assert "gateway_ttft_seconds_count 1" in text
        status, raw = call("GET", "/state")
        assert status == 200
        state = json.loads(raw)
        assert len(state["replicas"]) == 2
        assert state["outcomes"] == {"ok": 1}
        status, _ = call("POST", "/nope", {})
        assert status == 404
        conn = http.client.HTTPConnection(host, port, timeout=10)
        conn.request("POST", "/v1/generate", body=b"{not json",
                     headers={"Content-Length": "9"})
        assert conn.getresponse().status == 400
        conn.close()
    finally:
        server.stop()
        client.stop()


def test_readyz_tracks_live_replicas_not_hardcoded():
    """/readyz with a wired data plane: 200 while >=1 routable replica,
    503 once the registry drains to zero, 200 again on revival —
    readiness is the registry's routable set, not a hardcode."""
    import http.client

    c = make_serving_cluster(1)
    client = InMemoryReplicaClient(batcher_factory=lambda k: SimBatcher())
    c.registry.subscribe(client.sync_live)
    gw = Gateway(c.registry, client, metrics=Metrics(), dispatchers=1)
    server = GatewayServer(gw, listen=("127.0.0.1", 0), watch=False)
    server.start()
    host, port = server.address
    try:
        def readyz():
            conn = http.client.HTTPConnection(host, port, timeout=10)
            conn.request("GET", "/readyz")
            resp = conn.getresponse()
            raw = resp.read()
            conn.close()
            return resp.status, raw.decode()

        assert readyz() == (200, "ok")
        victim = c.registry.live()[0]
        kill_replica(c, victim)
        status, body = readyz()
        assert status == 503 and "no routable replicas" in body
        for coords in victim.coords:
            c.slices[victim.slice_id].revive_chip(coords)
        advertise_all(c)
        c.registry.refresh()
        assert readyz() == (200, "ok")
    finally:
        server.stop()
        client.stop()


def test_readyz_503_when_data_plane_unwired():
    """A client that can reach nothing (no workers, no factory) keeps
    /readyz at 503 however many replicas the registry sees.  (The
    in-cluster default is now the HTTP data plane — see
    tests/test_http_data_plane.py for readiness driven by live replica
    probes; this pins the degenerate no-data-plane posture.)"""
    import http.client

    c = make_serving_cluster(1)
    client = InMemoryReplicaClient(batcher_factory=None)
    gw = Gateway(c.registry, client, metrics=Metrics(), dispatchers=0)
    server = GatewayServer(gw, listen=("127.0.0.1", 0), watch=False)
    server.start()
    host, port = server.address
    try:
        conn = http.client.HTTPConnection(host, port, timeout=10)
        conn.request("GET", "/readyz")
        resp = conn.getresponse()
        assert resp.status == 503
        assert b"data plane" in resp.read()
        conn.close()
    finally:
        server.stop()
        client.stop()


def test_gateway_http_429_on_backpressure():
    import http.client
    import json

    c = make_serving_cluster(1)
    client = InMemoryReplicaClient(batcher_factory=lambda k: SimBatcher())
    c.registry.refresh()
    gw = Gateway(
        c.registry, client, queue=AdmissionQueue(capacity=1),
        metrics=Metrics(), dispatchers=0,  # nothing drains the queue
    )
    server = GatewayServer(gw, listen=("127.0.0.1", 0), watch=False)
    server.start()
    host, port = server.address
    try:
        gw.submit(req(request_id="filler"))  # occupies the whole queue
        conn = http.client.HTTPConnection(host, port, timeout=10)
        conn.request("POST", "/v1/generate",
                     body=json.dumps({"prompt": [1], "max_new_tokens": 2}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 429
        assert "retry" in json.loads(resp.read())["error"]
        conn.close()
    finally:
        server.stop()
        client.stop()


# ---------------------------------------------------------------------------
# Acceptance e2e: 3 replicas, 2 slices, ≥200 requests, mid-run kill
# ---------------------------------------------------------------------------

def test_e2e_load_balance_and_replica_kill_zero_lost():
    """The ISSUE's acceptance scenario: 3 decode replicas on a fake
    2-slice cluster, ≥200 requests with a replica killed mid-run.  Every
    request completes or is rejected with explicit backpressure — zero
    lost, zero double-served — and least-outstanding routing keeps the
    per-replica completed counts within 2x before the kill."""
    c = make_serving_cluster(3, pin_slices=["sa", "sa", "sb"])
    m = Metrics()
    gw, client = make_gateway(
        c, metrics=m, dispatchers=8, step_delay_s=0.001,
        policy=FailoverPolicy(
            deadline_s=30.0, hedge_after_s=60.0, max_attempts=4,
            retry_budget_ratio=1.0, budget_floor=64,
        ),
    )
    try:
        assert len(c.registry.live()) == 3
        assert {r.slice_id for r in c.registry.live()} == {"sa", "sb"}

        # phase 1: steady state — balance check before any failure
        phase1 = [
            gw.submit(req(max_new=10, request_id=f"p1-{i}",
                          tenant=f"t{i % 4}"))
            for i in range(120)
        ]
        assert gw.drain(30.0)
        counts = dict(gw.completed_by_replica)
        assert sum(counts.values()) == 120
        assert len(counts) == 3, counts
        assert max(counts.values()) <= 2 * min(counts.values()), counts

        # phase 2: 100 longer requests with a replica killed mid-flight
        phase2 = [
            gw.submit(req(max_new=30, request_id=f"p2-{i}",
                          tenant=f"t{i % 4}"))
            for i in range(100)
        ]
        time.sleep(0.05)  # some of phase 2 is decoding on the victim now
        victim = c.registry.live()[0]
        client.fail_replica(victim.key)   # the process dies with its chips
        kill_replica(c, victim)           # ...and the control plane sees it
        assert victim.key not in {r.key for r in c.registry.live()}
        assert gw.drain(60.0)

        results = gw.results()
        all_pending = phase1 + phase2
        assert len(results) == 220
        # zero lost: every handle resolved with a terminal result
        for p in all_pending:
            assert p.wait(0), f"{p.request_id} never resolved"
            r = results[p.request_id]
            # zero silently dropped: only explicit outcomes, and under a
            # generous retry budget a single kill costs no request
            assert r.status in ("ok", "rejected"), (r.status, r.error)
        # zero double-served: no second terminal result was ever recorded
        assert m.get("gateway_duplicate_results_total") == 0
        # ...and the data plane delivered each ok request exactly once
        for p in all_pending:
            r = results[p.request_id]
            if r.status == "ok":
                assert client.decodes.get(p.request_id, 0) >= 1
        n_ok = sum(1 for r in results.values() if r.status == "ok")
        assert n_ok == m.get("gateway_requests_total", outcome="ok")
        assert m.gauge("gateway_live_replicas") == 2  # drained to survivors
        # post-kill traffic flowed to the survivors only
        post = {k: v - counts.get(k, 0)
                for k, v in gw.completed_by_replica.items()}
        assert post.get(victim.key, 0) <= sum(post.values()) // 2
    finally:
        gw.stop()
        client.stop()


# ---------------------------------------------------------------------------
# e2e with a REAL ContinuousBatcher: queue → route → admit → decode → retire
# ---------------------------------------------------------------------------

def test_e2e_real_continuous_batcher_matches_greedy_oracle():
    """Two replicas each drive an actual ContinuousBatcher (tiny model,
    CPU): requests flow through the full gateway path and the returned
    tokens must equal per-sequence greedy_generate — which replica served
    a request is irrelevant because both hold the same checkpoint, the
    production-replica contract."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubegpu_tpu.models import TransformerLM, greedy_generate
    from kubegpu_tpu.models.serving import ContinuousBatcher

    cfg = dict(vocab_size=61, num_layers=1, num_heads=2, hidden=16,
               max_seq=32)
    params = TransformerLM(dtype=jnp.float32, **cfg).init(
        jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32)
    )["params"]

    rng = np.random.RandomState(7)
    prompts = [
        np.array(rng.randint(0, cfg["vocab_size"], size=n), dtype=np.int32)
        for n in (3, 5, 7, 4, 6, 2)
    ]
    budgets = [5, 3, 4, 6, 2, 5]
    expected = {}
    for i, (p, n) in enumerate(zip(prompts, budgets)):
        out = greedy_generate(
            params, jnp.asarray(p)[None, :], n, dtype=jnp.float32, **cfg
        )
        expected[i] = list(np.asarray(out)[0, len(p):])

    c = make_serving_cluster(2)
    m = Metrics()  # ONE registry: gateway metrics + replica serve_* rows
    client = InMemoryReplicaClient(
        batcher_factory=lambda key: ContinuousBatcher(
            params, slots=2, prompt_pad=8, dtype=jnp.float32, metrics=m,
            **cfg
        )
    )
    c.registry.subscribe(client.sync_live)
    gw = Gateway(
        c.registry, client, metrics=m, dispatchers=4,
        policy=FailoverPolicy(deadline_s=120.0, hedge_after_s=600.0),
    )
    c.registry.refresh()
    gw.start()
    try:
        pendings = [
            gw.submit(GatewayRequest(
                prompt=list(map(int, prompts[i])),
                max_new_tokens=budgets[i], request_id=f"real-{i}",
            ))
            for i in range(len(prompts))
        ]
        for i, p in enumerate(pendings):
            assert p.wait(180.0), f"real-{i} did not finish"
            r = p.result()
            assert r.status == "ok", (r.status, r.error)
            assert r.tokens == expected[i], (
                f"real-{i}: gateway {r.tokens} != greedy {expected[i]} "
                f"(served by {r.replica})"
            )
        served = {p.result().replica for p in pendings}
        assert served  # at least one replica served; both usually did
        # data-plane latency flows through the SAME exposition the
        # gateway serves at /metrics: TTFT/ITL histograms and the
        # prefill-chunk counter sit next to gateway_requests_total
        text = m.render()
        assert "serve_ttft_seconds_count" in text
        assert "serve_itl_seconds_count" in text
        assert m.get("serve_prefill_chunks_total") > 0
        assert m.histogram_count("serve_ttft_seconds") == len(prompts)
    finally:
        gw.stop()
        client.stop()
