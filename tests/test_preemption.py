"""Preemption + multi-tenant tests (BASELINE config 5)."""

import pytest

from kubegpu_tpu.scheduler.preemption import collect_units, find_victims
from kubegpu_tpu.types import annotations, is_contiguous_submesh
from kubegpu_tpu.types.info import PodInfo, ContainerInfo

from test_scheduler import fake_cluster, make_sched, pod_obj, nodes_of


def schedule_gang(sched, api, prefix, n_pods, chips, group, priority=0):
    objs = [
        pod_obj(f"{prefix}{i}", chips, group=group, group_size=n_pods)
        for i in range(n_pods)
    ]
    for o in objs:
        if priority:
            o["metadata"]["annotations"][annotations.POD_PRIORITY] = str(priority)
        api.create_pod(o)
    for o in objs:
        name = o["metadata"]["name"]
        r = sched.filter(o, nodes_of(api))
        assert r.nodes, f"{name}: {r.failed}"
        err = sched.bind("default", name, r.nodes[0])
        assert err is None, err
    return objs


# -- config 5: two concurrent 8-chip tenants (no preemption needed) ---------

def test_two_tenants_bin_pack_the_slice():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    schedule_gang(sched, api, "a", 2, 4, group="tenant-a")
    schedule_gang(sched, api, "b", 2, 4, group="tenant-b")
    view = next(iter(sched.cache.views().values()))
    assert len(view.free) == 0
    # each tenant's 8 chips form a contiguous rectangle
    for tenant in ("a", "b"):
        coords = set()
        for i in range(2):
            a = annotations.assignment_from_pod(api.get_pod("default", f"{tenant}{i}"))
            coords |= {c.coords for c in a.all_chips()}
        assert len(coords) == 8
        assert is_contiguous_submesh(coords, (4, 4))


def test_third_tenant_rejected_without_priority():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    schedule_gang(sched, api, "a", 2, 4, group="tenant-a")
    schedule_gang(sched, api, "b", 2, 4, group="tenant-b")
    objs = [pod_obj(f"c{i}", 4, group="tenant-c", group_size=2) for i in range(2)]
    for o in objs:
        api.create_pod(o)
    r = sched.filter(objs[0], nodes_of(api))
    assert r.nodes == []
    # nothing was evicted
    assert len(api.list_pods()) == 6
    assert sched.metrics.get("kubegpu_preemptions_total") == 0


# -- preemption -------------------------------------------------------------

def test_high_priority_gang_preempts_lowest_tenant():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    schedule_gang(sched, api, "a", 2, 4, group="tenant-a", priority=5)
    schedule_gang(sched, api, "b", 2, 4, group="tenant-b", priority=1)
    # high-priority 8-chip job arrives on the full slice
    vip = schedule_gang(sched, api, "v", 2, 4, group="tenant-vip", priority=10)
    assert sched.metrics.get("kubegpu_preemptions_total") == 1
    # the LOWEST-priority tenant (b) was evicted whole; a survives
    remaining = {p["metadata"]["name"] for p in api.list_pods()}
    assert remaining == {"a0", "a1", "v0", "v1"}
    # vip got contiguous chips
    coords = set()
    for o in vip:
        a = annotations.assignment_from_pod(
            api.get_pod("default", o["metadata"]["name"])
        )
        coords |= {c.coords for c in a.all_chips()}
    assert len(coords) == 8 and is_contiguous_submesh(coords, (4, 4))


def test_preemption_evicts_gangs_whole():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    schedule_gang(sched, api, "low", 4, 1, group="tenant-low", priority=1)  # 4 chips
    schedule_gang(sched, api, "mid", 2, 4, group="tenant-mid", priority=5)  # 8 chips
    # 8-chip vip: evicting tenant-low (4 chips) is not enough on its own if
    # the free 4 don't align; whatever is evicted must be whole units
    schedule_gang(sched, api, "v", 2, 4, group="tenant-vip", priority=10)
    names = {p["metadata"]["name"] for p in api.list_pods()}
    # tenant-low either fully present or fully evicted
    low = {f"low{i}" for i in range(4)}
    assert low <= names or not (low & names)


def test_equal_priority_never_preempts():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    schedule_gang(sched, api, "a", 2, 4, group="tenant-a", priority=5)
    schedule_gang(sched, api, "b", 2, 4, group="tenant-b", priority=5)
    objs = [pod_obj(f"c{i}", 4, group="tenant-c", group_size=2) for i in range(2)]
    for o in objs:
        o["metadata"]["annotations"][annotations.POD_PRIORITY] = "5"
        api.create_pod(o)
    r = sched.filter(objs[0], nodes_of(api))
    assert r.nodes == []
    assert len(api.list_pods()) == 6


def test_single_pod_preemption_path():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    # fill the slice with low-priority singles
    for i in range(4):
        obj = pod_obj(f"low{i}", 4)
        obj["metadata"]["annotations"][annotations.POD_PRIORITY] = "1"
        api.create_pod(obj)
        r = sched.filter(obj, nodes_of(api))
        assert sched.bind("default", f"low{i}", r.nodes[0]) is None
    vip = pod_obj("vip", 4)
    vip["metadata"]["annotations"][annotations.POD_PRIORITY] = "10"
    api.create_pod(vip)
    r = sched.filter(vip, nodes_of(api))
    assert r.nodes, r.failed
    assert sched.bind("default", "vip", r.nodes[0]) is None
    # exactly one victim evicted (minimal set)
    assert len([p for p in api.list_pods() if p["metadata"]["name"].startswith("low")]) == 3


def test_preemption_scoped_to_candidate_slices():
    # regression (review finding): victims must never be evicted on slices
    # the candidate node list cannot reach
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    schedule_gang(sched, api, "low", 2, 4, group="tenant-low", priority=1)
    vip = pod_obj("vip", 4)
    vip["metadata"]["annotations"][annotations.POD_PRIORITY] = "10"
    api.create_pod(vip)
    # candidate list contains only unknown (non-TPU) nodes
    r = sched.filter(vip, ["unrelated-node-1", "unrelated-node-2"])
    assert r.nodes == []
    # nothing was evicted for zero benefit
    assert sched.metrics.get("kubegpu_preemptions_total") == 0
    assert len(api.list_pods()) == 3


def test_evicted_victim_annotation_cleared_before_delete():
    # regression (review finding): a victim lingering in Terminating must
    # not be replayed by refresh onto the preemptor's chips
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    seen_cleared = []

    def watcher(event, obj):
        if event == "pod-updated":
            ann = obj.get("metadata", {}).get("annotations", {})
            if ann.get(annotations.POD_ASSIGNMENT) == "":
                seen_cleared.append(obj["metadata"]["name"])

    api.observe(watcher)
    schedule_gang(sched, api, "low", 2, 4, group="tenant-low", priority=1)
    schedule_gang(sched, api, "mid", 2, 4, group="tenant-mid", priority=5)
    schedule_gang(sched, api, "v", 2, 4, group="tenant-vip", priority=10)
    assert sorted(seen_cleared) == ["low0", "low1"]


# -- pure victim-finding ----------------------------------------------------

def make_pod_info(name, chips, priority=0, group=None):
    return PodInfo(
        name=name,
        containers=[ContainerInfo("m", chips)],
        priority=priority,
        pod_group=group,
    )


def test_find_victims_none_when_all_higher():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    schedule_gang(sched, api, "a", 4, 4, group="tenant-a", priority=10)
    units = collect_units(api.list_pods(), sched.cache.assignments_snapshot())
    assert all(u.priority == 10 for u in units)
    d = find_victims(sched.cache.views(), units, [make_pod_info("x", 4)], incoming_priority=5)
    assert d is None


def test_find_victims_minimal_set():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    for i, prio in enumerate((1, 2, 3, 4)):
        obj = pod_obj(f"p{i}", 4)
        obj["metadata"]["annotations"][annotations.POD_PRIORITY] = str(prio)
        api.create_pod(obj)
        r = sched.filter(obj, nodes_of(api))
        assert sched.bind("default", f"p{i}", r.nodes[0]) is None
    units = collect_units(api.list_pods(), sched.cache.assignments_snapshot())
    d = find_victims(
        sched.cache.views(), units, [make_pod_info("x", 4, priority=10)], incoming_priority=10
    )
    assert d is not None and len(d.victims) == 1
    assert d.victims[0].priority == 1  # cheapest victim chosen


# -- multislice preemption (joint cross-slice victim search) ----------------

def two_slice_cluster():
    from kubegpu_tpu.plugins import Advertiser, FakeSlice
    from kubegpu_tpu.utils import InMemoryApiServer

    api = InMemoryApiServer()
    slices = {}
    for sid in ("sa", "sb"):
        fs = FakeSlice(slice_id=sid, mesh_shape=(4, 4), host_block=(2, 2))
        slices[sid] = fs
        for prov in fs.providers().values():
            Advertiser(prov, api).advertise_once()
    return api, slices


def ms_pod(name, chips, group, size, priority=0):
    o = pod_obj(name, chips, group=group, group_size=size)
    o["metadata"]["annotations"][annotations.POD_MULTISLICE] = "true"
    if priority:
        o["metadata"]["annotations"][annotations.POD_PRIORITY] = str(priority)
    return o


def schedule_all_pods(sched, api, objs):
    for o in objs:
        name = o["metadata"]["name"]
        r = sched.filter(o, nodes_of(api))
        assert r.nodes, f"{name}: {r.failed}"
        err = sched.bind("default", name, r.nodes[0])
        assert err is None, err


def test_fresh_multislice_gang_preempts_on_both_slices():
    """VERDICT r1 #6: a 2-slice gang preempts lower-priority units on BOTH
    its slices — the per-slice victim search cannot model this (the gang
    needs 16 chips per slice; each slice holds an 8-chip squatter)."""
    api, _ = two_slice_cluster()
    sched = make_sched(api)
    # low-priority squatters: 8 of 16 chips on each slice — the incoming
    # gang needs all 16 of both, so eviction must hit both slices at once
    for sid_tag in ("a", "b"):
        objs = [
            ms_pod(f"{sid_tag}{i}", 4, group=f"tenant-{sid_tag}", size=2,
                   priority=1)
            for i in range(2)
        ]
        for o in objs:
            # pin each squatter gang to its own slice so the setup is
            # deterministic (they are single-slice gangs)
            o["metadata"]["annotations"][annotations.POD_SLICE_SELECTOR] = (
                "sa" if sid_tag == "a" else "sb"
            )
            del o["metadata"]["annotations"][annotations.POD_MULTISLICE]
            api.create_pod(o)
        schedule_all_pods(sched, api, objs)

    # incoming: 8 x 4 chips = 32 > any slice; needs ALL chips of both
    big = [ms_pod(f"m{i}", 4, group="big", size=8, priority=5) for i in range(8)]
    for o in big:
        api.create_pod(o)
    schedule_all_pods(sched, api, big)

    assert sched.metrics.get("kubegpu_preemptions_total") >= 1
    # both squatter gangs were evicted whole
    left = {p["metadata"]["name"] for p in api.list_pods()}
    assert not any(n.startswith(("a", "b")) for n in left), left
    per_slice = {}
    for i in range(8):
        a = annotations.assignment_from_pod(api.get_pod("default", f"m{i}"))
        assert a is not None and len(a.all_chips()) == 4
        per_slice.setdefault(a.slice_id, set()).update(
            c.coords for c in a.all_chips()
        )
    assert set(per_slice) == {"sa", "sb"}
    assert all(len(v) == 16 for v in per_slice.values())


def test_anchored_multislice_gang_replacement_preempts_squatter():
    """A partially-bound 2-slice gang whose dead member's chips were grabbed
    by a lower-priority pod: the anchored re-plan must preempt the squatter
    on exactly the deficit slice (previously declined outright)."""
    api, _ = two_slice_cluster()
    sched = make_sched(api)
    gang = [ms_pod(f"m{i}", 4, group="big", size=8, priority=5) for i in range(8)]
    for o in gang:
        api.create_pod(o)
    schedule_all_pods(sched, api, gang)
    layouts = {}
    for i in range(8):
        a = annotations.assignment_from_pod(api.get_pod("default", f"m{i}"))
        layouts[f"m{i}"] = (a.slice_id, {c.coords for c in a.all_chips()})

    # one member dies; a low-priority squatter grabs its freed chips
    victim_name = "m7"
    dead_slice, dead_coords = layouts[victim_name]
    dead = api.get_pod("default", victim_name)
    api.delete_pod("default", victim_name)
    sched.on_pod_deleted(dead)
    squatter = pod_obj("squat", 4)
    squatter["metadata"]["annotations"][annotations.POD_PRIORITY] = "1"
    api.create_pod(squatter)
    schedule_all_pods(sched, api, [squatter])
    sq = annotations.assignment_from_pod(api.get_pod("default", "squat"))
    assert sq.slice_id == dead_slice  # it took the only free chips

    # the replacement member arrives; anchored re-plan must evict the
    # squatter and reclaim the dead member's exact coords
    repl = ms_pod(victim_name, 4, group="big", size=8, priority=5)
    api.create_pod(repl)
    schedule_all_pods(sched, api, [repl])
    with pytest.raises(Exception):
        api.get_pod("default", "squat")  # evicted
    a = annotations.assignment_from_pod(api.get_pod("default", victim_name))
    assert a.slice_id == dead_slice
    assert {c.coords for c in a.all_chips()} == dead_coords
