"""Preemption + multi-tenant tests (BASELINE config 5)."""

import pytest

from kubegpu_tpu.scheduler.preemption import collect_units, find_victims
from kubegpu_tpu.types import annotations, is_contiguous_submesh
from kubegpu_tpu.types.info import PodInfo, ContainerInfo

from test_scheduler import fake_cluster, make_sched, pod_obj, nodes_of


def schedule_gang(sched, api, prefix, n_pods, chips, group, priority=0):
    objs = [
        pod_obj(f"{prefix}{i}", chips, group=group, group_size=n_pods)
        for i in range(n_pods)
    ]
    for o in objs:
        if priority:
            o["metadata"]["annotations"][annotations.POD_PRIORITY] = str(priority)
        api.create_pod(o)
    for o in objs:
        name = o["metadata"]["name"]
        r = sched.filter(o, nodes_of(api))
        assert r.nodes, f"{name}: {r.failed}"
        err = sched.bind("default", name, r.nodes[0])
        assert err is None, err
    return objs


# -- config 5: two concurrent 8-chip tenants (no preemption needed) ---------

def test_two_tenants_bin_pack_the_slice():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    schedule_gang(sched, api, "a", 2, 4, group="tenant-a")
    schedule_gang(sched, api, "b", 2, 4, group="tenant-b")
    view = next(iter(sched.cache.views().values()))
    assert len(view.free) == 0
    # each tenant's 8 chips form a contiguous rectangle
    for tenant in ("a", "b"):
        coords = set()
        for i in range(2):
            a = annotations.assignment_from_pod(api.get_pod("default", f"{tenant}{i}"))
            coords |= {c.coords for c in a.all_chips()}
        assert len(coords) == 8
        assert is_contiguous_submesh(coords, (4, 4))


def test_third_tenant_rejected_without_priority():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    schedule_gang(sched, api, "a", 2, 4, group="tenant-a")
    schedule_gang(sched, api, "b", 2, 4, group="tenant-b")
    objs = [pod_obj(f"c{i}", 4, group="tenant-c", group_size=2) for i in range(2)]
    for o in objs:
        api.create_pod(o)
    r = sched.filter(objs[0], nodes_of(api))
    assert r.nodes == []
    # nothing was evicted
    assert len(api.list_pods()) == 6
    assert sched.metrics.get("kubegpu_preemptions_total") == 0


# -- preemption -------------------------------------------------------------

def test_high_priority_gang_preempts_lowest_tenant():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    schedule_gang(sched, api, "a", 2, 4, group="tenant-a", priority=5)
    schedule_gang(sched, api, "b", 2, 4, group="tenant-b", priority=1)
    # high-priority 8-chip job arrives on the full slice
    vip = schedule_gang(sched, api, "v", 2, 4, group="tenant-vip", priority=10)
    assert sched.metrics.get("kubegpu_preemptions_total") == 1
    # the LOWEST-priority tenant (b) was evicted whole; a survives
    remaining = {p["metadata"]["name"] for p in api.list_pods()}
    assert remaining == {"a0", "a1", "v0", "v1"}
    # vip got contiguous chips
    coords = set()
    for o in vip:
        a = annotations.assignment_from_pod(
            api.get_pod("default", o["metadata"]["name"])
        )
        coords |= {c.coords for c in a.all_chips()}
    assert len(coords) == 8 and is_contiguous_submesh(coords, (4, 4))


def test_preemption_evicts_gangs_whole():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    schedule_gang(sched, api, "low", 4, 1, group="tenant-low", priority=1)  # 4 chips
    schedule_gang(sched, api, "mid", 2, 4, group="tenant-mid", priority=5)  # 8 chips
    # 8-chip vip: evicting tenant-low (4 chips) is not enough on its own if
    # the free 4 don't align; whatever is evicted must be whole units
    schedule_gang(sched, api, "v", 2, 4, group="tenant-vip", priority=10)
    names = {p["metadata"]["name"] for p in api.list_pods()}
    # tenant-low either fully present or fully evicted
    low = {f"low{i}" for i in range(4)}
    assert low <= names or not (low & names)


def test_equal_priority_never_preempts():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    schedule_gang(sched, api, "a", 2, 4, group="tenant-a", priority=5)
    schedule_gang(sched, api, "b", 2, 4, group="tenant-b", priority=5)
    objs = [pod_obj(f"c{i}", 4, group="tenant-c", group_size=2) for i in range(2)]
    for o in objs:
        o["metadata"]["annotations"][annotations.POD_PRIORITY] = "5"
        api.create_pod(o)
    r = sched.filter(objs[0], nodes_of(api))
    assert r.nodes == []
    assert len(api.list_pods()) == 6


def test_single_pod_preemption_path():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    # fill the slice with low-priority singles
    for i in range(4):
        obj = pod_obj(f"low{i}", 4)
        obj["metadata"]["annotations"][annotations.POD_PRIORITY] = "1"
        api.create_pod(obj)
        r = sched.filter(obj, nodes_of(api))
        assert sched.bind("default", f"low{i}", r.nodes[0]) is None
    vip = pod_obj("vip", 4)
    vip["metadata"]["annotations"][annotations.POD_PRIORITY] = "10"
    api.create_pod(vip)
    r = sched.filter(vip, nodes_of(api))
    assert r.nodes, r.failed
    assert sched.bind("default", "vip", r.nodes[0]) is None
    # exactly one victim evicted (minimal set)
    assert len([p for p in api.list_pods() if p["metadata"]["name"].startswith("low")]) == 3


def test_preemption_scoped_to_candidate_slices():
    # regression (review finding): victims must never be evicted on slices
    # the candidate node list cannot reach
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    schedule_gang(sched, api, "low", 2, 4, group="tenant-low", priority=1)
    vip = pod_obj("vip", 4)
    vip["metadata"]["annotations"][annotations.POD_PRIORITY] = "10"
    api.create_pod(vip)
    # candidate list contains only unknown (non-TPU) nodes
    r = sched.filter(vip, ["unrelated-node-1", "unrelated-node-2"])
    assert r.nodes == []
    # nothing was evicted for zero benefit
    assert sched.metrics.get("kubegpu_preemptions_total") == 0
    assert len(api.list_pods()) == 3


def test_evicted_victim_annotation_cleared_before_delete():
    # regression (review finding): a victim lingering in Terminating must
    # not be replayed by refresh onto the preemptor's chips
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    seen_cleared = []

    def watcher(event, obj):
        if event == "pod-updated":
            ann = obj.get("metadata", {}).get("annotations", {})
            if ann.get(annotations.POD_ASSIGNMENT) == "":
                seen_cleared.append(obj["metadata"]["name"])

    api.observe(watcher)
    schedule_gang(sched, api, "low", 2, 4, group="tenant-low", priority=1)
    schedule_gang(sched, api, "mid", 2, 4, group="tenant-mid", priority=5)
    schedule_gang(sched, api, "v", 2, 4, group="tenant-vip", priority=10)
    assert sorted(seen_cleared) == ["low0", "low1"]


# -- pure victim-finding ----------------------------------------------------

def make_pod_info(name, chips, priority=0, group=None):
    return PodInfo(
        name=name,
        containers=[ContainerInfo("m", chips)],
        priority=priority,
        pod_group=group,
    )


def test_find_victims_none_when_all_higher():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    schedule_gang(sched, api, "a", 4, 4, group="tenant-a", priority=10)
    units = collect_units(api.list_pods(), sched.cache.assignments_snapshot())
    assert all(u.priority == 10 for u in units)
    d = find_victims(sched.cache.views(), units, [make_pod_info("x", 4)], incoming_priority=5)
    assert d is None


def test_find_victims_minimal_set():
    api, _, _ = fake_cluster()
    sched = make_sched(api)
    for i, prio in enumerate((1, 2, 3, 4)):
        obj = pod_obj(f"p{i}", 4)
        obj["metadata"]["annotations"][annotations.POD_PRIORITY] = str(prio)
        api.create_pod(obj)
        r = sched.filter(obj, nodes_of(api))
        assert sched.bind("default", f"p{i}", r.nodes[0]) is None
    units = collect_units(api.list_pods(), sched.cache.assignments_snapshot())
    d = find_victims(
        sched.cache.views(), units, [make_pod_info("x", 4, priority=10)], incoming_priority=10
    )
    assert d is not None and len(d.victims) == 1
    assert d.victims[0].priority == 1  # cheapest victim chosen
