"""Paged KV serving: the Pallas paged-attention kernel against its dense
oracle, and the paged continuous batcher against per-sequence greedy —
plus the page-pool accounting invariants (reservation, sharing, reuse)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubegpu_tpu.models import TransformerLM, greedy_generate
from kubegpu_tpu.models.paging import PagedContinuousBatcher, PagedDecodeLM
from kubegpu_tpu.ops.paged_attention import (
    paged_chunk_attention,
    paged_decode_attention,
    reference_paged_attention,
    reference_paged_chunk_attention,
)

pytestmark = pytest.mark.slow

CFG = dict(vocab_size=61, num_layers=2, num_heads=4, hidden=32, max_seq=32)


def trained_params():
    model = TransformerLM(dtype=jnp.float32, **CFG)
    return model.init(jax.random.PRNGKey(0), jnp.ones((2, 8), jnp.int32))[
        "params"
    ]


def test_paged_kernel_matches_dense_reference():
    """Shuffled page tables, ragged lengths (page-aligned and not, incl.
    length 1 and a full table) — kernel output equals the gathered dense
    oracle."""
    rng = np.random.RandomState(0)
    b, h, hd, page, n_pages, pool = 4, 8, 128, 128, 4, 16
    q = jnp.asarray(rng.randn(b, h, hd), jnp.float32)
    kp = jnp.asarray(rng.randn(pool, h, page, hd), jnp.float32) * 0.3
    vp = jnp.asarray(rng.randn(pool, h, page, hd), jnp.float32) * 0.3
    table = jnp.asarray(
        np.stack([rng.choice(pool, n_pages, replace=False) for _ in range(b)]),
        jnp.int32,
    )
    lengths = jnp.asarray([1, 200, 256, 512], jnp.int32)
    out = paged_decode_attention(q, kp, vp, table, lengths)
    ref = reference_paged_attention(q, kp, vp, table, lengths)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_paged_chunk_kernel_matches_reference():
    """The multi-query verify kernel against its intra-window-causal
    oracle: shuffled tables, ragged lengths, including a window whose
    widest row spills onto a page the narrowest row never touches
    (lengths near a page boundary) and a length-1 slot."""
    rng = np.random.RandomState(1)
    b, h, hd, page, n_pages, pool, L = 4, 8, 128, 128, 4, 16, 5
    q = jnp.asarray(rng.randn(b, L, h, hd), jnp.float32)
    kp = jnp.asarray(rng.randn(pool, h, page, hd), jnp.float32) * 0.3
    vp = jnp.asarray(rng.randn(pool, h, page, hd), jnp.float32) * 0.3
    table = jnp.asarray(
        np.stack([rng.choice(pool, n_pages, replace=False) for _ in range(b)]),
        jnp.int32,
    )
    # 254/508: rows 2..4 of the window cross onto the next page
    lengths = jnp.asarray([1, 200, 254, 508], jnp.int32)
    out = paged_chunk_attention(q, kp, vp, table, lengths)
    ref = reference_paged_chunk_attention(q, kp, vp, table, lengths)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_paged_chunk_kernel_rows_bit_match_decode_kernel():
    """Window row j must equal the single-query kernel at lengths+j
    BIT-EXACTLY (not just to tolerance): both fold pages through the
    same online-softmax recipe in f32 scratch, so the verify program's
    per-position outputs are the decode program's outputs — the kernel
    half of the spec-serving losslessness argument (the other half, the
    projection GEMMs, is covered by the fp32 batcher identity tests)."""
    rng = np.random.RandomState(2)
    b, h, hd, page, n_pages, pool, L = 3, 4, 128, 128, 4, 12, 3
    q = jnp.asarray(rng.randn(b, L, h, hd), jnp.float32)
    kp = jnp.asarray(rng.randn(pool, h, page, hd), jnp.float32) * 0.3
    vp = jnp.asarray(rng.randn(pool, h, page, hd), jnp.float32) * 0.3
    table = jnp.asarray(
        np.stack([rng.choice(pool, n_pages, replace=False) for _ in range(b)]),
        jnp.int32,
    )
    lengths = jnp.asarray([1, 127, 300], jnp.int32)
    out = np.asarray(paged_chunk_attention(q, kp, vp, table, lengths))
    for j in range(L):
        single = np.asarray(
            paged_decode_attention(q[:, j], kp, vp, table, lengths + j)
        )
        assert (out[:, j] == single).all(), f"window row {j} diverged"
    # L=1 is the degenerate window: one row, same causal limit
    one = np.asarray(paged_chunk_attention(q[:, :1], kp, vp, table, lengths))
    single0 = np.asarray(paged_decode_attention(q[:, 0], kp, vp, table, lengths))
    assert (one[:, 0] == single0).all()


def test_paged_decode_lm_param_tree_matches_training_model():
    """The paged twin accepts TransformerLM checkpoints verbatim (the same
    contract DecodeLM keeps)."""
    params = trained_params()
    paged = PagedDecodeLM(dtype=jnp.float32, **CFG)
    hd = CFG["hidden"] // CFG["num_heads"]
    pools = [
        (
            jnp.zeros((4, CFG["num_heads"], 8, hd), jnp.float32),
            jnp.zeros((4, CFG["num_heads"], 8, hd), jnp.float32),
        )
        for _ in range(CFG["num_layers"])
    ]
    table = jnp.zeros((2, 4), jnp.int32)
    pos = jnp.zeros((2,), jnp.int32)
    pparams = paged.init(
        jax.random.PRNGKey(0), jnp.zeros((2, 1), jnp.int32), pools, table, pos
    )["params"]
    assert jax.tree.structure(params) == jax.tree.structure(pparams)
    same = jax.tree.map(lambda a, b: a.shape == b.shape, params, pparams)
    assert all(jax.tree.leaves(same))


def make_batcher(params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("prompt_pad", 8)
    kw.setdefault("page_size", 8)
    kw.setdefault("pool_pages", 12)
    return PagedContinuousBatcher(params, dtype=jnp.float32, **CFG, **kw)


def test_paged_batcher_matches_per_sequence_greedy():
    """The full paged path (dense admit prefill -> page scatter -> paged
    kernel decode steps with slot reuse) must reproduce per-sequence
    greedy_generate token-for-token, and the pool must come back whole."""
    params = trained_params()
    rng = np.random.RandomState(0)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=n), dtype=np.int32)
        for n in (3, 5, 7, 4, 6)
    ]
    budgets = [6, 3, 5, 7, 4]
    expected = {}
    for i, (p, n) in enumerate(zip(prompts, budgets)):
        out = greedy_generate(
            params, jnp.asarray(p)[None, :], n, dtype=jnp.float32, **CFG
        )
        expected[i] = list(np.asarray(out)[0, len(p):])
    cb = make_batcher(params)
    got = cb.run(prompts, budgets)
    assert set(got) == set(expected)
    for i in expected:
        assert got[i] == expected[i], (
            f"seq {i}: paged {got[i]} != greedy {expected[i]}"
        )
    assert cb.stats["admits"] == 5
    # every reserved page returned; the dump page was never allocated
    assert cb.free_pages == set(range(1, cb.pool_pages))
    # sharing evidence: the pool high watermark stayed at the live mix's
    # need, far under slots x max_pages
    assert 0 < cb.stats["peak_pages"] <= 2 * cb.max_pages


def test_paged_batcher_defers_admission_until_pages_free():
    """A pool too small for two live sequences serves them one after the
    other (FIFO deferral), still token-exact; a request whose worst case
    exceeds the whole pool is rejected up front."""
    params = trained_params()
    rng = np.random.RandomState(1)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=4), dtype=np.int32)
        for _ in range(3)
    ]
    budgets = [6, 6, 6]
    expected = {
        i: list(
            np.asarray(
                greedy_generate(
                    params, jnp.asarray(p)[None, :], n, dtype=jnp.float32,
                    **CFG,
                )
            )[0, len(p):]
        )
        for i, (p, n) in enumerate(zip(prompts, budgets))
    }
    # each request needs ceil((4+6)/8)=2 pages; 3 allocatable pages admit
    # only one sequence at a time alongside a partial second
    cb = make_batcher(params, pool_pages=4)
    got = cb.run(prompts, budgets)
    for i in expected:
        assert got[i] == expected[i]
    assert cb.free_pages == set(range(1, 4))
    with pytest.raises(ValueError, match="pages"):
        big = np.array(rng.randint(0, CFG["vocab_size"], size=8), np.int32)
        make_batcher(params, pool_pages=3).run([big], [20])


def test_paged_batcher_serves_int8_quantized_checkpoints():
    """quant=True: the paged path serves quantize_params_int8 trees and
    matches per-sequence int8 greedy token-for-token (fp32 activations)."""
    from kubegpu_tpu.models.decoding import quantize_params_int8

    params = trained_params()
    qparams = quantize_params_int8(params)
    rng = np.random.RandomState(2)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=n), dtype=np.int32)
        for n in (3, 6)
    ]
    budgets = [5, 4]
    expected = {
        i: list(
            np.asarray(
                greedy_generate(
                    qparams, jnp.asarray(p)[None, :], n, dtype=jnp.float32,
                    quant=True, **CFG,
                )
            )[0, len(p):]
        )
        for i, (p, n) in enumerate(zip(prompts, budgets))
    }
    cb = make_batcher(qparams, quant=True)
    got = cb.run(prompts, budgets)
    for i in expected:
        assert got[i] == expected[i]


def test_paged_batcher_rejects_misaligned_prompt_pad():
    params = trained_params()
    with pytest.raises(ValueError, match="multiple of"):
        make_batcher(params, prompt_pad=6, page_size=8)
