"""Multislice placement tests: gangs spanning DCN-connected slices
(grpalloc.multislice), the megascale env contract, and the hybrid
DCN x ICI workload mesh — all on fabricated topologies (SURVEY.md §4)."""

from typing import Dict

import pytest

from kubegpu_tpu.crishim.daemon import ShimDaemon
from kubegpu_tpu.crishim.inject import InjectionError, multislice_env
from kubegpu_tpu.grpalloc import build_slice_views, fit_gang_multislice
from kubegpu_tpu.plugins import Advertiser, FakeSlice
from kubegpu_tpu.scheduler import Scheduler
from kubegpu_tpu.types import RES_TPU, annotations, is_contiguous_submesh
from kubegpu_tpu.types.info import ContainerInfo, NodeInfo, PodInfo
from kubegpu_tpu.types.topology import SliceTopology, TpuGeneration
from kubegpu_tpu.utils import InMemoryApiServer
from kubegpu_tpu.utils.metrics import Metrics


def make_nodes(slice_id, mesh=(4, 4), host_block=(2, 2)) -> Dict[str, NodeInfo]:
    topo = SliceTopology.build(slice_id, TpuGeneration.V5E, mesh, host_block=host_block)
    nodes = {}
    for h in topo.hosts():
        n = NodeInfo(
            name=h,
            slice_id=slice_id,
            generation=topo.generation,
            mesh_shape=topo.mesh_shape,
            wrap=topo.wrap,
            chips=topo.host_chips(h),
        )
        n.rebuild_capacity()
        nodes[h] = n
    return nodes


def two_slice_views():
    nodes = {**make_nodes("sa"), **make_nodes("sb")}
    return build_slice_views(nodes.values())


def gang(n, chips, multislice=False):
    return [
        PodInfo(
            name=f"w{i}",
            containers=[ContainerInfo(name="main", tpu_chips=chips)],
            pod_group="g",
            pod_group_size=n,
            allow_multislice=multislice,
        )
        for i in range(n)
    ]


# -- allocator --------------------------------------------------------------

def test_single_slice_preferred_when_it_fits():
    views = two_slice_views()
    res = fit_gang_multislice(views, gang(4, 4, multislice=True), allow_multislice=True)
    assert res.success and res.num_slices == 1
    slice_ids = {a.slice_id for a in res.per_pod.values()}
    assert len(slice_ids) == 1


def test_multislice_requires_opt_in():
    views = two_slice_views()  # 2 x 16 chips; 32-chip gang fits neither alone
    res = fit_gang_multislice(views, gang(8, 4), allow_multislice=False)
    assert not res.success
    assert annotations.POD_MULTISLICE in res.reason  # actionable hint


def test_multislice_spans_two_slices_with_equal_shapes():
    views = two_slice_views()
    pods = gang(8, 4, multislice=True)
    res = fit_gang_multislice(views, pods, allow_multislice=True)
    assert res.success, res.reason
    assert sorted(res.slice_ids) == ["sa", "sb"]
    assert res.slice_shape is not None
    per_slice = {}
    for a in res.per_pod.values():
        per_slice.setdefault(a.slice_id, set()).update(
            c.coords for c in a.all_chips()
        )
    assert set(per_slice) == {"sa", "sb"}
    for sid, coords in per_slice.items():
        assert len(coords) == 16  # whole slice each
        assert is_contiguous_submesh(coords, (4, 4))
        # the common rectangle shape really is the advertised one
        from kubegpu_tpu.types.topology import coords_bounding_box

        _, shape = coords_bounding_box(coords)
        assert shape == res.slice_shape
    # every pod's own chips are host-local and contiguous
    for a in res.per_pod.values():
        hosts = {c.host for c in a.all_chips()}
        assert len(hosts) == 1
        assert is_contiguous_submesh({c.coords for c in a.all_chips()}, (4, 4))


def test_multislice_minimizes_slice_count():
    # 4 slices available, but 2 suffice for 32 chips -> exactly 2 used
    nodes = {}
    for sid in ("sa", "sb", "sc", "sd"):
        nodes.update(make_nodes(sid))
    views = build_slice_views(nodes.values())
    res = fit_gang_multislice(views, gang(8, 4, multislice=True), allow_multislice=True)
    assert res.success and res.num_slices == 2


def test_multislice_rejects_heterogeneous_pods():
    views = two_slice_views()
    pods = gang(7, 4, multislice=True) + [
        PodInfo(
            name="odd",
            containers=[ContainerInfo(name="main", tpu_chips=2)],
            pod_group="g",
            pod_group_size=8,
            allow_multislice=True,
        )
    ]
    res = fit_gang_multislice(views, pods, allow_multislice=True)
    assert not res.success
    assert "homogeneous" in res.reason


# -- scheduler e2e over two advertised slices -------------------------------

def two_slice_cluster():
    api = InMemoryApiServer()
    slices = {
        sid: FakeSlice(slice_id=sid, mesh_shape=(4, 4), host_block=(2, 2))
        for sid in ("sa", "sb")
    }
    for fs in slices.values():
        for h, p in fs.providers().items():
            Advertiser(p, api).advertise_once()
    return api, slices


def pod_obj(name, chips, ann, subdomain=None):
    spec = {
        "containers": [
            {"name": "main", "resources": {"limits": {RES_TPU: str(chips)}}}
        ]
    }
    if subdomain:
        spec["subdomain"] = subdomain
    return {
        "metadata": {
            "name": name,
            "namespace": "default",
            "uid": f"uid-{name}",
            "annotations": dict(ann),
        },
        "spec": spec,
    }


def multislice_pod(name, chips, group, size):
    return pod_obj(
        name, chips,
        {
            annotations.POD_GROUP: group,
            annotations.POD_GROUP_SIZE: str(size),
            annotations.POD_MULTISLICE: "true",
        },
        subdomain="ms-svc",
    )


def schedule_all(api, sched, pods):
    names = sorted(n["metadata"]["name"] for n in api.list_nodes())
    for obj in pods:
        name = obj["metadata"]["name"]
        r = sched.filter(obj, names)
        assert r.nodes, f"{name}: {r.failed or r.error}"
        scores = dict(sched.prioritize(obj, r.nodes))
        target = max(r.nodes, key=lambda n: (scores.get(n, 0), n))
        assert sched.bind("default", name, target) is None, name


def test_scheduler_binds_multislice_gang_across_slices():
    api, _ = two_slice_cluster()
    sched = Scheduler(api, metrics=Metrics())
    sched.cache.refresh()
    pods = [multislice_pod(f"m{i}", 4, "ms", 8) for i in range(8)]
    for obj in pods:
        api.create_pod(obj)
    schedule_all(api, sched, pods)
    slice_ids = set()
    for i in range(8):
        a = annotations.assignment_from_pod(api.get_pod("default", f"m{i}"))
        assert a is not None and a.all_chips()
        slice_ids.add(a.slice_id)
    assert slice_ids == {"sa", "sb"}


def test_scheduler_gang_without_opt_in_stays_unscheduled():
    api, _ = two_slice_cluster()
    sched = Scheduler(api, metrics=Metrics())
    sched.cache.refresh()
    pods = [multislice_pod(f"m{i}", 4, "ms", 8) for i in range(8)]
    for obj in pods:
        del obj["metadata"]["annotations"][annotations.POD_MULTISLICE]
        api.create_pod(obj)
    names = sorted(n["metadata"]["name"] for n in api.list_nodes())
    r = sched.filter(pods[0], names)
    assert not r.nodes
    # and the failure explains the fix
    assert any(annotations.POD_MULTISLICE in msg for msg in r.failed.values())


# -- megascale env injection ------------------------------------------------

def test_multislice_env_contract():
    pod = PodInfo(name="m1", namespace="default", pod_group="ms")
    member_slices = {"m0": "sa", "m1": "sb", "m2": "sa", "m3": "sb"}
    env = multislice_env(pod, member_slices, subdomain="ms-svc")
    assert env["MEGASCALE_NUM_SLICES"] == "2"
    assert env["MEGASCALE_SLICE_ID"] == "1"  # sb sorts after sa
    assert env["MEGASCALE_COORDINATOR_ADDRESS"].startswith("m0.ms-svc.default.svc:")
    # single-slice gang: no megascale vars at all
    assert multislice_env(pod, {"m0": "sa", "m1": "sa"}) == {}


def test_multislice_env_coordinator_is_on_slice_zero():
    # the globally-first NAME sits on the second slice: the coordinator must
    # be the first member ON slice 0, not the first name overall
    pod = PodInfo(name="b0", namespace="default", pod_group="ms")
    member_slices = {"a1": "sb", "a2": "sb", "b0": "sa", "b1": "sa"}
    env = multislice_env(pod, member_slices)
    assert env["MEGASCALE_COORDINATOR_ADDRESS"].startswith("b0:")
    assert env["MEGASCALE_SLICE_ID"] == "0"


def test_crishim_injects_megascale_for_multislice_gang():
    api, slices = two_slice_cluster()
    sched = Scheduler(api, metrics=Metrics())
    sched.cache.refresh()
    pods = [multislice_pod(f"m{i}", 4, "ms", 8) for i in range(8)]
    for obj in pods:
        api.create_pod(obj)
    schedule_all(api, sched, pods)
    a0 = annotations.assignment_from_pod(api.get_pod("default", "m0"))
    fs = slices[a0.slice_id]
    daemon = ShimDaemon(api, fs.provider_for(a0.node))
    inj = daemon.decide(
        "default", "m0", "main",
        api.get_pod("default", "m0")["metadata"]["annotations"], "m0",
    )
    assert inj is not None
    assert inj.env["MEGASCALE_NUM_SLICES"] == "2"
    assert inj.env["MEGASCALE_SLICE_ID"] in ("0", "1")
    assert inj.env["JAX_NUM_PROCESSES"] == "8"
    assert inj.env["TPU_VISIBLE_CHIPS"]


def test_multislice_worker_table_is_slice_local():
    # ADVICE r1 (high): the libtpu worker table is PER SLICE — a gang-global
    # TPU_WORKER_HOSTNAMES would make every slice's libtpu bootstrap one ICI
    # topology spanning DCN and hang at TPU init.  JAX_* stays gang-global.
    from kubegpu_tpu.crishim.inject import worker_env

    member_slices = {"m0": "sa", "m1": "sa", "m2": "sb", "m3": "sb"}
    members = sorted(member_slices)
    envs = {}
    for name in members:
        pod = PodInfo(name=name, namespace="default", pod_group="ms")
        envs[name] = worker_env(pod, members, member_slices=member_slices)
    # slice-local table: ids restart at 0 per slice, hostnames list only
    # the pod's own slice's members
    assert envs["m0"]["TPU_WORKER_ID"] == "0"
    assert envs["m1"]["TPU_WORKER_ID"] == "1"
    assert envs["m2"]["TPU_WORKER_ID"] == "0"  # first on slice sb
    assert envs["m3"]["TPU_WORKER_ID"] == "1"
    assert envs["m2"]["TPU_WORKER_HOSTNAMES"] == "m2,m3"
    assert envs["m0"]["TPU_WORKER_HOSTNAMES"] == "m0,m1"
    # jax.distributed spans slices over DCN: global table unchanged
    for name in members:
        assert envs[name]["JAX_NUM_PROCESSES"] == "4"
        assert envs[name]["JAX_PROCESS_ID"] == str(members.index(name))
    assert len({e["JAX_COORDINATOR_ADDRESS"] for e in envs.values()}) == 1
    # single-slice gang: global and local tables coincide (no regression)
    env = worker_env(
        PodInfo(name="m1", namespace="default", pod_group="g"),
        ["m0", "m1"],
        member_slices={"m0": "sa", "m1": "sa"},
    )
    assert env["TPU_WORKER_HOSTNAMES"] == "m0,m1"
    assert env["TPU_WORKER_ID"] == "1"


def test_crishim_multislice_injection_has_slice_local_table():
    # end-to-end through the shim: a scheduled 2-slice gang member's
    # injected TPU_WORKER_HOSTNAMES covers exactly its own slice's members
    api, slices = two_slice_cluster()
    sched = Scheduler(api, metrics=Metrics())
    sched.cache.refresh()
    pods = [multislice_pod(f"m{i}", 4, "ms", 8) for i in range(8)]
    for obj in pods:
        api.create_pod(obj)
    schedule_all(api, sched, pods)
    by_slice: Dict[str, set] = {}
    for i in range(8):
        a = annotations.assignment_from_pod(api.get_pod("default", f"m{i}"))
        by_slice.setdefault(a.slice_id, set()).add(f"m{i}")
    a0 = annotations.assignment_from_pod(api.get_pod("default", "m0"))
    daemon = ShimDaemon(api, slices[a0.slice_id].provider_for(a0.node))
    inj = daemon.decide(
        "default", "m0", "main",
        api.get_pod("default", "m0")["metadata"]["annotations"], "m0",
    )
    hosts = inj.env["TPU_WORKER_HOSTNAMES"].split(",")
    local = by_slice[a0.slice_id]
    assert len(hosts) == len(local) == 4
    assert {h.split(".")[0] for h in hosts} == local
    assert int(inj.env["TPU_WORKER_ID"]) < 4
    assert inj.env["JAX_NUM_PROCESSES"] == "8"


def test_crishim_refuses_partial_multislice_table():
    api, slices = two_slice_cluster()
    sched = Scheduler(api, metrics=Metrics())
    sched.cache.refresh()
    pods = [multislice_pod(f"m{i}", 4, "ms", 8) for i in range(8)]
    for obj in pods:
        api.create_pod(obj)
    schedule_all(api, sched, pods)
    # strip one sibling's assignment: the slice table is incomplete
    victim = api.get_pod("default", "m7")
    del victim["metadata"]["annotations"][annotations.POD_ASSIGNMENT]
    api.delete_pod("default", "m7")
    api.create_pod(victim)
    a0 = annotations.assignment_from_pod(api.get_pod("default", "m0"))
    daemon = ShimDaemon(api, slices[a0.slice_id].provider_for(a0.node))
    with pytest.raises(InjectionError):
        daemon.decide(
            "default", "m0", "main",
            api.get_pod("default", "m0")["metadata"]["annotations"], "m0",
        )


def test_unrecoverable_member_slice_fails_with_explicit_reason():
    # ADVICE r1: a bound member whose slice cannot be recovered (assignment
    # annotation cleared mid-eviction, no cache reservation) must fail the
    # anchored re-plan with the REAL reason, not a misleading "cannot split
    # equally" from undercounted layout math
    api, _ = two_slice_cluster()
    sched = Scheduler(api, metrics=Metrics())
    sched.cache.refresh()
    pods = [multislice_pod(f"m{i}", 4, "ms", 8) for i in range(8)]
    for obj in pods:
        api.create_pod(obj)
    schedule_all(api, sched, pods)
    # m3 fully evicted; m4 caught mid-eviction: annotation cleared but the
    # pod lingers bound (Terminating on a real cluster)
    api.delete_pod("default", "m3")
    api.patch_pod_annotations("default", "m4", {annotations.POD_ASSIGNMENT: ""})
    sched.cache.refresh()
    replacement = multislice_pod("m3", 4, "ms", 8)
    api.create_pod(replacement)
    names = sorted(n["metadata"]["name"] for n in api.list_nodes())
    r = sched.filter(replacement, names)
    assert not r.nodes
    msgs = list(r.failed.values())
    assert any("no recoverable slice" in m and "m4" in m for m in msgs), msgs
    assert not any("split equally" in m for m in msgs)


def test_replacement_waits_when_home_slice_chips_were_taken():
    # code-review r2 regression: with the anchored path accidentally dead,
    # a replacement whose gang's home-slice chips were snatched by a
    # competitor was freshly planned onto the OTHER slice instead of
    # waiting.  Correct behavior: the anchored refit fails loudly.
    api, _ = two_slice_cluster()
    sched = Scheduler(api, metrics=Metrics())
    sched.cache.refresh()
    pods = [multislice_pod(f"g{i}", 4, "sg", 4) for i in range(4)]
    for obj in pods:
        api.create_pod(obj)
    schedule_all(api, sched, pods)
    home = annotations.assignment_from_pod(api.get_pod("default", "g0")).slice_id
    api.delete_pod("default", "g2")
    sched.cache.refresh()
    # competitor pinned to the home slice takes the freed chips
    competitor = pod_obj(
        "thief", 4, {annotations.POD_SLICE_SELECTOR: home}
    )
    api.create_pod(competitor)
    names = sorted(n["metadata"]["name"] for n in api.list_nodes())
    r = sched.filter(competitor, names)
    assert r.nodes, r.failed
    assert sched.bind("default", "thief", r.nodes[0]) is None
    # the gang replacement must NOT drift to the other (empty) slice
    replacement = multislice_pod("g2", 4, "sg", 4)
    api.create_pod(replacement)
    r = sched.filter(replacement, names)
    assert not r.nodes, (
        f"replacement was planned onto "
        f"{ {annotations.assignment_from_pod(api.get_pod('default', 'g2'))} }"
    )
    assert any("cannot rejoin" in m or home in m for m in r.failed.values()), r.failed


def test_all_members_unrecoverable_still_waits():
    # code-review r2: scheduler restart mid-gang-eviction — EVERY bound
    # member's annotation was cleared, so the recoverable layout is empty.
    # A fresh plan would bind replacements to arbitrary slices, diverging
    # from the Terminating siblings; the plan must wait instead.
    api, _ = two_slice_cluster()
    sched = Scheduler(api, metrics=Metrics())
    sched.cache.refresh()
    pods = [multislice_pod(f"m{i}", 4, "ms", 8) for i in range(8)]
    for obj in pods:
        api.create_pod(obj)
    schedule_all(api, sched, pods)
    api.delete_pod("default", "m3")
    for i in range(8):
        if i != 3:
            api.patch_pod_annotations(
                "default", f"m{i}", {annotations.POD_ASSIGNMENT: ""}
            )
    # restart: a new scheduler has no cache reservations to recover slices
    sched2 = Scheduler(api, metrics=Metrics())
    sched2.cache.refresh()
    replacement = multislice_pod("m3", 4, "ms", 8)
    api.create_pod(replacement)
    names = sorted(n["metadata"]["name"] for n in api.list_nodes())
    r = sched2.filter(replacement, names)
    assert not r.nodes
    assert any("no recoverable slice" in m for m in r.failed.values()), r.failed


# -- partial re-plan anchoring ----------------------------------------------

def test_replanned_member_rejoins_its_gangs_slice():
    # a dead member's replacement must land on the slice its gang already
    # occupies — anywhere else and its baked-in megascale table would
    # disagree with every running sibling's
    api, _ = two_slice_cluster()
    sched = Scheduler(api, metrics=Metrics())
    sched.cache.refresh()
    pods = [multislice_pod(f"m{i}", 4, "ms", 8) for i in range(8)]
    for obj in pods:
        api.create_pod(obj)
    schedule_all(api, sched, pods)
    victim_slice = annotations.assignment_from_pod(api.get_pod("default", "m3")).slice_id
    api.delete_pod("default", "m3")
    sched.cache.refresh()  # chips freed via annotation replay
    replacement = multislice_pod("m3", 4, "ms", 8)
    api.create_pod(replacement)
    names = sorted(n["metadata"]["name"] for n in api.list_nodes())
    r = sched.filter(replacement, names)
    assert r.nodes, (r.failed, r.error)
    # every feasible node is on the dead member's slice
    assert all(n.startswith(victim_slice) for n in r.nodes)
    target = sorted(r.nodes)[0]
    assert sched.bind("default", "m3", target) is None
    a = annotations.assignment_from_pod(api.get_pod("default", "m3"))
    assert a.slice_id == victim_slice


def test_replanned_member_stays_on_single_slice_gang_slice():
    # same anchoring for a single-slice gang: the replacement cannot drift
    # to the emptier slice (rendezvous assumes one ICI domain)
    api, _ = two_slice_cluster()
    sched = Scheduler(api, metrics=Metrics())
    sched.cache.refresh()
    pods = [multislice_pod(f"g{i}", 4, "sg", 4) for i in range(4)]
    for obj in pods:  # 16 chips: fits exactly one slice
        api.create_pod(obj)
    schedule_all(api, sched, pods)
    home = annotations.assignment_from_pod(api.get_pod("default", "g0")).slice_id
    api.delete_pod("default", "g1")
    sched.cache.refresh()
    replacement = multislice_pod("g1", 4, "sg", 4)
    api.create_pod(replacement)
    names = sorted(n["metadata"]["name"] for n in api.list_nodes())
    r = sched.filter(replacement, names)
    assert r.nodes and all(n.startswith(home) for n in r.nodes)


# -- lenient sibling parsing ------------------------------------------------

def test_malformed_sibling_quantity_does_not_wedge_gang_injection():
    # one bound member's extended resource is corrupted after bind: the
    # sibling must stay VISIBLE to gang gathering (lenient list parse), or
    # every member's CreateContainer fails forever
    api, slices = two_slice_cluster()
    sched = Scheduler(api, metrics=Metrics())
    sched.cache.refresh()
    pods = [multislice_pod(f"m{i}", 4, "ms", 8) for i in range(8)]
    for obj in pods:
        api.create_pod(obj)
    schedule_all(api, sched, pods)
    bad = api.get_pod("default", "m7")
    bad["spec"]["containers"][0]["resources"]["limits"]["example.com/npu"] = "2k"
    api.delete_pod("default", "m7")
    api.create_pod(bad)
    a0 = annotations.assignment_from_pod(api.get_pod("default", "m0"))
    daemon = ShimDaemon(api, slices[a0.slice_id].provider_for(a0.node))
    inj = daemon.decide(
        "default", "m0", "main",
        api.get_pod("default", "m0")["metadata"]["annotations"], "m0",
    )
    assert inj is not None
    assert inj.env["JAX_NUM_PROCESSES"] == "8"  # m7 still in the table
    assert inj.env["MEGASCALE_NUM_SLICES"] == "2"


# -- zero-chip gang members -------------------------------------------------

def zero_chip_pod(name, group, size):
    return {
        "metadata": {
            "name": name,
            "namespace": "default",
            "uid": f"uid-{name}",
            "annotations": {
                annotations.POD_GROUP: group,
                annotations.POD_GROUP_SIZE: str(size),
                annotations.POD_MULTISLICE: "true",
            },
        },
        "spec": {"containers": [{"name": "main", "resources": {}}]},
    }


def test_zero_chip_member_does_not_wedge_multislice_injection():
    # a chipless coordinator pod in the gang binds plain (no assignment
    # annotation, it owns no chips) — the chip workers' megascale table must
    # exclude it instead of waiting for an annotation that never comes
    api, slices = two_slice_cluster()
    sched = Scheduler(api, metrics=Metrics())
    sched.cache.refresh()
    pods = [multislice_pod(f"m{i}", 4, "ms", 9) for i in range(8)]
    coord = zero_chip_pod("coord", "ms", 9)
    for obj in pods + [coord]:
        api.create_pod(obj)
    schedule_all(api, sched, pods)
    # the coordinator schedules plain: any node passes filter
    names = sorted(n["metadata"]["name"] for n in api.list_nodes())
    r = sched.filter(coord, names)
    assert r.nodes == names
    assert sched.bind("default", "coord", names[0]) is None
    a0 = annotations.assignment_from_pod(api.get_pod("default", "m0"))
    daemon = ShimDaemon(api, slices[a0.slice_id].provider_for(a0.node))
    inj = daemon.decide(
        "default", "m0", "main",
        api.get_pod("default", "m0")["metadata"]["annotations"], "m0",
    )
    assert inj is not None
    assert inj.env["MEGASCALE_NUM_SLICES"] == "2"
    assert inj.env["JAX_NUM_PROCESSES"] == "9"  # coordinator in the table


def test_layout_refit_counts_chip_members_only():
    from kubegpu_tpu.grpalloc.multislice import fit_gang_into_layout

    views = two_slice_views()
    # simulate: gang had 8 chip members 4+4 over two slices; one on sb died
    # freeing its host's 2x2 block
    views["sa"].used = frozenset(views["sa"].chips)  # sa fully occupied
    hole = {(2, 0), (2, 1), (3, 0), (3, 1)}  # one host's block on sb
    views["sb"].used = frozenset(set(views["sb"].chips) - hole)
    pending = gang(1, 4, multislice=True) + [
        PodInfo(name="zz-coord", containers=[ContainerInfo(name="main")],
                pod_group="g", pod_group_size=10)
    ]
    res = fit_gang_into_layout(views, pending, {"sa": 4, "sb": 3})
    assert res.success, res.reason
    chip_assignment = res.per_pod["default/w0"]
    assert chip_assignment.slice_id == "sb"
    assert len(chip_assignment.all_chips()) == 4
    assert res.per_pod["default/zz-coord"].all_chips() == []


def test_exact_hole_refit_restores_rectangular_union():
    """VERDICT r1 #5: a replacement must prefer the dead member's freed
    coords so the gang's union stays rectangular — best-score refit alone
    provably does not (documented by the fit_gang probe below)."""
    from kubegpu_tpu.grpalloc.allocator import fit_gang
    from kubegpu_tpu.grpalloc.multislice import fit_gang_into_layout

    views = build_slice_views(make_nodes("sa").values())
    v = views["sa"]
    occupied = frozenset({(0, 0), (0, 1), (1, 0)})  # survivors of a 2x2 gang
    v.used = occupied  # the dead member's (1, 1) is free again

    # the old path (plain best-score fit_gang) picks a non-hole chip:
    g = fit_gang(v, gang(4, 1)[3:])
    old_pick = {c.coords for c in g.per_pod["default/w3"].all_chips()}
    assert not is_contiguous_submesh(old_pick | occupied, (4, 4))

    res = fit_gang_into_layout(views, gang(4, 1)[3:], {"sa": 3}, {"sa": occupied})
    assert res.success, res.reason
    new_pick = {c.coords for c in res.per_pod["default/w3"].all_chips()}
    assert new_pick == {(1, 1)}
    assert is_contiguous_submesh(new_pick | occupied, (4, 4))


def test_exact_hole_refit_falls_back_when_hole_taken():
    from kubegpu_tpu.grpalloc.multislice import fit_gang_into_layout

    views = build_slice_views(make_nodes("sa").values())
    v = views["sa"]
    occupied = frozenset({(0, 0), (0, 1), (1, 0)})
    v.used = occupied | {(1, 1)}  # another tenant stole the hole
    res = fit_gang_into_layout(views, gang(4, 1)[3:], {"sa": 3}, {"sa": occupied})
    assert res.success, res.reason  # best-score fallback still places it
    pick = {c.coords for c in res.per_pod["default/w3"].all_chips()}
    assert pick and (1, 1) not in pick


def test_exact_hole_refit_multislice_deficit():
    # gang 4+4 over two slices; one sb member (2 chips at (0,0),(0,1)... )
    # died — the sb replacement must restore sb's rectangle
    from kubegpu_tpu.grpalloc.multislice import fit_gang_into_layout

    views = two_slice_views()
    sa_occ = frozenset({(0, 0), (0, 1), (1, 0), (1, 1),
                        (2, 0), (2, 1), (3, 0), (3, 1)})  # 4 members x 2
    sb_occ = frozenset({(0, 0), (0, 1), (1, 0), (1, 1), (2, 0), (2, 1)})
    views["sa"].used = sa_occ
    views["sb"].used = sb_occ  # (3, 0), (3, 1) freed by the dead member
    pending = gang(8, 2, multislice=True)[7:]
    res = fit_gang_into_layout(
        views, pending, {"sa": 4, "sb": 3},
        {"sa": sa_occ, "sb": sb_occ},
    )
    assert res.success, res.reason
    pick = {c.coords for c in res.per_pod["default/w7"].all_chips()}
    assert res.per_pod["default/w7"].slice_id == "sb"
    assert pick == {(3, 0), (3, 1)}
    assert is_contiguous_submesh(pick | sb_occ, (4, 4))


def test_replacement_pod_reclaims_dead_members_chips_end_to_end():
    """Scheduler-level: delete one member of a bound gang, recreate it, and
    the anchored re-plan hands the replacement EXACTLY the freed coords —
    the gang's rectangle survives member churn."""
    api = InMemoryApiServer()
    fs = FakeSlice(slice_id="v5e-16", mesh_shape=(4, 4), host_block=(2, 2))
    for prov in fs.providers().values():
        Advertiser(prov, api).advertise_once()
    sched = Scheduler(api, metrics=Metrics())
    sched.cache.refresh()
    pods = [
        {
            "metadata": {
                "name": f"w{i}", "namespace": "default",
                "annotations": {
                    annotations.POD_GROUP: "g",
                    annotations.POD_GROUP_SIZE: "4",
                },
            },
            "spec": {"containers": [
                {"name": "m", "resources": {"limits": {RES_TPU: "1"}}}]},
        }
        for i in range(4)
    ]
    for obj in pods:
        api.create_pod(obj)
    schedule_all(api, sched, pods)
    before = {
        name: {c.coords for c in
               annotations.assignment_from_pod(api.get_pod("default", name)).all_chips()}
        for name in ("w0", "w1", "w2", "w3")
    }
    union_before = set().union(*before.values())
    assert is_contiguous_submesh(union_before, (4, 4))

    # the member dies (controller will recreate it)
    victim = api.get_pod("default", "w2")
    api.delete_pod("default", "w2")
    sched.on_pod_deleted(victim)
    api.create_pod({
        "metadata": {
            "name": "w2", "namespace": "default",
            "annotations": {
                annotations.POD_GROUP: "g",
                annotations.POD_GROUP_SIZE: "4",
            },
        },
        "spec": {"containers": [
            {"name": "m", "resources": {"limits": {RES_TPU: "1"}}}]},
    })
    schedule_all(api, sched, [api.get_pod("default", "w2")])
    after = {c.coords for c in
             annotations.assignment_from_pod(api.get_pod("default", "w2")).all_chips()}
    assert after == before["w2"], (after, before["w2"])


def test_malformed_pending_sibling_keeps_gang_waiting():
    # a PENDING member with an unparseable quantity can never pass its own
    # strict filter — the gang must wait, not plan around it as a 0-chip
    # ghost and strand the others' chips
    api, _ = two_slice_cluster()
    sched = Scheduler(api, metrics=Metrics())
    sched.cache.refresh()
    pods = [multislice_pod(f"m{i}", 4, "ms", 8) for i in range(7)]
    bad = multislice_pod("m7", 4, "ms", 8)
    bad["spec"]["containers"][0]["resources"]["limits"][RES_TPU] = "four"
    for obj in pods + [bad]:
        api.create_pod(obj)
    names = sorted(n["metadata"]["name"] for n in api.list_nodes())
    r = sched.filter(pods[0], names)
    assert not r.nodes
    assert any("waiting for members" in m for m in r.failed.values())


# -- slice selectors (tenant pinning) ---------------------------------------

def selector_pod(name, chips, slices, group=None, size=1, priority=0):
    ann = {annotations.POD_SLICE_SELECTOR: ",".join(slices)}
    if group:
        ann[annotations.POD_GROUP] = group
        ann[annotations.POD_GROUP_SIZE] = str(size)
    if priority:
        ann[annotations.POD_PRIORITY] = str(priority)
    return pod_obj(name, chips, ann)


def test_slice_selector_pins_plain_pod():
    api, _ = two_slice_cluster()
    sched = Scheduler(api, metrics=Metrics())
    sched.cache.refresh()
    obj = selector_pod("pinned", 2, ["sb"])
    api.create_pod(obj)
    names = sorted(n["metadata"]["name"] for n in api.list_nodes())
    r = sched.filter(obj, names)
    assert r.nodes and all(n.startswith("sb") for n in r.nodes)
    assert any("slice-selector" in m for m in r.failed.values())


def test_slice_selector_pins_gang():
    api, _ = two_slice_cluster()
    sched = Scheduler(api, metrics=Metrics())
    sched.cache.refresh()
    pods = [selector_pod(f"t{i}", 4, ["sb"], group="tb", size=4) for i in range(4)]
    for obj in pods:
        api.create_pod(obj)
    schedule_all(api, sched, pods)
    for i in range(4):
        a = annotations.assignment_from_pod(api.get_pod("default", f"t{i}"))
        assert a.slice_id == "sb"


def test_slice_selector_unmatched_is_unschedulable_with_reason():
    api, _ = two_slice_cluster()
    sched = Scheduler(api, metrics=Metrics())
    sched.cache.refresh()
    pods = [selector_pod(f"u{i}", 4, ["nonexistent"], group="ug", size=2)
            for i in range(2)]
    for obj in pods:
        api.create_pod(obj)
    names = sorted(n["metadata"]["name"] for n in api.list_nodes())
    r = sched.filter(pods[0], names)
    assert not r.nodes
    assert any("slice-selector" in m for m in r.failed.values())


def test_mixed_selector_gang_member_fails_loudly_not_mispinned():
    # t3's own selector excludes the slice its gang planned on: it must be
    # held with a clear reason, never silently bound outside its pin
    api, _ = two_slice_cluster()
    sched = Scheduler(api, metrics=Metrics())
    sched.cache.refresh()
    pods = [selector_pod(f"x{i}", 4, ["sa"], group="xg", size=4) for i in range(3)]
    odd = selector_pod("x3", 4, ["sb"], group="xg", size=4)
    for obj in pods + [odd]:
        api.create_pod(obj)
    names = sorted(n["metadata"]["name"] for n in api.list_nodes())
    # first member plans the gang (on sa, per ITS selector)
    assert sched.filter(pods[0], names).nodes
    r = sched.filter(odd, names)
    assert not r.nodes
    assert any("outside its slice-selector" in m for m in r.failed.values())


def test_preemption_respects_slice_selector():
    # low-priority tenants on BOTH slices; the high-priority pinned pod may
    # only evict victims on ITS slice
    api, _ = two_slice_cluster()
    sched = Scheduler(api, metrics=Metrics())
    sched.cache.refresh()
    low = []
    for sid in ("sa", "sb"):
        for i in range(4):
            obj = selector_pod(f"low-{sid}-{i}", 4, [sid], group=f"g{sid}",
                               size=4, priority=1)
            low.append(obj)
            api.create_pod(obj)
    schedule_all(api, sched, low)  # both slices now full
    hi = selector_pod("hi", 4, ["sb"], priority=9)
    api.create_pod(hi)
    victims = sched.preemption_victims(hi)
    victim_keys = {k for ks in victims.values() for k in ks}
    assert victim_keys  # something must be evictable
    assert all("low-sb" in k for k in victim_keys), victim_keys


# -- hybrid workload mesh ---------------------------------------------------

def test_hybrid_device_mesh_cpu_groups():
    import jax

    from kubegpu_tpu.parallel import hybrid_device_mesh

    mesh = hybrid_device_mesh({"dcn": 2, "data": 4}, num_slices=2)
    assert mesh.shape == {"dcn": 2, "data": 4}
    assert tuple(mesh.axis_names) == ("dcn", "data")
    devs = jax.devices()
    # slice-major device order: first row is the first contiguous group
    assert [d.id for d in mesh.devices[0].flat] == [d.id for d in devs[:4]]
    with pytest.raises(ValueError):
        hybrid_device_mesh({"data": 4, "dcn": 2}, num_slices=2)  # dcn not first
    with pytest.raises(ValueError):
        hybrid_device_mesh({"dcn": 3, "data": 2}, num_slices=3)  # 8 % 3
