"""End-to-end request tracing: the span tracer, its oracles, the
batchers' phase trees, the gateway's tree, and trace completeness under
churn (ISSUE 6).

Layers, in test order:

1. the Tracer itself — span trees, bounded rings, JSONL round-trip,
   and the validate/retire oracles catching deliberately broken traces;
2. batcher-side tracing — dense + paged + SimBatcher emit complete
   trees whose phase decomposition sums to the independently-measured
   TTFT, with cancel/churn/speculation covered;
3. gateway-side tracing — admission_wait/route/dispatch spans, the
   /debug/trace HTTP surface, and the GatewaySoak kill schedule's
   trace-derived I5 (zero orphans, one retire per serve subtree).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubegpu_tpu.models import TransformerLM
from kubegpu_tpu.models.paging import PagedContinuousBatcher
from kubegpu_tpu.models.serving import ContinuousBatcher
from kubegpu_tpu.utils.metrics import Metrics
from kubegpu_tpu.utils.tracing import (
    Tracer,
    load_jsonl,
    phase_durations,
    serve_retire_violations,
    span_tree,
    validate_trace,
)

TINY = dict(vocab_size=61, num_layers=1, num_heads=2, hidden=16, max_seq=48)


@pytest.fixture(scope="module")
def tiny_params():
    model = TransformerLM(dtype=jnp.float32, **TINY)
    return model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32)
    )["params"]


def make_paged(params, **kw):
    cfg = dict(slots=3, prompt_pad=12, page_size=4, pool_pages=32,
               dtype=jnp.float32, **TINY)
    cfg.update(kw)
    return PagedContinuousBatcher(params, **cfg)


# ---------------------------------------------------------------------------
# 1. Tracer mechanics + oracles
# ---------------------------------------------------------------------------

def test_tracer_basic_tree_completion_and_jsonl(tmp_path):
    tr = Tracer()
    root = tr.start_trace("gateway_request", request_id="r1")
    child = root.child("admission_wait", tenant="t0")
    child.end()
    serve = root.child("serve", seq_id=0)
    q = serve.child("queue")
    q.end()
    serve.event("retire", reason="finished")
    serve.end()
    assert tr.open_count() == 1          # root still open
    assert tr.completed() == []
    root.end(status="ok")
    assert tr.open_count() == 0
    comp = tr.completed()
    assert len(comp) == 1
    spans = comp[0]
    assert validate_trace(spans) == []
    assert serve_retire_violations(spans) == []
    tree = span_tree(spans)
    assert tree["name"] == "gateway_request"
    assert {c["name"] for c in tree["children"]} == {
        "admission_wait", "serve",
    }
    # JSONL round-trip: same spans, same verdicts
    path = tmp_path / "traces.jsonl"
    n = tr.dump_jsonl(str(path))
    assert n == len(spans)
    loaded = load_jsonl(str(path))
    assert len(loaded) == 1
    (reloaded,) = loaded.values()
    assert validate_trace(reloaded) == []
    assert {s["name"] for s in reloaded} == {s["name"] for s in spans}
    for line in path.read_text().splitlines():
        assert json.loads(line)["v"] == 1


def test_tracer_completion_waits_for_late_children():
    """A hedge loser's teardown lands AFTER the root closed: the trace
    must complete then (not at root end), with no abandoned spans."""
    tr = Tracer()
    root = tr.start_trace("gateway_request")
    dispatch = root.child("dispatch", replica="a", overhang_ok=True)
    root.end(status="ok")                # winner recorded
    assert tr.open_count() == 1          # loser still draining
    serve = dispatch.child("serve")
    serve.event("retire", reason="cancelled")
    serve.end()
    dispatch.end(outcome="cancelled")
    assert tr.open_count() == 0
    (spans,) = tr.completed()
    assert validate_trace(spans) == []   # overhang_ok exempts the subtree
    assert serve_retire_violations(spans) == []


def test_oracles_catch_broken_traces():
    # orphan: parent id points nowhere
    spans = [
        {"trace": "t", "span": 1, "parent": None, "name": "root",
         "start": 0.0, "end": 2.0, "attrs": {}},
        {"trace": "t", "span": 2, "parent": 99, "name": "lost",
         "start": 0.1, "end": 0.2, "attrs": {}},
    ]
    assert any("orphan" in p for p in validate_trace(spans))
    # unclosed span
    spans[1] = {"trace": "t", "span": 2, "parent": 1, "name": "open",
                "start": 0.1, "end": None, "attrs": {}}
    assert any("never closed" in p for p in validate_trace(spans))
    # child outliving parent without overhang_ok
    spans[1] = {"trace": "t", "span": 2, "parent": 1, "name": "late",
                "start": 0.1, "end": 5.0, "attrs": {}}
    assert any("outlives" in p for p in validate_trace(spans))
    spans[1]["attrs"] = {"overhang_ok": True}
    assert validate_trace(spans) == []
    # double retire inside one serve subtree
    spans = [
        {"trace": "t", "span": 1, "parent": None, "name": "serve",
         "start": 0.0, "end": 1.0, "attrs": {}},
        {"trace": "t", "span": 2, "parent": 1, "name": "retire",
         "start": 0.5, "end": 0.5, "attrs": {}},
        {"trace": "t", "span": 3, "parent": 1, "name": "retire",
         "start": 0.6, "end": 0.6, "attrs": {}},
    ]
    assert serve_retire_violations(spans)
    # zero retires is just as wrong (vanished sequence)
    assert serve_retire_violations(spans[:1])
    assert not serve_retire_violations(spans[:2])


def test_tracer_rings_are_bounded():
    tr = Tracer(max_traces=4, max_open=8)
    for i in range(10):
        tr.start_trace("r", request_id=f"r{i}").end()
    assert len(tr.completed()) == 4
    assert tr.evicted == 6
    # leak guard: open traces past max_open force-complete as abandoned
    tr2 = Tracer(max_traces=64, max_open=3)
    ctxs = [tr2.start_trace("leak") for _ in range(6)]
    assert tr2.open_count() == 3
    assert tr2.aborted == 3
    abandoned = [
        s for spans in tr2.completed() for s in spans
        if s["attrs"].get("abandoned")
    ]
    assert len(abandoned) == 3
    # and the oracle refuses abandoned spans
    assert all(validate_trace(spans) for spans in tr2.completed())
    for c in ctxs:
        c.end()


# ---------------------------------------------------------------------------
# 2. Batcher-side tracing
# ---------------------------------------------------------------------------

def assert_sound(spans):
    problems = validate_trace(spans) + serve_retire_violations(spans)
    assert not problems, problems


@pytest.mark.slow
def test_paged_batcher_traces_complete_and_sum_to_ttft(tiny_params):
    """Every served request yields one complete tree; the phase
    decomposition (queue + station_wait + prefill + first_step, via
    span timestamps) matches the measured TTFT (submitted_at
    arithmetic) — two independent instrumentation paths agreeing."""
    tr = Tracer()
    m = Metrics()
    cb = make_paged(tiny_params, tracer=tr, metrics=m, token_budget=8,
                    station_slots=2)
    rs = np.random.RandomState(3)
    prompts = [
        rs.randint(0, 61, size=rs.randint(3, 12)).astype(np.int32)
        for _ in range(6)
    ]
    out = cb.run(prompts, [5, 4, 6, 0, 3, 2])
    assert len(out) == 6
    assert tr.open_count() == 0
    comp = tr.completed()
    assert len(comp) == 6
    checked = 0
    for spans in comp:
        assert_sound(spans)
        phases = phase_durations(spans)
        measured = next(
            (s["attrs"]["measured_ttft"] for s in spans
             if "measured_ttft" in s["attrs"]), None,
        )
        if measured is None:
            continue  # the zero-budget request emits nothing
        ttft_sum = sum(v for k, v in phases.items() if k != "decode")
        assert abs(ttft_sum - measured) < 0.005 + 0.1 * measured, (
            phases, measured,
        )
        checked += 1
    assert checked == 5
    # the phase histogram is labeled: split by phase, no unlabeled twin.
    # Every request — the zero-budget no-op included — waits in queue,
    # so the queue series counts all 6; only the 5 emitting requests
    # reach a first token
    assert m.histogram_count("serve_phase_seconds", phase="queue") == 6
    assert m.histogram_count(
        "serve_phase_seconds", phase="first_step") == 5
    assert m.histogram_count("serve_phase_seconds") == 0
    cb.assert_page_accounting()
    # the ledger ring recorded every iteration, within budget accounting
    rows = cb.ledger_rows()
    assert rows and rows[-1]["step"] == cb.stats["steps"]
    for row in rows:
        assert row["rows"] >= 0
        assert row["pages_free"] + row["pages_live"] + row[
            "cache_idle"] <= cb.pool_pages - 1 + row["pages_cached"]


@pytest.mark.slow
def test_paged_tracing_under_cancel_prefix_hits_and_speculation(
        tiny_params):
    """Churny single-replica schedule: prefix-cache hits (gather span),
    cancels mid-prefill and mid-decode, speculation spans — trees stay
    complete, accounting stays balanced, exactly one retire each."""
    tr = Tracer()
    cb = make_paged(
        tiny_params, tracer=tr, prompt_pad=16, draft_params=tiny_params,
        speculate_k=2, draft_num_layers=TINY["num_layers"],
        draft_num_heads=TINY["num_heads"], draft_hidden=TINY["hidden"],
    )
    rs = np.random.RandomState(5)
    base = rs.randint(0, 61, size=9).astype(np.int32)
    cb.submit(0, base, 6)
    while cb.has_work():
        cb.serve_step()
    # same prefix again: gather span rides the hit
    cb.submit(1, np.concatenate([base, [7, 8]]).astype(np.int32), 5)
    cb.submit(2, rs.randint(0, 61, size=14).astype(np.int32), 6)
    cb.serve_step()
    cb.cancel(2)                         # mid-prefill (or just admitted)
    cb.submit(3, rs.randint(0, 61, size=5).astype(np.int32), 8)
    for _ in range(2):
        cb.serve_step()
    cb.cancel(3)                         # mid-decode or mid-queue
    while cb.has_work():
        cb.serve_step()
    assert tr.open_count() == 0
    comp = tr.completed()
    assert len(comp) == 4
    names = set()
    reasons = []
    for spans in comp:
        assert_sound(spans)
        names |= {s["name"] for s in spans}
        reasons += [
            s["attrs"]["reason"] for s in spans if s["name"] == "retire"
        ]
    assert "prefix_gather" in names
    assert "spec_draft" in names and "spec_verify" in names
    assert "chunk" in names
    assert reasons.count("cancelled") == 2
    cb.assert_page_accounting()
    # died-path: live requests' spans close when the replica dies
    cb.submit(7, base, 6)
    cb.serve_step()
    cb.trace_shutdown("replica test died")
    assert tr.open_count() == 0
    last = tr.completed()[-1]
    assert_sound(last)
    assert any(
        s["name"] == "retire" and s["attrs"]["reason"] == "died"
        for s in last
    )


@pytest.mark.slow
def test_dense_batcher_traces_monolithic_and_chunked(tiny_params):
    for chunk in (None, 4):
        tr = Tracer()
        cb = ContinuousBatcher(
            params=tiny_params, slots=2, prompt_pad=12,
            prefill_chunk=chunk, dtype=jnp.float32, tracer=tr, **TINY
        )
        rs = np.random.RandomState(1)
        prompts = [
            rs.randint(0, 61, size=rs.randint(3, 12)).astype(np.int32)
            for _ in range(4)
        ]
        out = cb.run(prompts, [4, 3, 0, 5])
        assert len(out) == 4
        assert tr.open_count() == 0, f"chunk={chunk}"
        comp = tr.completed()
        assert len(comp) == 4
        for spans in comp:
            assert_sound(spans)
        names = {s["name"] for spans in comp for s in spans}
        assert {"serve", "queue", "prefill", "decode", "retire"} <= names
        if chunk is not None:
            assert "chunk" in names
        # cancel closes the tree too
        cb.submit(9, prompts[0], 6)
        cb.serve_step()
        cb.cancel(9)
        assert tr.open_count() == 0
        assert_sound(tr.completed()[-1])


# ---------------------------------------------------------------------------
# 3. Gateway-side tracing
# ---------------------------------------------------------------------------

def make_traced_gateway(n_replicas=3, **policy_kw):
    from kubegpu_tpu.gateway import (
        FailoverPolicy, Gateway, InMemoryReplicaClient, SimBatcher,
    )
    from kubegpu_tpu.testing.fake_serving import build_fake_serving_stack

    stack = build_fake_serving_stack(n_replicas)
    client = InMemoryReplicaClient(
        batcher_factory=lambda key: SimBatcher(slots=8),
        step_delay_s=0.001,
    )
    stack.registry.subscribe(client.sync_live)
    defaults = dict(deadline_s=30.0, hedge_after_s=0.05, max_attempts=6,
                    retry_budget_ratio=1.0, budget_floor=256)
    defaults.update(policy_kw)
    gw = Gateway(
        stack.registry, client, metrics=Metrics(), dispatchers=4,
        policy=FailoverPolicy(**defaults),
    )
    stack.registry.refresh()
    gw.start()
    return stack, client, gw


def test_gateway_request_yields_one_nested_tree():
    from kubegpu_tpu.gateway import GatewayRequest

    stack, client, gw = make_traced_gateway()
    try:
        pendings = [
            gw.submit(GatewayRequest(
                prompt=[1, 2, 3], max_new_tokens=4, request_id=f"r{i}",
                tenant=f"t{i % 2}", session=f"s{i % 3}",
            ))
            for i in range(12)
        ]
        assert gw.drain(30.0)
        assert all(p.wait(1.0) for p in pendings)
        assert gw.tracer.wait_quiescent(5.0)
        comp = gw.tracer.completed()
        assert len(comp) == 12
        for spans in comp:
            assert_sound(spans)
            names = {s["name"] for s in spans}
            assert {"gateway_request", "admission_wait", "route",
                    "dispatch", "serve", "queue", "decode",
                    "retire"} <= names
            root = next(s for s in spans if s["parent"] is None)
            assert root["attrs"]["status"] == "ok"
            # dispatch nests under root; serve nests under dispatch
            dispatch = next(s for s in spans if s["name"] == "dispatch")
            serve = next(s for s in spans if s["name"] == "serve")
            assert dispatch["parent"] == root["span"]
            assert serve["parent"] == dispatch["span"]
            # the session router annotated its routing decision
            route = next(s for s in spans if s["name"] == "route")
            assert route["attrs"]["replica"]
    finally:
        gw.stop()
        client.stop()


def test_gateway_rejected_request_still_closes_its_trace():
    from kubegpu_tpu.gateway import AdmissionQueue, Gateway, GatewayRequest
    from kubegpu_tpu.gateway import InMemoryReplicaClient, SimBatcher
    from kubegpu_tpu.testing.fake_serving import build_fake_serving_stack

    stack = build_fake_serving_stack(1)
    client = InMemoryReplicaClient(
        batcher_factory=lambda key: SimBatcher(slots=8))
    gw = Gateway(
        stack.registry, client, queue=AdmissionQueue(capacity=2),
        metrics=Metrics(), dispatchers=0,  # nobody drains: queue fills
    )
    try:
        for i in range(4):
            gw.submit(GatewayRequest(
                prompt=[1], max_new_tokens=2, request_id=f"q{i}",
            ))
        rejected = [
            spans for spans in gw.tracer.completed()
            if next(s for s in spans if s["parent"] is None)
            ["attrs"]["status"] == "rejected"
        ]
        assert len(rejected) == 2
        for spans in rejected:
            assert validate_trace(spans) == []
    finally:
        gw.stop()
        client.stop()


def test_debug_trace_http_endpoint():
    """GET /debug/trace returns parseable span trees + replica ledgers
    through the real HTTP frontend."""
    import http.client

    from kubegpu_tpu.gateway import GatewayRequest
    from kubegpu_tpu.gateway.server import GatewayServer

    stack, client, gw = make_traced_gateway(n_replicas=2)
    server = GatewayServer(gw, listen=("127.0.0.1", 0), watch=False)
    # Gateway.start() is idempotent enough for this test path: the
    # server starts the HTTP thread; gw dispatchers already run
    t = __import__("threading").Thread(
        target=server.httpd.serve_forever, daemon=True)
    t.start()
    try:
        for i in range(3):
            gw.submit(GatewayRequest(
                prompt=[1, 2], max_new_tokens=3, request_id=f"d{i}",
            ))
        assert gw.drain(30.0)
        assert gw.tracer.wait_quiescent(5.0)
        host, port = server.address
        conn = http.client.HTTPConnection(host, port, timeout=5)
        conn.request("GET", "/debug/trace?n=2")
        resp = conn.getresponse()
        assert resp.status == 200
        body = json.loads(resp.read())
        assert body["tracing"] is True
        assert body["open_traces"] == 0
        assert 1 <= len(body["traces"]) <= 2
        tree = body["traces"][0]
        assert tree["name"] == "gateway_request"
        assert tree["children"]
        assert isinstance(body["ledgers"], dict)  # SimBatcher: no rows
        conn.close()
    finally:
        server.httpd.shutdown()
        server.httpd.server_close()
        gw.stop()
        client.stop()


def test_gateway_soak_trace_oracle_kill_schedule():
    """The FAST trace-completeness churn test (SimBatcher data plane):
    the GatewaySoak kill/revive/straggle schedule must leave every
    request with exactly one complete span tree — zero orphans, zero
    double-retires — via the soak's own check_traces oracle."""
    from kubegpu_tpu.testing.soak import GatewaySoak

    soak = GatewaySoak(seed=11, n_replicas=3, multiturn=True)
    soak.run(steps=25)
    # run() already called check() -> check_traces(); re-assert the
    # headline numbers explicitly so a future soak refactor cannot
    # silently stop checking traces
    completed = soak.gw.tracer.completed()
    assert completed
    assert soak.gw.tracer.evicted == 0
    for spans in completed:
        assert_sound(spans)


@pytest.mark.slow
def test_gateway_soak_paged_multiturn_spec_traces(tiny_params):
    """ISSUE 6 acceptance churn: the GatewaySoak kill schedule over
    REAL paged batchers with speculation AND multi-turn caching on,
    tracing enabled end to end — zero orphan spans, zero requests with
    two retire spans, and page accounting still balances on every
    surviving replica (check() runs assert_page_accounting with the
    tracer attached)."""
    from kubegpu_tpu.testing.soak import GatewaySoak

    soak = GatewaySoak(
        seed=31, n_replicas=2, multiturn=True, follow_prompt_cap=12,
        batcher_factory=lambda key: PagedContinuousBatcher(
            tiny_params, slots=4, prompt_pad=12, page_size=4,
            pool_pages=48, station_slots=2, token_budget=8,
            dtype=jnp.float32, decode_page_cache="fp32",
            draft_params=tiny_params, speculate_k=2, draft_window=16,
            draft_num_layers=TINY["num_layers"],
            draft_num_heads=TINY["num_heads"],
            draft_hidden=TINY["hidden"], **TINY,
        ),
    )
    soak.run(steps=15)
    completed = soak.gw.tracer.completed()
    assert completed
    double_retires = [
        v for spans in completed for v in serve_retire_violations(spans)
    ]
    orphans = [
        p for spans in completed for p in validate_trace(spans)
        if "orphan" in p
    ]
    assert not double_retires and not orphans, (
        double_retires, orphans,
    )
    # replica-side phase spans made it through the gateway tree: the
    # paged batcher's serve subtree carries its prefill/decode phases
    names = {s["name"] for spans in completed for s in spans}
    assert {"serve", "queue", "decode", "retire"} <= names
