"""Allocator core tests: fabricated v5e topologies, no cluster, no TPUs —
the reference's crown-jewel test pattern (SURVEY.md §4)."""

from typing import Dict, List

import pytest

from kubegpu_tpu.grpalloc import (
    build_slice_views,
    fit_gang,
    fit_request_tree,
    expand_scalar_request,
    placement_score,
    pod_fits_group_constraints,
    return_pod_resources,
    take_pod_resources,
)
from kubegpu_tpu.types import (
    LEAF_TPU,
    NodeInfo,
    PodInfo,
    ResourceTree,
    SliceTopology,
    TpuGeneration,
    is_contiguous_submesh,
)
from kubegpu_tpu.types.info import ContainerInfo, TpuRequest


def make_cluster(
    mesh=(4, 4), host_block=(2, 2), unhealthy=(), slice_id="s0"
) -> Dict[str, NodeInfo]:
    topo = SliceTopology.build(
        slice_id, TpuGeneration.V5E, mesh, host_block=host_block, unhealthy=unhealthy
    )
    nodes = {}
    for h in topo.hosts():
        n = NodeInfo(
            name=h,
            slice_id=slice_id,
            generation=topo.generation,
            mesh_shape=topo.mesh_shape,
            wrap=topo.wrap,
            chips=topo.host_chips(h),
        )
        n.rebuild_capacity()
        nodes[h] = n
    return nodes


def make_pod(name, chips, contiguous=True, group=None, group_size=1) -> PodInfo:
    return PodInfo(
        name=name,
        containers=[ContainerInfo(name="main", tpu_chips=chips)],
        require_contiguous=contiguous,
        pod_group=group,
        pod_group_size=group_size,
    )


def req(pod: PodInfo) -> TpuRequest:
    return TpuRequest.from_pod(pod)


# -- single-pod fit ---------------------------------------------------------

def test_zero_request_passthrough():
    nodes = make_cluster()
    n = next(iter(nodes.values()))
    r = pod_fits_group_constraints(n, req(make_pod("p", 0)))
    assert r.fits and r.assignment is None


def test_zero_request_on_cpu_node():
    r = pod_fits_group_constraints(NodeInfo(name="cpu-1"), req(make_pod("p", 0)))
    assert r.fits


def test_tpu_request_on_cpu_node_rejected():
    r = pod_fits_group_constraints(NodeInfo(name="cpu-1"), req(make_pod("p", 1)))
    assert not r.fits and "no TPU" in r.reason


def test_whole_host_block_allocation():
    nodes = make_cluster()
    views = build_slice_views(nodes.values())
    n = nodes[sorted(nodes)[0]]
    r = pod_fits_group_constraints(n, req(make_pod("p", 4)), views["s0"])
    assert r.fits
    coords = {c.coords for c in r.assignment.all_chips()}
    assert is_contiguous_submesh(coords, (4, 4))
    assert len(coords) == 4
    assert r.assignment.node == n.name
    # 2x2 block: full contiguity + perfect aspect
    assert r.score > 75


def test_insufficient_chips_reason():
    nodes = make_cluster()
    n = nodes[sorted(nodes)[0]]
    r = pod_fits_group_constraints(n, req(make_pod("p", 5)))
    assert not r.fits and "insufficient" in r.reason


def test_contiguity_constraint_enforced_and_relaxable():
    nodes = make_cluster()
    n = nodes[sorted(nodes)[0]]  # owns (0,0),(0,1),(1,0),(1,1)
    views = build_slice_views(nodes.values())
    view = views["s0"]
    # occupy the diagonal so only (0,1),(1,0) remain — not adjacent
    by_coord = {c.coords: c for c in n.chips}
    fake_assignment_chips = [(0, 0), (1, 1)]
    from kubegpu_tpu.types.info import Assignment, ChipRef

    a = Assignment(
        node=n.name,
        slice_id="s0",
        per_container={
            "main": [
                ChipRef(n.name, by_coord[c].device_index, by_coord[c].chip_id, c)
                for c in fake_assignment_chips
            ]
        },
    )
    take_pod_resources(n, a)
    views = build_slice_views(nodes.values())
    r = pod_fits_group_constraints(n, req(make_pod("p", 2)), views["s0"])
    assert not r.fits and "contiguous" in r.reason
    r2 = pod_fits_group_constraints(n, req(make_pod("p", 2, contiguous=False)), views["s0"])
    assert r2.fits
    got = {c.coords for c in r2.assignment.all_chips()}
    assert got == {(0, 1), (1, 0)}


def test_score_prefers_square_over_line():
    # the ICI analog of "NVLink-local beats cross-group" (SURVEY.md §4):
    # a 2x2 placement outranks a 1x4 line of the same size
    square = placement_score({(0, 0), (0, 1), (1, 0), (1, 1)}, frozenset(), (4, 4))
    line = placement_score({(0, 0), (0, 1), (0, 2), (0, 3)}, frozenset(), (4, 4))
    scatter = placement_score({(0, 0), (0, 2), (2, 0), (2, 2)}, frozenset(), (4, 4))
    assert square > line > scatter


def test_corner_preferred_over_center_for_fragmentation():
    nodes = make_cluster(mesh=(4, 4), host_block=(4, 4))  # single host owns all 16
    n = next(iter(nodes.values()))
    views = build_slice_views(nodes.values())
    r = pod_fits_group_constraints(n, req(make_pod("p", 4)), views["s0"])
    assert r.fits
    coords = {c.coords for c in r.assignment.all_chips()}
    # best placement hugs a corner, not the center of the mesh
    assert (0, 0) in coords or (3, 3) in coords or (0, 3) in coords or (3, 0) in coords


def test_determinism():
    nodes = make_cluster()
    n = nodes[sorted(nodes)[0]]
    views = build_slice_views(nodes.values())
    r1 = pod_fits_group_constraints(n, req(make_pod("p", 2)), views["s0"])
    r2 = pod_fits_group_constraints(n, req(make_pod("p", 2)), views["s0"])
    assert [c.coords for c in r1.assignment.all_chips()] == [
        c.coords for c in r2.assignment.all_chips()
    ]


# -- take / return ----------------------------------------------------------

def test_take_return_roundtrip():
    nodes = make_cluster()
    n = nodes[sorted(nodes)[0]]
    r = pod_fits_group_constraints(n, req(make_pod("p", 2)))
    take_pod_resources(n, r.assignment)
    assert n.allocatable().total(LEAF_TPU) == 2
    views = build_slice_views(nodes.values())
    assert len(views["s0"].free) == 14
    return_pod_resources(n, r.assignment)
    assert n.allocatable().total(LEAF_TPU) == 4
    assert n.used.to_flat() == {}


def test_double_take_rejected_atomically():
    nodes = make_cluster()
    n = nodes[sorted(nodes)[0]]
    r = pod_fits_group_constraints(n, req(make_pod("p", 2)))
    take_pod_resources(n, r.assignment)
    with pytest.raises(ValueError, match="double-take|already allocated"):
        take_pod_resources(n, r.assignment)
    # no partial mutation: still exactly one take recorded
    assert n.allocatable().total(LEAF_TPU) == 2


def test_double_return_idempotent():
    nodes = make_cluster()
    n = nodes[sorted(nodes)[0]]
    r = pod_fits_group_constraints(n, req(make_pod("p", 2)))
    take_pod_resources(n, r.assignment)
    return_pod_resources(n, r.assignment)
    return_pod_resources(n, r.assignment)  # replay-safe cleanup
    assert n.used.to_flat() == {} and n.allocatable().total(LEAF_TPU) == 4


def test_unhealthy_chips_never_allocated():
    nodes = make_cluster(unhealthy=[(0, 0), (0, 1)])
    views = build_slice_views(nodes.values())
    assert len(views["s0"].free) == 14
    host = None
    for h, n in nodes.items():
        if any(not c.healthy for c in n.chips):
            host = h
    r = pod_fits_group_constraints(nodes[host], req(make_pod("p", 4)), views["s0"])
    assert not r.fits  # only 2 healthy chips left on that host


# -- gang fit ---------------------------------------------------------------

def test_gang_four_singles_on_empty_slice():
    nodes = make_cluster()
    view = build_slice_views(nodes.values())["s0"]
    pods = [make_pod(f"w{i}", 1, group="j", group_size=4) for i in range(4)]
    g = fit_gang(view, pods)
    assert g.success
    coords = {r.coords for a in g.per_pod.values() for r in a.all_chips()}
    assert len(coords) == 4
    assert is_contiguous_submesh(coords, (4, 4))


def test_gang_two_quads_spans_hosts():
    nodes = make_cluster()
    view = build_slice_views(nodes.values())["s0"]
    pods = [make_pod(f"w{i}", 4, group="j", group_size=2) for i in range(2)]
    g = fit_gang(view, pods)
    assert g.success
    all_coords = set()
    for key, a in g.per_pod.items():
        pod_coords = {r.coords for r in a.all_chips()}
        # every pod's own chips must be host-local and contiguous
        assert len({r.host for r in a.all_chips()}) == 1
        assert is_contiguous_submesh(pod_coords, (4, 4))
        all_coords |= pod_coords
    assert len(all_coords) == 8
    assert is_contiguous_submesh(all_coords, (4, 4))


def test_gang_pod_too_big_for_any_host():
    nodes = make_cluster()
    view = build_slice_views(nodes.values())["s0"]
    g = fit_gang(view, [make_pod("w0", 8, group="j")])
    assert not g.success and "span hosts" in g.reason


def test_gang_all_or_nothing_capacity():
    nodes = make_cluster()
    view = build_slice_views(nodes.values())["s0"]
    pods = [make_pod(f"w{i}", 4, group="j", group_size=5) for i in range(5)]
    g = fit_gang(view, pods)
    assert not g.success and "want 20" in g.reason


def test_gang_contiguous_blocked_by_holes_then_relaxed():
    nodes = make_cluster()
    # poke used holes so no 8-rectangle is free: occupy (1,1) and (2,2)
    from kubegpu_tpu.types.info import Assignment, ChipRef

    for hole in [(1, 1), (2, 2)]:
        for n in nodes.values():
            for ch in n.chips:
                if ch.coords == hole:
                    take_pod_resources(
                        n,
                        Assignment(
                            node=n.name,
                            slice_id="s0",
                            per_container={"m": [ChipRef(n.name, ch.device_index, ch.chip_id, hole)]},
                        ),
                    )
    view = build_slice_views(nodes.values())["s0"]
    assert len(view.free) == 14
    pods = [make_pod(f"w{i}", 4, group="j", group_size=2) for i in range(2)]
    g = fit_gang(view, pods)
    assert not g.success
    relaxed = [make_pod(f"w{i}", 4, contiguous=False, group="j", group_size=2) for i in range(2)]
    g2 = fit_gang(view, relaxed)
    assert g2.success


def test_two_sequential_gangs_fill_slice():
    # BASELINE config 5 shape (without preemption): two 8-chip tenants
    nodes = make_cluster()
    for tenant in ("a", "b"):
        view = build_slice_views(nodes.values())["s0"]
        pods = [make_pod(f"{tenant}{i}", 4, group=tenant, group_size=2) for i in range(2)]
        g = fit_gang(view, pods)
        assert g.success, g.reason
        for key, a in g.per_pod.items():
            take_pod_resources(nodes[a.node], a)
    view = build_slice_views(nodes.values())["s0"]
    assert len(view.free) == 0
    # a third tenant must be cleanly rejected
    g3 = fit_gang(view, [make_pod("c0", 4, group="c")])
    assert not g3.success


def test_gang_zero_chip_pods():
    nodes = make_cluster()
    view = build_slice_views(nodes.values())["s0"]
    g = fit_gang(view, [make_pod("w0", 0)])
    assert g.success


# -- generic tree fit (capability parity) -----------------------------------

def test_treefit_wildcard():
    alloc = ResourceTree.from_flat(
        {
            "grp/0/dev/0/cards": 1,
            "grp/0/dev/1/cards": 1,
            "grp/1/dev/0/cards": 1,
        }
    )
    request = expand_scalar_request("cards", 2, "grp/*/dev/*/cards")
    r = fit_request_tree(request, alloc)
    assert r.fits
    taken = r.bindings["grp/*/dev/*/cards"]
    assert sum(q for _, q in taken) == 2


def test_treefit_insufficient():
    alloc = ResourceTree.from_flat({"grp/0/dev/0/cards": 1})
    request = expand_scalar_request("cards", 3, "grp/*/dev/*/cards")
    r = fit_request_tree(request, alloc)
    assert not r.fits and "wants 3" in r.reason


def test_treefit_concrete_path():
    alloc = ResourceTree.from_flat({"grp/0/dev/0/cards": 2})
    request = expand_scalar_request("cards", 2, "grp/0/dev/0/cards")
    r = fit_request_tree(request, alloc)
    assert r.fits


def test_treefit_wildcard_must_not_starve_specific_request():
    # regression (review finding): greedy matching rejected this satisfiable
    # set — the wildcard must yield grp/0 to the concrete request and take
    # grp/1 instead; max-flow finds it.
    alloc = ResourceTree.from_flat(
        {"grp/0/dev/0/cards": 1, "grp/0/dev/1/cards": 1, "grp/1/dev/0/cards": 2}
    )
    request = ResourceTree()
    wild = expand_scalar_request("cards", 2, "grp/*/dev/*/cards")
    specific = expand_scalar_request("cards", 2, "grp/0/dev/*/cards")
    for src in (wild, specific):
        for p, q in src.walk():
            node = request
            for kind, idx in p.groups:
                node = node.child(kind, idx, create=True)
            node.leaves[p.leaf] = node.leaves.get(p.leaf, 0) + q
    r = fit_request_tree(request, alloc)
    assert r.fits, r.reason
    specific_bindings = r.bindings["grp/0/dev/*/cards"]
    assert sum(q for _, q in specific_bindings) == 2
    assert all(path.startswith("grp/0/") for path, _ in specific_bindings)


def test_slice_view_skips_wrap_disagreement():
    nodes = make_cluster()
    rogue = nodes[sorted(nodes)[0]]
    rogue.wrap = (True, True)  # misconfigured advertiser
    views = build_slice_views(nodes.values())
    v = views["s0"]
    # rogue host excluded; its 4 chips missing from the view
    assert len(v.chips) == 12
    assert rogue.name not in v.by_host


def test_scored_rectangles_membership_origin_scan_matches_enumeration():
    """The gang-path Python scan iterates membership-anchored origins
    (allocator._scored_rectangles); it must produce the IDENTICAL
    candidate list — same rects, same scores, same order, tie-breaks
    included — as the defining whole-mesh enumeration with the origin
    pre-filter, across wrap configs, ragged memberships, distinct scoring
    contexts, and the multislice fixed-shape restriction."""
    import random as _r

    from kubegpu_tpu.grpalloc.allocator import _scored_rectangles
    from kubegpu_tpu.grpalloc.scoring import placement_score
    from kubegpu_tpu.types.topology import enumerate_rectangles

    def oracle(n, mesh, wrap, membership, scoring, shape=None):
        out = []
        for rect in enumerate_rectangles(
            n, mesh, wrap, shapes=[shape] if shape else None
        ):
            if rect.origin not in membership:
                continue
            coords = rect.coords(mesh, wrap)
            if not coords <= membership:
                continue
            s = placement_score(coords, scoring, mesh, wrap)
            out.append((s, sorted(coords), coords))
        out.sort(key=lambda t: (-t[0], t[1]))
        return out

    rng = _r.Random(3)
    for mesh, wrap in [
        ((4, 4), (False, False)),
        ((4, 4), (True, True)),
        ((8, 4), (True, False)),
    ]:
        coords_all = [(x, y) for x in range(mesh[0]) for y in range(mesh[1])]
        for _ in range(6):
            membership = frozenset(
                rng.sample(coords_all, rng.randrange(1, len(coords_all)))
            )
            scoring = (
                frozenset(rng.sample(coords_all, len(coords_all) // 2))
                | membership
            )
            for n in (1, 2, 4):
                got = _scored_rectangles(
                    n, mesh, wrap, membership, scoring_free=scoring
                )
                assert got == oracle(n, mesh, wrap, membership, scoring)
            got = _scored_rectangles(
                4, mesh, wrap, membership, scoring_free=scoring, shape=(2, 2)
            )
            assert got == oracle(4, mesh, wrap, membership, scoring, (2, 2))
