"""KV-cache decoding tests: the DecodeLM twin must accept TransformerLM
checkpoints verbatim and reproduce its next-token choices."""

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from kubegpu_tpu.models import DecodeLM, TransformerLM, greedy_generate

pytestmark = pytest.mark.slow  # JAX compile-heavy; run with -m slow

CFG = dict(vocab_size=61, num_layers=2, num_heads=4, hidden=32, max_seq=32)


def trained_params():
    model = TransformerLM(dtype=jnp.float32, **CFG)
    tokens = jnp.ones((2, 8), jnp.int32)
    return model.init(jax.random.PRNGKey(0), tokens)["params"]


def test_decode_lm_param_tree_matches_training_model():
    params = trained_params()
    decode = DecodeLM(dtype=jnp.float32, **CFG)
    from kubegpu_tpu.models.decoding import init_caches

    caches = init_caches(2, CFG["num_layers"], CFG["num_heads"], CFG["hidden"],
                         CFG["max_seq"], jnp.float32)
    dparams = decode.init(
        jax.random.PRNGKey(0), jnp.zeros((2, 1), jnp.int32), caches,
        jnp.zeros((), jnp.int32),
    )["params"]
    assert jax.tree.structure(params) == jax.tree.structure(dparams)
    same_shapes = jax.tree.map(lambda a, b: a.shape == b.shape, params, dparams)
    assert all(jax.tree.leaves(same_shapes))


def test_greedy_generate_matches_full_forward_argmax():
    params = trained_params()
    model = TransformerLM(dtype=jnp.float32, **CFG)
    prompt = (jnp.arange(2 * 5, dtype=jnp.int32) % CFG["vocab_size"]).reshape(2, 5)
    steps = 6

    # oracle: re-run the FULL training model on the growing sequence
    seq = prompt
    for _ in range(steps):
        logits = model.apply({"params": params}, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)

    out = greedy_generate(
        params, prompt, steps, dtype=jnp.float32, **CFG
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))


@pytest.mark.exhaustive
def test_greedy_generate_matches_training_argmax_at_bf16():
    # default-dtype checkpoints: decode numerics mirror the training
    # attention exactly (bf16 scores, finfo-min mask, fp32 softmax), so
    # the argmax contract holds at bf16 too
    model = TransformerLM(dtype=jnp.bfloat16, **CFG)
    params = model.init(jax.random.PRNGKey(1), jnp.ones((2, 8), jnp.int32))[
        "params"
    ]
    prompt = (jnp.arange(2 * 4, dtype=jnp.int32) % CFG["vocab_size"]).reshape(2, 4)
    steps = 5
    seq = prompt
    for _ in range(steps):
        logits = model.apply({"params": params}, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    out = greedy_generate(params, prompt, steps, dtype=jnp.bfloat16, **CFG)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))


def test_greedy_generate_rejects_cache_overflow():
    import pytest

    params = trained_params()
    prompt = jnp.ones((1, 10), jnp.int32)
    with pytest.raises(ValueError, match="exceeds"):
        greedy_generate(params, prompt, 30, dtype=jnp.float32, **CFG)


def test_sampling_respects_top_k_and_needs_rng():
    import pytest

    from kubegpu_tpu.models import generate

    params = trained_params()
    prompt = jnp.ones((1, 3), jnp.int32)
    with pytest.raises(ValueError, match="rng"):
        generate(params, prompt, 2, temperature=1.0, dtype=jnp.float32, **CFG)
    # top_k=1 at any temperature IS greedy (only the argmax survives)
    greedy = greedy_generate(params, prompt, 5, dtype=jnp.float32, **CFG)
    sampled = generate(
        params, prompt, 5, temperature=2.0, top_k=1,
        rng=jax.random.PRNGKey(0), dtype=jnp.float32, **CFG,
    )
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(sampled))
    # unconstrained sampling at high temperature explores: two keys diverge
    a = generate(params, prompt, 8, temperature=5.0,
                 rng=jax.random.PRNGKey(1), dtype=jnp.float32, **CFG)
    b = generate(params, prompt, 8, temperature=5.0,
                 rng=jax.random.PRNGKey(2), dtype=jnp.float32, **CFG)
    assert not np.array_equal(np.asarray(a), np.asarray(b))


def test_greedy_generate_is_jittable_one_program():
    params = trained_params()
    prompt = jnp.ones((1, 4), jnp.int32)
    f = jax.jit(
        lambda p, t: greedy_generate(p, t, 4, dtype=jnp.float32, **CFG)
    )
    out = f(params, prompt)
    assert out.shape == (1, 8)
    assert out.dtype == jnp.int32


def test_int8_quant_decode_tracks_bf16_choices():
    """Weight-only int8 decode (VERDICT r3 #3a): quantize_params_int8 of
    the same checkpoint generates through the QuantDense path and must
    track the full-precision generation closely (identical here at fp32
    activations on a tiny model; bench.py measures the quality delta on
    the flagship)."""
    from kubegpu_tpu.models.decoding import quantize_params_int8

    params = trained_params()
    prompt = (jnp.arange(2 * 5, dtype=jnp.int32) % CFG["vocab_size"]).reshape(2, 5)
    steps = 6
    ref = greedy_generate(params, prompt, steps, dtype=jnp.float32, **CFG)
    qparams = quantize_params_int8(params)
    # every Dense kernel became int8+scale; embeds/LNs untouched
    leaves = jax.tree_util.tree_flatten_with_path(qparams)[0]
    kinds = {"int8": 0, "scale": 0, "other": 0}
    for path, leaf in leaves:
        names = [getattr(k, "key", "") for k in path]
        if "kernel_int8" in names:
            assert leaf.dtype == jnp.int8
            kinds["int8"] += 1
        elif "qscale" in names:
            kinds["scale"] += 1
        else:
            kinds["other"] += 1
    # q/k/v/o + up/down per layer (x2 layers) + lm_head = 13 quant kernels
    assert kinds["int8"] == kinds["scale"] == 13, kinds
    out = greedy_generate(
        qparams, prompt, steps, dtype=jnp.float32, quant=True, **CFG
    )
    ref_np, out_np = np.asarray(ref), np.asarray(out)
    match = (ref_np[:, 5:] == out_np[:, 5:]).mean()
    assert match >= 0.75, f"int8 decode diverged: token match {match:.2f}"


def test_continuous_batching_matches_per_sequence_greedy():
    """Slot-based continuous batching (models/serving.py): a queue of
    prompts with different lengths and different new-token budgets, served
    through 2 slots, must produce EXACTLY the tokens per-sequence
    greedy_generate produces — slot reuse, per-slot positions, padded
    admits and mid-flight admissions all transparent to the output."""
    import numpy as np

    from kubegpu_tpu.models.serving import ContinuousBatcher

    params = trained_params()
    rng = np.random.RandomState(0)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=n), dtype=np.int32)
        for n in (3, 5, 7, 4, 6)
    ]
    budgets = [6, 3, 5, 7, 4]

    # oracle: each sequence alone through the aligned-batch greedy path
    expected = {}
    for i, (p, n) in enumerate(zip(prompts, budgets)):
        out = greedy_generate(
            params, jnp.asarray(p)[None, :], n, dtype=jnp.float32, **CFG
        )
        expected[i] = list(np.asarray(out)[0, len(p):])

    cb = ContinuousBatcher(
        params, slots=2, prompt_pad=8, dtype=jnp.float32, **CFG
    )
    got = cb.run(prompts, budgets)
    assert set(got) == set(expected)
    for i in expected:
        assert got[i] == expected[i], (
            f"seq {i}: continuous {got[i]} != per-sequence {expected[i]}"
        )
    # 5 sequences through 2 slots: admits prove slot REUSE happened
    assert cb.stats["admits"] == 5
    # continuous batching never runs longer than the total token budget
    assert cb.stats["steps"] <= sum(budgets)


def test_continuous_batching_eos_frees_slot_early():
    """An EOS-terminated sequence releases its slot before its budget is
    spent, and the freed slot serves the next queued prompt."""
    import numpy as np

    from kubegpu_tpu.models.serving import ContinuousBatcher

    params = trained_params()
    rng = np.random.RandomState(1)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=4), dtype=np.int32)
        for _ in range(3)
    ]
    # pick the EOS id as the very first token the middle sequence greedily
    # emits, so it terminates immediately
    probe = greedy_generate(
        params, jnp.asarray(prompts[1])[None, :], 1, dtype=jnp.float32, **CFG
    )
    eos = int(np.asarray(probe)[0, -1])
    cb = ContinuousBatcher(
        params, slots=1, prompt_pad=8, eos_id=eos, dtype=jnp.float32, **CFG
    )
    got = cb.run(prompts, [8, 8, 8])
    assert set(got) == {0, 1, 2}
    assert got[1][-1] == eos and len(got[1]) <= 8
    # sequence 1 stopped at its EOS, strictly before its budget...
    assert len(got[1]) < 8 or got[1].index(eos) == len(got[1]) - 1
    # ...and later sequences still completed through the same slot
    assert len(got[2]) >= 1


def test_int8_tp_sharded_decode_matches_single_device():
    """int8 serving under tensor parallelism: the quantized param tree
    takes the TP rules (kernel_int8 like its bf16 twin, qscale following
    the output dim) and the TP-sharded quantized decode must reproduce
    the single-device quantized generation token-for-token."""
    import numpy as np

    from jax.sharding import NamedSharding, PartitionSpec as P

    from kubegpu_tpu.models.decoding import quantize_params_int8
    from kubegpu_tpu.parallel import device_mesh
    from kubegpu_tpu.parallel.sharding import (
        TRANSFORMER_TP_RULES,
        param_shardings,
    )

    # TP-friendly dims: vocab/hidden/heads divisible by the 4-way axis
    tp_cfg = dict(vocab_size=64, num_layers=2, num_heads=4, hidden=32,
                  max_seq=32)
    model = TransformerLM(dtype=jnp.float32, **tp_cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((2, 8), jnp.int32)
    )["params"]
    qparams = quantize_params_int8(params)
    prompt = (jnp.arange(2 * 5, dtype=jnp.int32) % tp_cfg["vocab_size"]).reshape(2, 5)
    ref = greedy_generate(
        qparams, prompt, 6, dtype=jnp.float32, quant=True, **tp_cfg
    )
    mesh = device_mesh({"model": 4}, devices=jax.devices()[:4])
    shardings = param_shardings(qparams, mesh, TRANSFORMER_TP_RULES)
    # the rules actually shard the quant layout (not silent replication)
    q_spec = shardings["layer0"]["attn"]["q_proj"]["kernel_int8"].spec
    assert q_spec == P(None, "model"), q_spec
    s_spec = shardings["layer0"]["attn"]["q_proj"]["qscale"].spec
    assert s_spec == P("model"), s_spec
    o_scale = shardings["layer0"]["attn"]["o_proj"]["qscale"].spec
    assert o_scale == P(), o_scale
    sharded = jax.device_put(qparams, shardings)
    fn = jax.jit(
        lambda p, t: greedy_generate(
            p, t, 6, dtype=jnp.float32, quant=True, **tp_cfg
        ),
        in_shardings=(shardings, NamedSharding(mesh, P())),
    )
    out = fn(sharded, prompt)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_continuous_batching_zero_budget_and_bad_config():
    """Review r4 edge cases: a 0-token budget yields an empty result
    (matching generate(num_steps=0)), and prompt_pad > max_seq fails at
    construction with a clear error, not an XLA shape error at first
    admit."""
    import numpy as np

    from kubegpu_tpu.models.serving import ContinuousBatcher

    params = trained_params()
    with pytest.raises(ValueError, match="prompt_pad"):
        ContinuousBatcher(
            params, slots=1, prompt_pad=64, dtype=jnp.float32, **CFG
        )
    cb = ContinuousBatcher(
        params, slots=1, prompt_pad=8, dtype=jnp.float32, **CFG
    )
    prompts = [np.array([1, 2, 3], np.int32), np.array([4, 5], np.int32)]
    got = cb.run(prompts, [0, 3])
    assert got[0] == []
    assert len(got[1]) == 3


def test_speculative_decode_is_lossless_for_any_draft():
    """Greedy speculative decoding (models/speculative.py) must emit
    EXACTLY the target's plain greedy sequence — for a draft that knows
    nothing about the target (independent random init), for a draft that
    IS the target (perfect acceptance), and across k values.  The
    target-call count shows the mechanism: a perfect draft costs
    ~steps/(k+1) verify iterations, a hopeless one at most steps."""
    import numpy as np

    from kubegpu_tpu.models.speculative import speculative_generate

    params = trained_params()
    prompt = (jnp.arange(2 * 5, dtype=jnp.int32) % CFG["vocab_size"]).reshape(2, 5)
    steps = 10
    ref = np.asarray(
        greedy_generate(params, prompt, steps, dtype=jnp.float32, **CFG)
    )

    # independent draft: smaller model, different seed
    draft_cfg = dict(vocab_size=CFG["vocab_size"], num_layers=1, num_heads=2,
                     hidden=16, max_seq=CFG["max_seq"])
    draft = TransformerLM(dtype=jnp.float32, **draft_cfg)
    draft_params = draft.init(
        jax.random.PRNGKey(7), jnp.ones((2, 8), jnp.int32)
    )["params"]
    for k in (1, 3):
        out, calls = speculative_generate(
            params, draft_params, prompt, steps, k=k, dtype=jnp.float32,
            **CFG, draft_num_layers=1, draft_num_heads=2, draft_hidden=16,
        )
        np.testing.assert_array_equal(np.asarray(out), ref, err_msg=f"k={k}")
        assert 1 <= int(calls) <= steps

    # perfect draft (the target itself): every proposal accepted, so the
    # verify count collapses toward steps/(k+1)
    out, calls = speculative_generate(
        params, params, prompt, steps, k=4, dtype=jnp.float32, **CFG,
        draft_num_layers=CFG["num_layers"], draft_num_heads=CFG["num_heads"],
        draft_hidden=CFG["hidden"],
    )
    np.testing.assert_array_equal(np.asarray(out), ref)
    assert int(calls) <= -(-steps // 5) + 1, int(calls)  # ceil(10/5)=2 (+1 slack)


def test_speculative_decode_validates_shapes():
    import pytest as _pytest

    from kubegpu_tpu.models.speculative import speculative_generate

    params = trained_params()
    prompt = jnp.ones((1, 5), jnp.int32)
    with _pytest.raises(ValueError, match="exceeds max_seq"):
        speculative_generate(
            params, params, prompt, 30, k=4, dtype=jnp.float32, **CFG,
            draft_num_layers=CFG["num_layers"],
            draft_num_heads=CFG["num_heads"], draft_hidden=CFG["hidden"],
        )
    with _pytest.raises(ValueError, match="k must"):
        speculative_generate(
            params, params, prompt, 4, k=0, dtype=jnp.float32, **CFG,
            draft_num_layers=CFG["num_layers"],
            draft_num_heads=CFG["num_heads"], draft_hidden=CFG["hidden"],
        )


def test_continuous_batching_mixed_sampling():
    """Per-request sampling in the batcher: greedy requests in a mixed
    batch are bit-identical to an all-greedy run (sampling neighbors
    cannot perturb them); sampled requests are deterministic per seed,
    vary across seeds, and top_k=1 degenerates to greedy."""
    import numpy as np

    from kubegpu_tpu.models.serving import ContinuousBatcher

    params = trained_params()
    rng = np.random.RandomState(3)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=n), dtype=np.int32)
        for n in (3, 5, 4)
    ]
    budgets = [5, 5, 5]
    # all-greedy baseline
    base = ContinuousBatcher(
        params, slots=2, prompt_pad=8, dtype=jnp.float32, **CFG
    ).run(prompts, budgets)
    # mixed: request 1 samples hot, 0 and 2 stay greedy
    cb = ContinuousBatcher(
        params, slots=2, prompt_pad=8, dtype=jnp.float32, seed=7, **CFG
    )
    mixed = cb.run(prompts, budgets, temperatures=[0.0, 5.0, 0.0])
    assert mixed[0] == base[0] and mixed[2] == base[2], (
        "greedy requests perturbed by a sampling neighbor"
    )
    # same seed reproduces; a different seed explores
    again = ContinuousBatcher(
        params, slots=2, prompt_pad=8, dtype=jnp.float32, seed=7, **CFG
    ).run(prompts, budgets, temperatures=[0.0, 5.0, 0.0])
    assert again[1] == mixed[1]
    other = ContinuousBatcher(
        params, slots=2, prompt_pad=8, dtype=jnp.float32, seed=8, **CFG
    ).run(prompts, budgets, temperatures=[0.0, 5.0, 0.0])
    assert other[1] != mixed[1], "high-temperature stream did not vary by seed"
    # top_k=1 at any temperature IS greedy
    k1 = ContinuousBatcher(
        params, slots=2, prompt_pad=8, dtype=jnp.float32, top_k=1, **CFG
    ).run(prompts, budgets, temperatures=[2.0, 2.0, 2.0])
    for i in base:
        assert k1[i] == base[i]


def test_batchers_agree_on_oversized_prompt_with_zero_budget():
    """An oversized prompt must be rejected regardless of max_new: the
    dense batcher used to short-circuit on max_new<=0 BEFORE validating
    prompt length while the paged one validated first, so the same bad
    input silently succeeded on one and raised on the other (ADVICE r4)."""
    import numpy as np

    from kubegpu_tpu.models.paging import PagedContinuousBatcher
    from kubegpu_tpu.models.serving import ContinuousBatcher

    params = trained_params()
    too_long = np.arange(9, dtype=np.int32)  # prompt_pad is 8
    dense = ContinuousBatcher(
        params, slots=1, prompt_pad=8, dtype=jnp.float32, **CFG
    )
    with pytest.raises(ValueError, match="prompt_pad"):
        dense.run([too_long], [0])
    paged = PagedContinuousBatcher(
        params, slots=1, prompt_pad=8, page_size=8, pool_pages=8,
        dtype=jnp.float32, **CFG
    )
    with pytest.raises(ValueError, match="prompt_pad"):
        paged.run([too_long], [0])
    # ...and a VALID zero-budget request is a no-op on both, even when the
    # paged pool could never hold it WITH a budget (zero pages needed)
    tight = PagedContinuousBatcher(
        params, slots=1, prompt_pad=8, page_size=2, pool_pages=3,
        dtype=jnp.float32, **CFG
    )
    fits_nothing = np.arange(6, dtype=np.int32)  # needs 3 pages; 2 allocatable
    assert tight.run([fits_nothing], [0]) == {0: []}
    assert dense.run([fits_nothing], [0]) == {0: []}


def test_speculative_batcher_matches_greedy_for_any_draft():
    """The speculative continuous batcher must emit EXACTLY the
    per-sequence greedy tokens for ANY draft — a hopeless one (independent
    random init: the all-reject path, one token per verify) and a perfect
    one (the target itself: the all-accept path).  The draft only moves
    ``stats['steps']``; slot reuse (5 sequences through 2 slots) exercises
    variable per-slot emission and mid-stream re-admission."""
    import numpy as np

    from kubegpu_tpu.models.spec_serving import SpeculativeContinuousBatcher

    params = trained_params()
    rng = np.random.RandomState(11)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=n), dtype=np.int32)
        for n in (3, 5, 7, 4, 6)
    ]
    budgets = [6, 3, 5, 7, 4]
    expected = {}
    for i, (p, n) in enumerate(zip(prompts, budgets)):
        out = greedy_generate(
            params, jnp.asarray(p)[None, :], n, dtype=jnp.float32, **CFG
        )
        expected[i] = list(np.asarray(out)[0, len(p):])

    draft_cfg = dict(num_layers=1, num_heads=2, hidden=16)
    draft = TransformerLM(
        vocab_size=CFG["vocab_size"], max_seq=CFG["max_seq"],
        dtype=jnp.float32, **draft_cfg,
    )
    draft_params = draft.init(
        jax.random.PRNGKey(7), jnp.ones((2, 8), jnp.int32)
    )["params"]
    hopeless = SpeculativeContinuousBatcher(
        params, draft_params, slots=2, prompt_pad=8, k=3,
        draft_num_layers=1, draft_num_heads=2, draft_hidden=16,
        dtype=jnp.float32, **CFG,
    )
    got = hopeless.run(prompts, budgets)
    for i in expected:
        assert got[i] == expected[i], (i, got[i], expected[i])
    assert hopeless.stats["admits"] == 5
    assert hopeless.stats["tokens"] >= sum(
        b - 1 for b in budgets
    )  # first tokens come from admit, the rest from steps

    perfect = SpeculativeContinuousBatcher(
        params, params, slots=2, prompt_pad=8, k=3,
        draft_num_layers=CFG["num_layers"],
        draft_num_heads=CFG["num_heads"], draft_hidden=CFG["hidden"],
        dtype=jnp.float32, **CFG,
    )
    got2 = perfect.run(prompts, budgets)
    for i in expected:
        assert got2[i] == expected[i], (i, got2[i], expected[i])
    # a perfect draft accepts every proposal: step-tokens per verify
    # approach k+1, so the verify count drops below the hopeless one
    assert perfect.stats["steps"] < hopeless.stats["steps"]


def test_speculative_batcher_guards():
    """Greedy-only and k-headroom contracts fail loudly, and the
    validation ORDER matches the dense batchers on shared inputs."""
    import numpy as np

    from kubegpu_tpu.models.spec_serving import SpeculativeContinuousBatcher

    params = trained_params()
    sb = SpeculativeContinuousBatcher(
        params, params, slots=1, prompt_pad=8, k=4,
        draft_num_layers=CFG["num_layers"],
        draft_num_heads=CFG["num_heads"], draft_hidden=CFG["hidden"],
        dtype=jnp.float32, **CFG,
    )
    with pytest.raises(ValueError, match="greedy-only"):
        sb.run([np.array([1, 2], np.int32)], [2], temperatures=[1.0])
    with pytest.raises(ValueError, match="prompt_pad"):
        sb.run([np.arange(9, dtype=np.int32)], [0])
    # max_seq 32: prompt 8 + max_new 22 fits the dense bound but not the
    # k=4 headroom
    with pytest.raises(ValueError, match="headroom"):
        sb.run([np.arange(8, dtype=np.int32)], [22])
    # zero-budget no-op agrees with the dense batchers
    assert sb.run([np.array([1, 2, 3], np.int32)], [0]) == {0: []}


def test_paged_batcher_mixed_sampling_matches_dense_batcher():
    """The paged batcher's sampling recipe mirrors the dense one exactly:
    same seed + traffic -> same sampled tokens through both (fp32)."""
    import numpy as np

    from kubegpu_tpu.models.paging import PagedContinuousBatcher
    from kubegpu_tpu.models.serving import ContinuousBatcher

    params = trained_params()
    rng = np.random.RandomState(4)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=n), dtype=np.int32)
        for n in (3, 6)
    ]
    budgets = [4, 6]
    temps = [3.0, 0.0]
    dense = ContinuousBatcher(
        params, slots=2, prompt_pad=8, dtype=jnp.float32, seed=5, **CFG
    ).run(prompts, budgets, temperatures=temps)
    paged = PagedContinuousBatcher(
        params, slots=2, prompt_pad=8, page_size=8, pool_pages=12,
        dtype=jnp.float32, seed=5, **CFG
    ).run(prompts, budgets, temperatures=temps)
    for i in dense:
        assert paged[i] == dense[i], (i, paged[i], dense[i])


def test_speculative_decode_composes_with_int8_target():
    """Spec x int8: a weight-only-quantized TARGET under draft
    verification must emit EXACTLY plain int8 greedy's sequence (the
    draft stays bf16/fp32 — the cheap model needs no quantization).  The
    losslessness proof carries over unchanged because verification
    compares the target's own logits, quantized or not."""
    import numpy as np

    from kubegpu_tpu.models.decoding import quantize_params_int8
    from kubegpu_tpu.models.speculative import speculative_generate

    params = trained_params()
    qparams = quantize_params_int8(params)
    prompt = (jnp.arange(2 * 5, dtype=jnp.int32) % CFG["vocab_size"]).reshape(2, 5)
    steps = 10
    # plain int8 greedy consumes qparams — the oracle sequence
    ref_q = np.asarray(
        greedy_generate(
            qparams, prompt, steps, dtype=jnp.float32, quant=True, **CFG
        )
    )
    draft_cfg = dict(num_layers=1, num_heads=2, hidden=16)
    draft = TransformerLM(
        dtype=jnp.float32, vocab_size=CFG["vocab_size"], max_seq=CFG["max_seq"],
        **draft_cfg,
    )
    draft_params = draft.init(
        jax.random.PRNGKey(7), jnp.ones((2, 8), jnp.int32)
    )["params"]
    out, calls = speculative_generate(
        qparams, draft_params, prompt, steps, k=3, dtype=jnp.float32,
        quant=True, **CFG, draft_num_layers=1, draft_num_heads=2,
        draft_hidden=16,
    )
    np.testing.assert_array_equal(np.asarray(out), ref_q)
    assert 1 <= int(calls) <= steps
