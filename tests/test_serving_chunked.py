"""Chunked prefill + paged prefix cache: token-identity and page
accounting.

The serving hot path's two new mechanisms must be INVISIBLE in the
output: chunked prefill (prompt sliced into fixed chunks interleaved
with decode) and prefix-cache page sharing (content-addressed K/V reuse)
each reproduce the monolithic-prefill greedy tokens exactly, for prompt
lengths straddling every chunk/page boundary.  And the pool must balance
— refcounts back to zero, every page free/cached/live — after any mix of
finishes and cancels, including the GatewaySoak kill schedule."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubegpu_tpu.models import TransformerLM, greedy_generate
from kubegpu_tpu.models.paging import PagedContinuousBatcher
from kubegpu_tpu.models.serving import ContinuousBatcher
from kubegpu_tpu.utils.metrics import Metrics

pytestmark = pytest.mark.slow

CFG = dict(vocab_size=61, num_layers=2, num_heads=4, hidden=32, max_seq=32)


def trained_params():
    model = TransformerLM(dtype=jnp.float32, **CFG)
    return model.init(jax.random.PRNGKey(0), jnp.ones((2, 8), jnp.int32))[
        "params"
    ]


def oracle(params, prompt, n):
    out = greedy_generate(
        params, jnp.asarray(prompt)[None, :], n, dtype=jnp.float32, **CFG
    )
    return list(np.asarray(out)[0, len(prompt):])


# ---------------------------------------------------------------------------
# Chunked prefill: token-identical to monolithic across chunk boundaries
# ---------------------------------------------------------------------------

def test_chunked_prefill_token_identical_across_boundaries():
    """Greedy, fixed seed: every prompt length straddling the chunk
    boundary (below, at, just past, multiple chunks, partial tail) must
    produce EXACTLY the monolithic-prefill tokens — and the per-sequence
    greedy oracle's."""
    params = trained_params()
    rng = np.random.RandomState(0)
    chunk = 4
    # lengths 1..9 straddle chunk=4 at 3/4/5 and 2*chunk at 7/8/9
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=n), np.int32)
        for n in (1, 2, 3, 4, 5, 7, 8, 9)
    ]
    budgets = [5, 4, 6, 3, 5, 4, 6, 5]
    expected = {
        i: oracle(params, p, n)
        for i, (p, n) in enumerate(zip(prompts, budgets))
    }
    mono = ContinuousBatcher(
        params, slots=3, prompt_pad=16, prefill_chunk=None,
        dtype=jnp.float32, **CFG,
    ).run(prompts, budgets)
    assert mono == expected
    cb = ContinuousBatcher(
        params, slots=3, prompt_pad=16, prefill_chunk=chunk,
        dtype=jnp.float32, **CFG,
    )
    got = cb.run(prompts, budgets)
    assert got == expected, {
        i: (got[i], expected[i]) for i in expected if got[i] != expected[i]
    }
    # the chunk count proves chunking actually happened: sum over
    # prompts of ceil((plen-1)/chunk)
    want_chunks = sum(-(-(len(p) - 1) // chunk) for p in prompts)
    assert cb.stats["prefill_chunks"] == want_chunks


def test_chunked_prefill_bounds_work_per_step():
    """A long prompt admitted while another sequence decodes adds at
    most ONE chunk of prefill per serving iteration — the running
    sequence keeps emitting every step (the ITL bound chunking buys)."""
    params = trained_params()
    rng = np.random.RandomState(3)
    runner = np.array(rng.randint(0, CFG["vocab_size"], size=2), np.int32)
    longp = np.array(rng.randint(0, CFG["vocab_size"], size=16), np.int32)
    cb = ContinuousBatcher(
        params, slots=2, prompt_pad=16, prefill_chunk=4,
        dtype=jnp.float32, **CFG,
    )
    cb.submit(0, runner, 12)
    cb.serve_step()  # runner active, one token out
    assert len(cb._slots[0].tokens) == 1
    cb.submit(1, longp, 4, session_id="s1")
    emitted = [len(cb._slots[0].tokens)]
    done = {}
    while cb.has_work():
        done.update(cb.serve_step())
        emitted.append(len(cb._slots[0].tokens))
    # the runner emitted on EVERY iteration until it finished (no
    # multi-step stall while the 16-token prompt prefilled in chunks)
    deltas = [b - a for a, b in zip(emitted, emitted[1:]) if a < 12]
    assert all(d == 1 for d in deltas), deltas
    assert done[0] == oracle(params, runner, 12)
    assert done[1] == oracle(params, longp, 4)


def test_chunked_prefill_validates_chunk_size():
    params = trained_params()
    with pytest.raises(ValueError, match="prefill_chunk"):
        ContinuousBatcher(
            params, slots=1, prompt_pad=8, prefill_chunk=0,
            dtype=jnp.float32, **CFG,
        )
    with pytest.raises(ValueError, match="multiple of page_size"):
        PagedContinuousBatcher(
            params, slots=1, prompt_pad=8, page_size=4, prefill_chunk=6,
            dtype=jnp.float32, **CFG,
        )


# ---------------------------------------------------------------------------
# Paged prefix cache: sharing is invisible in the tokens
# ---------------------------------------------------------------------------

def make_paged(params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("prompt_pad", 20)
    kw.setdefault("page_size", 4)
    kw.setdefault("pool_pages", 24)
    return PagedContinuousBatcher(params, dtype=jnp.float32, **CFG, **kw)


def test_prefix_cache_two_turn_session_token_identical():
    """The two-turn conversation shape: turn 2's prompt extends turn 1's.
    Turn 2 must hit the cached prefix pages (prefix_hit_tokens > 0) and
    still emit exactly the tokens a cache-less batcher emits — for
    second-turn lengths straddling the page boundary."""
    params = trained_params()
    rng = np.random.RandomState(1)
    turn1 = np.array(rng.randint(0, CFG["vocab_size"], size=9), np.int32)
    cb = make_paged(params)
    out1 = cb.run([turn1], [4])[0]
    assert out1 == oracle(params, turn1, 4)
    assert len(cb.prefix_cache) == 2  # (9-1)//4 full pages registered
    for extra in (1, 3, 4):  # extensions straddling the page boundary
        turn2 = np.concatenate([
            turn1, np.asarray(out1, np.int32),
            np.array(rng.randint(0, CFG["vocab_size"], size=extra), np.int32),
        ])
        expected = oracle(params, turn2, 5)
        cold = make_paged(params, prefix_cache=False)
        assert cold.run([turn2], [5])[0] == expected
        got = cb.run([turn2], [5])[0]  # run() resets stats per call
        assert got == expected, (extra, got, expected)
        assert cb.stats["prefix_hit_tokens"] >= 8, (
            "turn 2 did not reuse turn 1's prompt pages"
        )
        cb.assert_page_accounting()


def test_prefix_cache_concurrent_shared_system_prompt():
    """Two live requests sharing a system-prompt prefix share physical
    pages (refcount 2 while both run), diverge after it, and both match
    their oracles; the pool balances afterwards."""
    params = trained_params()
    rng = np.random.RandomState(2)
    system = np.array(rng.randint(0, CFG["vocab_size"], size=8), np.int32)
    a = np.concatenate([system, np.array([3, 7], np.int32)])
    b = np.concatenate([system, np.array([11, 5, 2], np.int32)])
    cb = make_paged(params)
    got = cb.run([a, b], [5, 6])
    assert got[0] == oracle(params, a, 5)
    assert got[1] == oracle(params, b, 6)
    # the 2 full system pages were computed once and shared
    assert cb.stats["prefix_hit_tokens"] >= 8
    cb.assert_page_accounting()
    assert all(
        cb.prefix_cache.refcount(p) == 0 for p in cb.prefix_cache.pages()
    )


def test_prefix_cache_lru_eviction_recomputes_correctly():
    """Pool pressure evicts idle cached pages LRU; a later request whose
    prefix was evicted recomputes it and still matches the oracle."""
    params = trained_params()
    rng = np.random.RandomState(4)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=9), np.int32)
        for _ in range(4)
    ]
    # pool with room for ~one live request + a couple cached pages:
    # each needs ceil((9+4)/4) = 4 pages
    cb = make_paged(params, slots=1, pool_pages=7)
    exp = {i: oracle(params, p, 4) for i, p in enumerate(prompts)}
    got = cb.run(prompts, [4, 4, 4, 4])
    assert got == exp
    cb.assert_page_accounting()
    # re-serve prompt 0 (its cache entries were evicted by later admits):
    # recompute, same tokens
    assert cb.run([prompts[0]], [4])[0] == exp[0]
    cb.assert_page_accounting()


def test_page_refcounts_zero_after_random_cancel_finish_schedule():
    """Property: a seeded random schedule of submit / serve / cancel
    (queued, mid-prefill, mid-decode) leaves the pool balanced — every
    page free, cached-idle, or provably-live, and every refcount equal
    to its live holders; after draining, refcounts are all zero."""
    params = trained_params()
    rng = np.random.RandomState(5)
    cb = make_paged(params, slots=3, pool_pages=16)
    seq = 0
    live = []
    for _ in range(60):
        roll = rng.rand()
        if roll < 0.45:
            n = rng.randint(1, 13)
            prompt = np.array(
                rng.randint(0, CFG["vocab_size"], size=n), np.int32
            )
            max_new = int(rng.randint(1, 5))
            if n + max_new <= CFG["max_seq"] and n <= cb.prompt_pad:
                cb.submit(seq, prompt, max_new)
                live.append(seq)
                seq += 1
        elif roll < 0.65 and live:
            victim = live.pop(rng.randint(len(live)))
            cb.cancel(victim)
        else:
            done = cb.serve_step()
            for s in done:
                live.remove(s)
        cb.assert_page_accounting()
    while cb.has_work():
        for s in cb.serve_step():
            live.remove(s)
    cb.assert_page_accounting()
    assert all(
        cb.prefix_cache.refcount(p) == 0 for p in cb.prefix_cache.pages()
    )
    assert not live


def test_gateway_soak_kill_schedule_no_page_leaks():
    """The GatewaySoak kill/revive/hedge schedule over REAL paged
    batchers: invariant I5 plus page accounting on every surviving
    replica at quiescence (the soak's check calls
    assert_page_accounting on any batcher exposing it)."""
    from kubegpu_tpu.testing.soak import GatewaySoak

    tiny = dict(vocab_size=61, num_layers=1, num_heads=2, hidden=16,
                max_seq=16)
    params = TransformerLM(dtype=jnp.float32, **tiny).init(
        jax.random.PRNGKey(1), jnp.ones((1, 4), jnp.int32)
    )["params"]
    soak = GatewaySoak(
        # workload prompts must fit the replicas' prompt_pad below
        seed=11, n_replicas=2, follow_prompt_cap=4,
        batcher_factory=lambda key: PagedContinuousBatcher(
            params, slots=4, prompt_pad=4, page_size=4, pool_pages=20,
            dtype=jnp.float32, **tiny,
        ),
    )
    soak.run(steps=18)


# ---------------------------------------------------------------------------
# Serving metrics flow through utils.metrics
# ---------------------------------------------------------------------------

def test_serving_metrics_histograms_and_counters():
    """Both batchers feed serve_ttft/serve_itl histograms and the
    prefill-chunk / prefix-hit counters into a shared Metrics registry —
    the same registry a gateway renders at /metrics."""
    params = trained_params()
    rng = np.random.RandomState(6)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=9), np.int32)
        for _ in range(2)
    ]
    m = Metrics()
    cb = ContinuousBatcher(
        params, slots=2, prompt_pad=16, prefill_chunk=4,
        dtype=jnp.float32, metrics=m, **CFG,
    )
    cb.run(prompts, [4, 4])
    assert m.histogram_count("serve_ttft_seconds") == 2
    assert m.histogram_count("serve_itl_seconds") == 6  # 2 x (4-1)
    assert m.get("serve_prefill_chunks_total") == 4     # 2 x ceil(8/4)
    assert m.quantile("serve_itl_seconds", 0.95) >= 0.0
    pm = Metrics()
    pb = make_paged(params, metrics=pm)
    pb.run([prompts[0], prompts[0]], [4, 4])
    assert pm.histogram_count("serve_ttft_seconds") == 2
    # hits split by the hit page's kind — labeled series ONLY, so
    # sum() over the family is the true total: prompt-station pages
    # here (the default decode_page_cache="off" seals nothing at
    # retirement), so the decode counter never appears
    assert pm.get("serve_prefix_hit_tokens_total", kind="prompt") > 0
    assert pm.get("serve_prefix_hit_tokens_total", kind="decode") == 0
    assert pm.get("serve_prefix_hit_tokens_total") == 0  # no unlabeled twin
    assert pm.get("serve_prompt_tokens_total") == 18
    # the token-budget station observes submit->first-chunk wait per
    # admission and tracks its occupancy as a gauge
    assert pm.histogram_count("serve_prefill_wait_seconds") == 2
    assert pm.histogram_sum("serve_prefill_wait_seconds") >= 0.0
    text = pm.render()
    assert "serve_ttft_seconds_count 2" in text
    assert "serve_prefix_hit_tokens_total" in text
    assert 'serve_prefix_hit_tokens_total{kind="prompt"}' in text
    assert "serve_prefill_wait_seconds_count 2" in text
    assert "# TYPE serve_station_slots_busy gauge" in text
    assert "serve_station_slots_busy 0.0" in text  # drained at rest
