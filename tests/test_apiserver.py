"""KubeApiServer wire coverage against a local stub HTTPS API server.

The real in-cluster client was previously untested (VERDICT r1 missing #1):
here a stub speaking the k8s REST dialect runs over TLS with a self-signed
CA, and the client is exercised end to end — bearer-token auth, CA pinning,
merge-patch bodies and content types, 404/409 → NotFound/Conflict mapping,
the pods/binding subresource, and the ?watch=true long-poll stream.  Plus
one full-control-plane pass: Advertiser → Scheduler → bind THROUGH the real
REST client against the stub.
"""

import ipaddress
import json
import ssl
import threading
import urllib.parse
from datetime import datetime, timedelta, timezone
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubegpu_tpu.utils.apiserver import Conflict, KubeApiServer, NotFound


# ---------------------------------------------------------------------------
# self-signed TLS material (the stand-in for the service-account CA bundle)
# ---------------------------------------------------------------------------

def make_tls(tmpdir):
    # a box without the optional TLS test dependency SKIPS these tests
    # cleanly (they exercise the wire client's cert handling, nothing
    # else) — an ERROR here is pure noise drowning real regressions
    pytest.importorskip("cryptography")
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "127.0.0.1")])
    now = datetime.now(timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - timedelta(days=1))
        .not_valid_after(now + timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName(
                [
                    x509.IPAddress(ipaddress.ip_address("127.0.0.1")),
                    x509.DNSName("localhost"),
                ]
            ),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    cert_path = tmpdir / "ca.crt"
    key_path = tmpdir / "ca.key"
    cert_path.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    key_path.write_bytes(
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        )
    )
    return str(cert_path), str(key_path)


# ---------------------------------------------------------------------------
# the stub API server (k8s REST dialect, in-memory state)
# ---------------------------------------------------------------------------

class StubState:
    def __init__(self):
        self.nodes = {}
        self.pods = {}          # "ns/name" -> obj
        self.leases = {}        # "ns/name" -> obj (rv-CAS'd like the real one)
        self.requests = []      # (method, path, content_type, auth)
        self.events = []        # POSTed v1 Events
        self.watch_events = []  # node events [{"type": ..., "object": ...}]
        self.pod_watch_events = []  # pod events, same shape
        self.watch_poll_s = 0.0  # >0: long-poll for NEW events this long
        self.lock = threading.Lock()


def make_stub_handler(state: StubState):
    class Handler(BaseHTTPRequestHandler):
        # HTTP/1.0: close delimits the watch stream like a k8s watch timeout

        def log_message(self, fmt, *args):
            pass

        def _record(self):
            with state.lock:
                state.requests.append(
                    (
                        self.command,
                        self.path,
                        self.headers.get("Content-Type", ""),
                        self.headers.get("Authorization", ""),
                    )
                )

        def _body(self):
            length = int(self.headers.get("Content-Length", "0"))
            raw = self.rfile.read(length)
            return json.loads(raw) if raw else {}

        def _send(self, code, payload):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _stream_watch(self, events=None):
            import time as _time

            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            sent = 0
            deadline = _time.monotonic() + state.watch_poll_s
            while True:
                with state.lock:
                    pending = (
                        state.watch_events if events is None else events
                    )[sent:]
                for evt in pending:
                    self.wfile.write(json.dumps(evt).encode() + b"\n")
                    self.wfile.flush()
                sent += len(pending)
                if _time.monotonic() >= deadline:
                    break  # k8s watch timeout; client re-watches
                _time.sleep(0.05)

        def _lease_parts(self, parts):
            """('ns', 'name'|None) if this is a coordination.k8s.io lease
            path, else None."""
            if parts[:4] == ["apis", "coordination.k8s.io", "v1", "namespaces"]:
                if len(parts) == 7 and parts[5] == "leases":
                    return parts[4], parts[6]
                if len(parts) == 6 and parts[5] == "leases":
                    return parts[4], None
            return None

        def do_PUT(self):
            self._record()
            parts = self.path.strip("/").split("/")
            lp = self._lease_parts(parts)
            if lp and lp[1]:
                key = f"{lp[0]}/{lp[1]}"
                body = self._body()
                with state.lock:
                    cur = state.leases.get(key)
                    if cur is None:
                        return self._send(404, {"reason": "NotFound"})
                    cur_rv = cur["metadata"].get("resourceVersion")
                    if (body.get("metadata") or {}).get("resourceVersion") != cur_rv:
                        return self._send(409, {"reason": "Conflict"})
                    body["metadata"]["resourceVersion"] = str(int(cur_rv) + 1)
                    state.leases[key] = body
                return self._send(200, body)
            self._send(404, {"reason": "NotFound"})

        def do_GET(self):
            self._record()
            url = urllib.parse.urlparse(self.path)
            parts = url.path.strip("/").split("/")
            lp = self._lease_parts(parts)
            if lp and lp[1]:
                lease = state.leases.get(f"{lp[0]}/{lp[1]}")
                return (
                    self._send(200, lease)
                    if lease
                    else self._send(404, {"reason": "NotFound"})
                )
            if url.path == "/api/v1/nodes":
                if "watch=true" in (url.query or ""):
                    return self._stream_watch()
                return self._send(200, {"items": list(state.nodes.values())})
            if parts[:3] == ["api", "v1", "nodes"] and len(parts) == 4:
                node = state.nodes.get(parts[3])
                return (
                    self._send(200, node)
                    if node
                    else self._send(404, {"reason": "NotFound"})
                )
            if url.path == "/api/v1/pods":
                if "watch=true" in (url.query or ""):
                    return self._stream_watch(state.pod_watch_events)
                return self._send(200, {"items": list(state.pods.values())})
            if parts[:3] == ["api", "v1", "namespaces"] and len(parts) == 5:
                return self._send(200, {
                    "items": [
                        p for k, p in state.pods.items()
                        if k.startswith(parts[3] + "/")
                    ]
                })
            if parts[:3] == ["api", "v1", "namespaces"] and len(parts) == 6:
                pod = state.pods.get(f"{parts[3]}/{parts[5]}")
                return (
                    self._send(200, pod)
                    if pod
                    else self._send(404, {"reason": "NotFound"})
                )
            self._send(404, {"reason": "NotFound"})

        def do_POST(self):
            self._record()
            parts = self.path.strip("/").split("/")
            body = self._body()
            lp = self._lease_parts(parts)
            if lp and lp[1] is None:
                ns = lp[0]
                name = (body.get("metadata") or {}).get("name", "")
                key = f"{ns}/{name}"
                with state.lock:
                    if key in state.leases:
                        return self._send(409, {"reason": "AlreadyExists"})
                    body.setdefault("metadata", {})["resourceVersion"] = "1"
                    state.leases[key] = body
                return self._send(201, body)
            # pods/{name}/binding subresource
            if len(parts) == 7 and parts[-1] == "binding":
                key = f"{parts[3]}/{parts[5]}"
                pod = state.pods.get(key)
                if pod is None:
                    return self._send(404, {"reason": "NotFound"})
                if pod.setdefault("spec", {}).get("nodeName"):
                    return self._send(409, {"reason": "AlreadyBound"})
                pod["spec"]["nodeName"] = body.get("target", {}).get("name", "")
                return self._send(201, {})
            if len(parts) == 5 and parts[4] == "events":
                with state.lock:
                    state.events.append(body)
                return self._send(201, body)
            if len(parts) == 5 and parts[4] == "pods":
                ns = parts[3]
                name = body.get("metadata", {}).get("name", "")
                key = f"{ns}/{name}"
                if key in state.pods:
                    return self._send(409, {"reason": "AlreadyExists"})
                body.setdefault("metadata", {}).setdefault("namespace", ns)
                with state.lock:
                    state.pods[key] = body
                    state.pod_watch_events.append(
                        {"type": "ADDED", "object": json.loads(json.dumps(body))}
                    )
                return self._send(201, body)
            self._send(404, {"reason": "NotFound"})

        def do_PATCH(self):
            self._record()
            parts = self.path.strip("/").split("/")
            body = self._body()
            if parts[:3] == ["api", "v1", "nodes"] and len(parts) in (4, 5):
                # mutate AND snapshot under the lock: handler threads are
                # concurrent (ThreadingHTTPServer), and a torn snapshot
                # would stream a half-updated node to the watch client
                with state.lock:
                    name = parts[3]
                    node = state.nodes.setdefault(
                        name, {"metadata": {"name": name}}
                    )
                    if len(parts) == 5 and parts[4] == "status":
                        status = node.setdefault("status", {})
                        for k in ("capacity", "allocatable"):
                            status.setdefault(k, {}).update(
                                body.get("status", {}).get(k, {})
                            )
                    else:
                        node.setdefault("metadata", {}).setdefault(
                            "annotations", {}
                        ).update(body.get("metadata", {}).get("annotations", {}))
                    # node mutations become watch events, like a real API
                    # server's MODIFIED notifications
                    snapshot = json.loads(json.dumps(node))
                    state.watch_events.append(
                        {"type": "MODIFIED", "object": snapshot}
                    )
                # respond with the locked-in snapshot, not the live dict
                return self._send(200, snapshot)
            if parts[:3] == ["api", "v1", "namespaces"] and len(parts) == 6:
                pod = state.pods.get(f"{parts[3]}/{parts[5]}")
                if pod is None:
                    return self._send(404, {"reason": "NotFound"})
                with state.lock:
                    pod.setdefault("metadata", {}).setdefault(
                        "annotations", {}
                    ).update(body.get("metadata", {}).get("annotations", {}))
                    state.pod_watch_events.append(
                        {"type": "MODIFIED",
                         "object": json.loads(json.dumps(pod))}
                    )
                return self._send(200, pod)
            self._send(404, {"reason": "NotFound"})

        def do_DELETE(self):
            self._record()
            parts = self.path.strip("/").split("/")
            if parts[:3] == ["api", "v1", "namespaces"] and len(parts) == 6:
                key = f"{parts[3]}/{parts[5]}"
                if key not in state.pods:
                    return self._send(404, {"reason": "NotFound"})
                with state.lock:
                    snapshot = json.loads(json.dumps(state.pods[key]))
                    del state.pods[key]
                    state.pod_watch_events.append(
                        {"type": "DELETED", "object": snapshot}
                    )
                return self._send(200, {})
            self._send(404, {"reason": "NotFound"})

    return Handler


@pytest.fixture()
def stub(tmp_path, monkeypatch):
    cert, key = make_tls(tmp_path)
    token = tmp_path / "token"
    token.write_text("sekret-token\n")
    state = StubState()
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_stub_handler(state))
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert, key)
    httpd.socket = ctx.wrap_socket(httpd.socket, server_side=True)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    monkeypatch.setattr(KubeApiServer, "CA", cert)
    monkeypatch.setattr(KubeApiServer, "TOKEN", str(token))
    api = KubeApiServer(base_url=f"https://127.0.0.1:{httpd.server_address[1]}")
    yield api, state
    httpd.shutdown()
    httpd.server_close()


# ---------------------------------------------------------------------------
# client coverage
# ---------------------------------------------------------------------------

def test_nodes_roundtrip_with_auth_and_merge_patch(stub):
    api, state = stub
    assert api.list_nodes() == []
    api.patch_node_annotations("h0", {"kubegpu-tpu/topology": "xyz"})
    api.patch_node_capacity("h0", {"google.com/tpu": "4"})
    nodes = api.list_nodes()
    assert len(nodes) == 1
    n = api.get_node("h0")
    assert n["metadata"]["annotations"]["kubegpu-tpu/topology"] == "xyz"
    assert n["status"]["capacity"]["google.com/tpu"] == "4"
    assert n["status"]["allocatable"]["google.com/tpu"] == "4"
    # every request carried the bearer token; patches used merge-patch
    for method, path, ctype, auth in state.requests:
        assert auth == "Bearer sekret-token"
        if method == "PATCH":
            assert ctype == "application/merge-patch+json", (path, ctype)


def test_pod_lifecycle_and_error_mapping(stub):
    api, state = stub
    with pytest.raises(NotFound):
        api.get_pod("default", "ghost")
    with pytest.raises(NotFound):
        api.delete_pod("default", "ghost")
    obj = {"metadata": {"name": "p1", "namespace": "default"}, "spec": {}}
    api.create_pod(obj)
    with pytest.raises(Conflict):
        api.create_pod(obj)
    api.patch_pod_annotations("default", "p1", {"k": "v"})
    assert api.get_pod("default", "p1")["metadata"]["annotations"]["k"] == "v"
    assert len(api.list_pods("default")) == 1
    assert len(api.list_pods()) == 1
    api.bind_pod("default", "p1", "h7")
    assert api.get_pod("default", "p1")["spec"]["nodeName"] == "h7"
    with pytest.raises(Conflict):
        api.bind_pod("default", "p1", "h8")
    api.delete_pod("default", "p1")
    assert api.list_pods() == []


def test_watch_nodes_streams_events_and_reconnects(stub):
    api, state = stub
    state.watch_events = [
        {"type": "ADDED", "object": {"metadata": {"name": "h0"}}},
        {"type": "MODIFIED", "object": {"metadata": {"name": "h0"}}},
        {"type": "DELETED", "object": {"metadata": {"name": "h1"}}},
        {"type": "BOOKMARK", "object": {}},  # unknown types are ignored
    ]
    got = []
    stop = threading.Event()

    def handler(event, obj):
        got.append((event, obj.get("metadata", {}).get("name")))
        if len(got) >= 3:
            stop.set()

    # the stub closes the stream after each pass (watch timeout); the
    # client must re-establish — requiring >1 GET proves the reconnect loop
    t = threading.Thread(
        target=api.watch_nodes, args=(handler, stop), kwargs={"timeout_s": 5}
    )
    t.start()
    t.join(timeout=30)
    assert not t.is_alive()
    assert got[:3] == [
        ("node-updated", "h0"),
        ("node-updated", "h0"),
        ("node-deleted", "h1"),
    ]


def test_extender_daemon_watch_eviction_through_rest_client(stub):
    """The deployed shape end to end: the ExtenderServer DAEMON (watch
    thread + resync backstop) runs against the REAL REST client over the
    stub TLS API server.  Advertise → schedule → the advertiser's health
    patch lands as a watch MODIFIED event → chip-death eviction DELETEs
    the pod through the wire, with resync parked so only the watch can
    have fired it."""
    import time

    from kubegpu_tpu.plugins import Advertiser, FakeSlice
    from kubegpu_tpu.scheduler import Scheduler
    from kubegpu_tpu.scheduler.server import ExtenderServer
    from kubegpu_tpu.types import annotations

    api, state = stub
    state.watch_poll_s = 3.0  # real long-poll: new events stream live
    fs = FakeSlice(slice_id="s0", mesh_shape=(2, 2), host_block=(2, 2))
    advs = {h: Advertiser(p, api) for h, p in fs.providers().items()}
    for a in advs.values():
        a.advertise_once()

    server = ExtenderServer(Scheduler(api), listen=("127.0.0.1", 0),
                            resync_interval_s=3600.0)
    server.start()
    try:
        obj = {
            "metadata": {"name": "victim", "namespace": "default",
                         "annotations": {}},
            "spec": {"containers": [
                {"name": "main",
                 "resources": {"limits": {"google.com/tpu": "1"}}}]},
        }
        api.create_pod(obj)
        nodes = sorted(n["metadata"]["name"] for n in api.list_nodes())
        r = server.sched.filter(obj, nodes)
        assert r.nodes, r.failed
        assert server.sched.bind("default", "victim", r.nodes[0]) is None
        assignment = annotations.assignment_from_pod(
            api.get_pod("default", "victim")
        )
        ref = assignment.all_chips()[0]

        fs.kill_chip(ref.coords)
        advs[ref.host].advertise_once()  # PATCH → MODIFIED watch event
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if "default/victim" not in state.pods:
                break
            time.sleep(0.1)
        assert "default/victim" not in state.pods, (
            "watch event over the REST wire did not evict the pod"
        )
        # the eviction explained itself: a ChipFailure Warning Event was
        # POSTed through the same REST client
        chip_events = [e for e in state.events if e.get("reason") == "ChipFailure"]
        assert chip_events, [e.get("reason") for e in state.events]
        assert chip_events[0]["involvedObject"]["name"] == "victim"
        assert chip_events[0]["type"] == "Warning"
    finally:
        server.stop()


def test_pod_watch_invalidates_gang_plan_without_ttl(stub):
    """VERDICT r2 #2 done-condition: deleting a pending gang member over
    the wire triggers plan invalidation in <1 s with the plan TTL cranked
    to HOURS — proving the gang lifecycle is event-driven (pod watch), not
    TTL/resync-pull.  A replacement member then re-plans successfully."""
    import time

    from kubegpu_tpu.plugins import Advertiser, FakeSlice
    from kubegpu_tpu.scheduler import Scheduler
    from kubegpu_tpu.scheduler.server import ExtenderServer
    from kubegpu_tpu.types import annotations

    api, state = stub
    state.watch_poll_s = 3.0  # live long-poll stream
    fs = FakeSlice(slice_id="s0", mesh_shape=(2, 4), host_block=(2, 2))
    for prov in fs.providers().values():
        Advertiser(prov, api).advertise_once()

    server = ExtenderServer(
        Scheduler(api, gang_plan_ttl_s=3600.0),  # hours: TTL cannot fire
        listen=("127.0.0.1", 0),
        resync_interval_s=3600.0,                # resync cannot fire either
    )
    server.start()
    try:
        def gang_pod(name):
            return {
                "metadata": {
                    "name": name, "namespace": "default",
                    "annotations": {
                        annotations.POD_GROUP: "ring",
                        annotations.POD_GROUP_SIZE: "2",
                    },
                },
                "spec": {"containers": [
                    {"name": "main",
                     "resources": {"limits": {"google.com/tpu": "2"}}}]},
            }

        api.create_pod(gang_pod("g-a"))
        api.create_pod(gang_pod("g-b"))
        nodes = sorted(n["metadata"]["name"] for n in api.list_nodes())
        r = server.sched.filter(gang_pod("g-a"), nodes)
        assert r.nodes, r.failed
        assert server.sched.groups.has_live_plan("default/ring")
        assert server.sched.cache.assignment_of("default/g-b") is not None

        t0 = time.monotonic()
        api.delete_pod("default", "g-b")  # wire DELETE → watch DELETED event
        deadline = t0 + 10.0
        while time.monotonic() < deadline:
            if not server.sched.groups.has_live_plan("default/ring"):
                break
            time.sleep(0.02)
        elapsed = time.monotonic() - t0
        assert not server.sched.groups.has_live_plan("default/ring"), (
            "pod DELETED event did not invalidate the gang plan"
        )
        assert elapsed < 1.0, f"plan invalidation took {elapsed:.2f}s"
        # the dead member's reservation was returned, not leaked
        assert server.sched.cache.assignment_of("default/g-b") is None

        # a replacement member re-plans the gang on the freed chips
        api.create_pod(gang_pod("g-c"))
        r2 = server.sched.filter(gang_pod("g-c"), nodes)
        assert r2.nodes, r2.failed
        assert server.sched.groups.has_live_plan("default/ring")
    finally:
        server.stop()


def test_full_control_plane_through_rest_client(stub):
    """Advertiser → Scheduler filter/bind entirely THROUGH KubeApiServer:
    the same flow the in-memory e2e drives, now over real HTTPS wire."""
    from kubegpu_tpu.plugins import Advertiser, FakeSlice
    from kubegpu_tpu.scheduler import Scheduler
    from kubegpu_tpu.types import annotations

    api, state = stub
    fs = FakeSlice(slice_id="s0", mesh_shape=(2, 2), host_block=(2, 2))
    for prov in fs.providers().values():
        Advertiser(prov, api).advertise_once()
    assert len(api.list_nodes()) == 1  # 2x2 slice, one (2,2)-host

    sched = Scheduler(api)
    sched.cache.refresh()
    obj = {
        "metadata": {"name": "w0", "namespace": "default", "annotations": {}},
        "spec": {"containers": [
            {"name": "main", "resources": {"limits": {"google.com/tpu": "2"}}}
        ]},
    }
    api.create_pod(obj)
    nodes = sorted(n["metadata"]["name"] for n in api.list_nodes())
    r = sched.filter(obj, nodes)
    assert r.nodes, r.failed
    err = sched.bind("default", "w0", r.nodes[0])
    assert not err
    pod = api.get_pod("default", "w0")
    a = annotations.assignment_from_pod(pod)
    assert a is not None and len(a.all_chips()) == 2
    assert pod["spec"]["nodeName"] == r.nodes[0]


def test_response_socket_chain_is_live(stub):
    """Pin the CPython http.client internals _response_socket() relies on
    (ADVICE r3 low): close_watches' prompt-shutdown guarantee depends on
    reaching the real socket to shutdown(SHUT_RDWR) — plain close() does
    NOT wake a reader blocked in recv().  If an interpreter upgrade breaks
    the attribute chain, this test fails loudly instead of the shutdown
    path silently degrading to the slow quiet-window timeout."""
    import time

    from kubegpu_tpu.utils.apiserver import _response_socket

    api, state = stub
    state.watch_poll_s = 10.0  # keep the stream open while we inspect it
    stop = threading.Event()
    t = threading.Thread(
        target=api.watch_nodes, args=(lambda e, o: None, stop),
        kwargs={"timeout_s": 10},
    )
    t.start()
    try:
        deadline = time.monotonic() + 5.0
        conns = []
        while time.monotonic() < deadline and not conns:
            with api._watch_lock:
                conns = list(api._watch_conns)
            time.sleep(0.02)
        assert conns, "watch stream never established"
        sock = _response_socket(conns[0])
        assert sock is not None, (
            "_response_socket could not reach the live watch socket — "
            "close_watches would silently lose prompt shutdown"
        )
        # and the full shutdown path is prompt: well under the 15 s
        # quiet-window fallback the close() path would need
        t0 = time.monotonic()
        stop.set()
        api.close_watches()
        t.join(timeout=5.0)
        assert not t.is_alive()
        assert time.monotonic() - t0 < 5.0
    finally:
        stop.set()
        t.join(timeout=5.0)


def test_lease_verbs_over_the_wire_with_cas(stub):
    """KubeApiServer's coordination.k8s.io Lease verbs against the TLS
    stub: create (POST), read (GET), CAS update (PUT with resourceVersion,
    409 -> Conflict on a stale version) — then a real LeaderElector
    acquiring and renewing THROUGH the REST client."""
    from kubegpu_tpu.utils.leaderelection import LeaderElector

    api, state = stub
    with pytest.raises(NotFound):
        api.get_lease("kube-system", "ha")
    obj = {
        "apiVersion": "coordination.k8s.io/v1",
        "kind": "Lease",
        "metadata": {"name": "ha", "namespace": "kube-system"},
        "spec": {"holderIdentity": "x", "leaseDurationSeconds": 15},
    }
    created = api.create_lease(obj)
    assert created["metadata"]["resourceVersion"] == "1"
    with pytest.raises(Conflict):
        api.create_lease(obj)
    lease = api.get_lease("kube-system", "ha")
    lease["spec"]["holderIdentity"] = "y"
    api.update_lease("kube-system", "ha", lease)
    with pytest.raises(Conflict):
        # same (now stale) resourceVersion again: the CAS must reject
        api.update_lease("kube-system", "ha", lease)
    # a real elector drives acquire-then-renew over the wire (the existing
    # holder "y" never renewed a timestamp, so its lease reads as stale)
    elector = LeaderElector(api, "replica-1", name="ha",
                            lease_duration_s=15.0, renew_period_s=5.0)
    assert elector.try_acquire_or_renew() == "ok"
    assert elector.try_acquire_or_renew() == "ok"  # renew
    stored = api.get_lease("kube-system", "ha")
    assert stored["spec"]["holderIdentity"] == "replica-1"
    assert stored["spec"]["leaseTransitions"] == 1
    # every request carried the bearer token
    lease_reqs = [r for r in state.requests if "leases" in r[1]]
    assert lease_reqs and all(r[3] == "Bearer sekret-token" for r in lease_reqs)
