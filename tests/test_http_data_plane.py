"""The distributed data plane end to end, over REAL loopback sockets
(ISSUE 10): replica HTTP serving (``ReplicaServer``) + the streaming
``HttpReplicaClient`` with wire-level cancel.

The acceptance claims:

- gateway → 2 HTTP replicas serves token-IDENTICALLY to the in-memory
  data plane (same tiny fp32 paged batchers both sides);
- a mid-stream cancel — and a client that simply vanishes — frees the
  sequence's pages ON THE REPLICA, across the wire;
- a deadline-expired attempt cancels on the wire (the replica stops
  decoding, not just the gateway);
- a request's trace tree spans BOTH processes: replica-side serve spans
  grafted under the gateway's dispatch span, one retire per serve
  subtree still enforced;
- in-cluster readiness is REAL: the registry's HTTP probe drains a
  replica whose serving endpoint dies, and /readyz follows;
- the GatewaySoak kill schedule holds page accounting across the wire
  (SimBatcher lane fast; the paged spec+multiturn schedule slow).
"""

import http.client
import json
import socket
import threading
import time
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubegpu_tpu.gateway import (
    FailoverPolicy,
    Gateway,
    GatewayRequest,
    GatewayServer,
    HttpReplicaClient,
    InMemoryReplicaClient,
    ReplicaServer,
    SimBatcher,
)
from kubegpu_tpu.models import TransformerLM
from kubegpu_tpu.models.paging import PagedContinuousBatcher
from kubegpu_tpu.testing.fake_serving import build_fake_serving_stack
from kubegpu_tpu.utils.metrics import Metrics
from kubegpu_tpu.utils.tracing import (
    serve_retire_violations,
    validate_trace,
)

TINY = dict(vocab_size=61, num_layers=1, num_heads=2, hidden=16, max_seq=48)
PAGED_KW = dict(slots=3, prompt_pad=12, page_size=4, pool_pages=32,
                dtype=jnp.float32)


@pytest.fixture(scope="module")
def tiny_params():
    return TransformerLM(dtype=jnp.float32, **TINY).init(
        jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32)
    )["params"]


def _paged(tiny_params, **over):
    kw = dict(PAGED_KW, **TINY)
    kw.update(over)
    return PagedContinuousBatcher(tiny_params, **kw)


def _req(rid, prompt, max_new, **kw):
    return types.SimpleNamespace(
        request_id=rid, prompt=list(map(int, prompt)),
        max_new_tokens=max_new, temperature=0.0, session=None, **kw,
    )


def _wait(cond, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# protocol basics (SimBatcher: fast, deterministic token mill)
# ---------------------------------------------------------------------------

def test_replica_server_streams_incremental_batches_then_done():
    srv = ReplicaServer(SimBatcher(slots=4), step_delay_s=0.001).start()
    client = HttpReplicaClient(endpoints={"r0": srv.endpoint})
    try:
        deltas = []
        a = client.submit("r0", _req(
            "rq", [1, 2, 3], 8, on_tokens=lambda at, d: deltas.append(d)
        ))
        assert a.wait(10) and a.result().ok, a.result()
        # the data plane seeds the mill from the PROMPT (request-
        # deterministic streams, like real greedy decode) — not the
        # replica-local slot id
        from kubegpu_tpu.gateway.client import sim_stream_seed

        seed = sim_stream_seed([1, 2, 3])
        expect = [(seed * 31 + i) % 256 for i in range(8)]
        assert a.result().tokens == expect
        # incremental events reassemble EXACTLY into the final stream,
        # and genuinely arrived in more than one flush
        assert sum(deltas, []) == expect
        assert len(deltas) > 1, deltas
        assert client.decodes.get("rq") == 1
    finally:
        srv.stop()
        client.stop()


def test_bearer_auth_gates_v1_verbs_plain_http():
    """Bearer auth without TLS (the knobs compose but don't require
    each other — and this leg keeps auth covered in tier-1, where the
    cryptography dep for the TLS tests may be absent): /v1/* refuses
    without the token, serves with it, /healthz and /metrics stay
    open."""
    srv = ReplicaServer(
        SimBatcher(slots=4), step_delay_s=0.001, auth_token="tok",
    ).start()
    good = HttpReplicaClient(
        endpoints={"r": srv.endpoint}, auth_token="tok",
    )
    bad = HttpReplicaClient(endpoints={"r": srv.endpoint})
    try:
        a = bad.submit("r", _req("x", [1, 2], 4))
        assert a.wait(10), "401 attempt hung"
        assert not a.result().ok and "401" in a.result().error
        assert bad._get_state("r") is None
        ok, why = bad.probe(types.SimpleNamespace(key="r", addr=None))
        assert ok, why  # liveness open: token skew must not drain pods
        a = good.submit("r", _req("y", [1, 2], 4))
        assert a.wait(10) and a.result().ok, a.result()
        assert good._get_state("r")["slots"] == 4
        # metrics scrape stays open too
        import http.client as _http

        host, port = srv.address
        conn = _http.HTTPConnection(host, port, timeout=5.0)
        conn.request("GET", "/metrics")
        assert conn.getresponse().status == 200
        conn.close()
    finally:
        good.stop()
        bad.stop()
        srv.stop()


def test_replica_state_advertises_contract_and_connection_reuse():
    srv = ReplicaServer(SimBatcher(slots=4, tp=1)).start()
    client = HttpReplicaClient(endpoints={"r0": srv.endpoint})
    try:
        a1 = client.submit("r0", _req("a", [1], 3))
        assert a1.wait(10) and a1.result().ok
        # the completed stream returns its connection to the pool (the
        # reader thread checks it in right after resolving the attempt);
        # the second submit must reuse it (the pool holds exactly one)
        def pooled_count():
            with client._lock:
                return len(client._pool.get("r0", []))
        _wait(lambda: pooled_count() == 1, timeout=10,
              msg="connection returned to the pool")
        with client._lock:
            pooled = client._pool["r0"][0]
        a2 = client.submit("r0", _req("b", [2], 3))
        assert a2.wait(10) and a2.result().ok
        _wait(lambda: pooled_count() == 1, timeout=10,
              msg="connection back in the pool after reuse")
        with client._lock:
            assert client._pool["r0"] == [pooled]
        assert client.advertised() == {"r0": {"tp": 1}}
        state = client._get_state("r0")
        assert state["slots"] == 4 and state["active_streams"] == 0
    finally:
        srv.stop()
        client.stop()


def test_unreachable_and_killed_replica_resolve_as_errors():
    srv = ReplicaServer(SimBatcher(slots=2), step_delay_s=0.02).start()
    client = HttpReplicaClient(endpoints={"r0": srv.endpoint})
    try:
        a = client.submit("nowhere", _req("x", [1], 4))
        assert a.wait(1) and not a.result().ok
        assert "unreachable" in a.result().error
        inflight = client.submit("r0", _req("y", [1], 400))
        time.sleep(0.05)
        srv.stop()  # process death: in-flight stream errors explicitly
        assert inflight.wait(10), "attempt hung across replica death"
        assert not inflight.result().ok
    finally:
        client.stop()


# ---------------------------------------------------------------------------
# acceptance: gateway → 2 HTTP replicas ≡ in-memory data plane
# ---------------------------------------------------------------------------

def test_gateway_http_replicas_token_identical_to_inmemory(tiny_params):
    rs = np.random.RandomState(5)
    prompts = [
        rs.randint(0, 61, size=rs.randint(3, 12)).astype(np.int32)
        for _ in range(6)
    ]
    budgets = [6, 10, 4, 8, 5, 12]

    def drive(make_client):
        stack = build_fake_serving_stack(2)
        registry = stack.registry
        registry.refresh()
        client, servers = make_client(registry)
        registry.subscribe(client.sync_live)
        registry.refresh()
        gw = Gateway(
            registry, client, metrics=Metrics(), dispatchers=4,
            policy=FailoverPolicy(deadline_s=60.0, hedge_after_s=30.0),
        )
        gw.start()
        try:
            pendings = [
                gw.submit(GatewayRequest(
                    prompt=[int(t) for t in prompts[i]],
                    max_new_tokens=budgets[i], request_id=f"r{i}",
                ))
                for i in range(len(prompts))
            ]
            assert gw.drain(120.0)
            out = {}
            for i, p in enumerate(pendings):
                r = p.result()
                assert r.status == "ok", (i, r.status, r.error)
                out[i] = r.tokens
            return out
        finally:
            gw.stop()
            client.stop()
            for srv in servers:
                srv.stop()

    def http_client(registry):
        client = HttpReplicaClient()
        servers = []
        for rep in registry.live():
            srv = ReplicaServer(_paged(tiny_params)).start()
            servers.append(srv)
            client.set_endpoint(rep.key, srv.endpoint)
        return client, servers

    def inmemory_client(registry):
        client = InMemoryReplicaClient(
            batcher_factory=lambda key: _paged(tiny_params)
        )
        for rep in registry.live():
            client.add_replica(rep.key)
        return client, []

    over_wire = drive(http_client)
    in_memory = drive(inmemory_client)
    # greedy fp32 paged decode is a pure function of (prompt, budget):
    # the wire must be a TRANSPORT, not a numerics or bookkeeping layer
    assert over_wire == in_memory


# ---------------------------------------------------------------------------
# acceptance: wire-level cancel frees pages on the replica
# ---------------------------------------------------------------------------

def test_midstream_cancel_frees_pages_across_the_wire(tiny_params):
    cb = _paged(tiny_params)
    srv = ReplicaServer(cb).start()
    client = HttpReplicaClient(endpoints={"r0": srv.endpoint})
    try:
        deltas = []
        a = client.submit("r0", _req(
            "long", [1, 2, 3], 30,
            on_tokens=lambda at, d: deltas.append(d),
        ))
        _wait(lambda: deltas, msg="first streamed tokens")
        client.cancel(a)
        assert a.wait(15), "cancel did not resolve the attempt"
        assert not a.result().ok
        # the replica must actually STOP (pages freed), not finish the
        # budget into a stream nobody reads
        _wait(lambda: not cb.has_work(), msg="replica idle after cancel")
        assert sum(len(d) for d in deltas) < 30
        cb.assert_page_accounting()
    finally:
        srv.stop()
        client.stop()


def test_client_disconnect_cancels_sequence_on_replica(tiny_params):
    cb = _paged(tiny_params)
    srv = ReplicaServer(cb).start()
    try:
        host, port = srv.address
        s = socket.create_connection((host, port), timeout=10)
        body = json.dumps({
            "request_id": "vanish", "prompt": [4, 5, 6],
            "max_new_tokens": 30,
        }).encode()
        s.sendall(
            b"POST /v1/submit HTTP/1.1\r\nHost: x\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: %d\r\n\r\n" % len(body) + body
        )
        s.recv(256)   # response headers arrived: the stream is live
        time.sleep(0.05)
        s.close()     # vanish — no /v1/cancel, no clean shutdown
        _wait(lambda: not cb.has_work(),
              msg="replica cancelled the abandoned stream")
        cb.assert_page_accounting()
        assert srv.metrics.get(
            "replica_http_disconnect_cancels_total") >= 1
    finally:
        srv.stop()


def test_deadline_expired_attempt_cancels_on_the_wire():
    # slow mill: 2 steps/s means the 100-token budget cannot finish
    # inside the deadline — the CLIENT must cancel wire-level
    batcher = SimBatcher(slots=2)
    srv = ReplicaServer(batcher, step_delay_s=0.05).start()
    client = HttpReplicaClient(endpoints={"r0": srv.endpoint})
    try:
        a = client.submit("r0", _req(
            "dl", [1], 100, deadline_s=0.4, enqueued_at=time.monotonic(),
        ))
        assert a.wait(10), "deadline attempt never resolved"
        assert not a.result().ok
        assert "deadline" in a.result().error
        _wait(lambda: not batcher.has_work(), timeout=10,
              msg="replica stopped decoding after wire cancel")
    finally:
        srv.stop()
        client.stop()


# ---------------------------------------------------------------------------
# acceptance: one trace tree across both processes
# ---------------------------------------------------------------------------

def test_trace_tree_spans_gateway_and_replica(tiny_params):
    stack = build_fake_serving_stack(1)
    registry = stack.registry
    registry.refresh()
    cb = _paged(tiny_params)
    srv = ReplicaServer(cb).start()
    client = HttpReplicaClient()
    client.set_endpoint(registry.live()[0].key, srv.endpoint)
    gw = Gateway(registry, client, metrics=Metrics(), dispatchers=2)
    gw.start()
    try:
        p = gw.submit(GatewayRequest(
            prompt=[1, 2, 3, 4], max_new_tokens=5, request_id="traced",
        ))
        assert gw.drain(60.0) and p.result().status == "ok"
        assert gw.tracer.wait_quiescent(10.0)
        spans = next(
            s for s in gw.tracer.completed()
            if any(x["attrs"].get("request_id") == "traced" for x in s
                   if x["parent"] is None)
        )
        problems = validate_trace(spans) + serve_retire_violations(spans)
        assert not problems, problems
        by_id = {s["span"]: s for s in spans}
        serve = next(s for s in spans if s["name"] == "serve")
        # the serve subtree is REMOTE (replica-side, grafted) and hangs
        # under this gateway's dispatch span via the replica root
        assert serve["attrs"].get("remote") is True
        hop = by_id[serve["parent"]]
        assert hop["name"] == "replica_request"
        dispatch = by_id[hop["parent"]]
        assert dispatch["name"] == "dispatch"
        assert not dispatch["attrs"].get("remote")
        # phase spans crossed the wire too: the replica-side decode span
        # with its first-token annotation nests under serve
        names = {s["name"] for s in spans if s["attrs"].get("remote")}
        assert {"serve", "queue", "decode", "retire"} <= names
    finally:
        gw.stop()
        client.stop()
        srv.stop()


# ---------------------------------------------------------------------------
# acceptance: in-cluster /readyz from live HTTP replica health
# ---------------------------------------------------------------------------

def test_registry_http_probe_drives_readyz():
    stack = build_fake_serving_stack(2)
    registry = stack.registry
    registry.refresh()
    client = HttpReplicaClient()
    servers = {}
    for rep in registry.live():
        srv = ReplicaServer(SimBatcher(slots=4)).start()
        servers[rep.key] = srv
        client.set_endpoint(rep.key, srv.endpoint)
    registry.probe = client.probe
    registry.subscribe(client.sync_live)
    registry.refresh()
    gw = Gateway(registry, client, metrics=Metrics(), dispatchers=2)
    server = GatewayServer(gw, listen=("127.0.0.1", 0), watch=False)
    server.start()
    host, port = server.address

    def readyz():
        c = http.client.HTTPConnection(host, port, timeout=5)
        c.request("GET", "/readyz")
        r = c.getresponse()
        body = r.read()
        c.close()
        return r.status, body.decode()

    try:
        assert readyz()[0] == 200
        keys = sorted(servers)
        # the control plane still believes in this pod (annotations,
        # chip health) but its serving process is GONE: only the HTTP
        # probe can know — and /readyz must follow it
        servers[keys[0]].stop()
        registry.refresh()
        assert len(registry.live()) == 1
        dead = next(r for r in registry.all() if not r.healthy)
        assert "data plane" in dead.reason
        assert readyz()[0] == 200  # one live replica still serves
        servers[keys[1]].stop()
        registry.refresh()
        status, body = readyz()
        assert status == 503, (status, body)
    finally:
        server.stop()
        client.stop()
        for srv in servers.values():
            srv.stop()


# ---------------------------------------------------------------------------
# gateway SSE pass-through to the caller
# ---------------------------------------------------------------------------

def test_gateway_streams_tokens_through_to_caller():
    stack = build_fake_serving_stack(1)
    registry = stack.registry
    registry.refresh()
    client = HttpReplicaClient()
    srv = ReplicaServer(SimBatcher(slots=4), step_delay_s=0.001).start()
    client.set_endpoint(registry.live()[0].key, srv.endpoint)
    gw = Gateway(registry, client, metrics=Metrics(), dispatchers=2)
    server = GatewayServer(gw, listen=("127.0.0.1", 0), watch=False)
    server.start()
    host, port = server.address
    try:
        c = http.client.HTTPConnection(host, port, timeout=15)
        c.request(
            "POST", "/v1/generate",
            json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 8,
                        "stream": True}),
            {"Content-Type": "application/json"},
        )
        r = c.getresponse()
        assert r.status == 200
        assert r.getheader("Content-Type") == "text/event-stream"
        streamed, terminal, ev = [], None, None
        while True:
            line = r.readline()
            if not line:
                break
            line = line.strip().decode()
            if line.startswith("event:"):
                ev = line[6:].strip()
            elif line.startswith("data:") and ev:
                data = json.loads(line[5:].strip())
                if ev == "tokens":
                    streamed += data["tokens"]
                elif ev in ("done", "error"):
                    terminal = (ev, data)
        c.close()
        assert terminal is not None and terminal[0] == "done", terminal
        assert terminal[1]["status"] == "ok"
        # un-hedged stream: the relayed deltas ARE the final result
        assert not terminal[1]["hedged"]
        assert streamed == terminal[1]["tokens"]
        assert gw.metrics.get("gateway_stream_requests_total") == 1
        assert gw.metrics.get("gateway_stream_tokens_total") == len(streamed)
    finally:
        server.stop()
        client.stop()
        srv.stop()


def test_gateway_stream_caller_disconnect_cancels_down_to_replica(
        tiny_params):
    stack = build_fake_serving_stack(1)
    registry = stack.registry
    registry.refresh()
    cb = _paged(tiny_params)
    client = HttpReplicaClient()
    # a slow decode loop: the budget CANNOT finish before the gateway
    # notices the dead caller, so the test deterministically exercises
    # the abort path instead of racing a fast completion
    srv = ReplicaServer(cb, step_delay_s=0.05).start()
    client.set_endpoint(registry.live()[0].key, srv.endpoint)
    gw = Gateway(registry, client, metrics=Metrics(), dispatchers=2)
    server = GatewayServer(gw, listen=("127.0.0.1", 0), watch=False)
    server.start()
    host, port = server.address
    try:
        c = http.client.HTTPConnection(host, port, timeout=15)
        c.request(
            "POST", "/v1/generate",
            json.dumps({"prompt": [1, 2], "max_new_tokens": 40,
                        "stream": True}),
            {"Content-Type": "application/json"},
        )
        r = c.getresponse()
        # read until the first token event reaches us, then VANISH
        ev = None
        while True:
            line = r.readline().strip().decode()
            if line.startswith("event:"):
                ev = line[6:].strip()
            elif not line and ev == "tokens":
                break
        # a REAL disconnect: shutdown tears the fd down even though the
        # response object still holds a reference to it (plain close()
        # would leave the connection standing)
        c.sock.shutdown(socket.SHUT_RDWR)
        c.sock.close()
        # the abort propagates: gateway cancels the attempt wire-level,
        # the replica frees the sequence's pages
        _wait(lambda: not cb.has_work(), timeout=20,
              msg="replica idle after caller disconnect")
        cb.assert_page_accounting()
        _wait(lambda: gw.metrics.get(
            "gateway_stream_disconnects_total") >= 1, timeout=10,
            msg="disconnect counted")
        assert gw.drain(30.0)
    finally:
        server.stop()
        client.stop()
        srv.stop()


# ---------------------------------------------------------------------------
# soak: page accounting ACROSS THE WIRE under the kill schedule
# ---------------------------------------------------------------------------

def test_gateway_soak_http_sim_lane():
    """Fast wire-chaos lane: SimBatcher replicas behind real loopback
    sockets, kills = server death (connection refusal for new work,
    reset for in-flight), plus raw mid-stream disconnects — I5 and the
    trace oracles hold."""
    from kubegpu_tpu.testing.soak import GatewaySoak

    soak = GatewaySoak(seed=13, n_replicas=4, http=True)
    soak.run(50)


@pytest.mark.slow
def test_gateway_soak_http_paged_kill_schedule(tiny_params):
    """The acceptance schedule ACROSS THE WIRE: real paged batchers
    (speculation + multi-turn decode-page caching, fp32 sealing) behind
    HTTP replica servers; kills, hedge-cancel losers and raw mid-stream
    disconnects interleaved — at quiescence every surviving replica's
    page pool balances, judged over the wire-driven batchers."""
    from kubegpu_tpu.testing.soak import GatewaySoak

    tiny = dict(vocab_size=61, num_layers=1, num_heads=2, hidden=16,
                max_seq=32)
    params = TransformerLM(dtype=jnp.float32, **tiny).init(
        jax.random.PRNGKey(1), jnp.ones((1, 4), jnp.int32)
    )["params"]
    soak = GatewaySoak(
        seed=31, n_replicas=2, multiturn=True, follow_prompt_cap=12,
        http=True,
        batcher_factory=lambda key: PagedContinuousBatcher(
            params, slots=4, prompt_pad=12, page_size=4, pool_pages=48,
            station_slots=2, token_budget=8, dtype=jnp.float32,
            decode_page_cache="fp32",
            draft_params=params, speculate_k=2, draft_window=16,
            draft_num_layers=tiny["num_layers"],
            draft_num_heads=tiny["num_heads"],
            draft_hidden=tiny["hidden"], **tiny,
        ),
    )
    soak.run(steps=20)
