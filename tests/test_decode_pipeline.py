"""Device-resident pipelined decode loop (ISSUE 8).

The paged decode loop keeps its state (last tokens, positions, page
tables, active mask, remaining budgets) on DEVICE and advances it
in-program; the host syncs tokens at ONE designated readback point, one
iteration late when ``pipeline_decode`` is on, so bookkeeping overlaps
device compute.  The pipelining must be INVISIBLE in the output:
greedy fp32 token-identical to the synchronous mode across speculation
× prefix hits × EOS/budget retirement × cancel churn × multi-turn
decode-page sealing, with page accounting balanced under the
GatewaySoak kill schedule and one compiled entry per program (including
the bucketed multi-page gather/scatter) across varied schedules.
"""

import inspect

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubegpu_tpu.models import TransformerLM, greedy_generate
from kubegpu_tpu.models import paging as paging_mod
from kubegpu_tpu.models.paging import PagedContinuousBatcher
from kubegpu_tpu.utils.metrics import Metrics

CFG = dict(vocab_size=61, num_layers=2, num_heads=4, hidden=32, max_seq=32)


def trained_params():
    model = TransformerLM(dtype=jnp.float32, **CFG)
    return model.init(jax.random.PRNGKey(0), jnp.ones((2, 8), jnp.int32))[
        "params"
    ]


def oracle(params, prompt, n):
    out = greedy_generate(
        params, jnp.asarray(prompt)[None, :], n, dtype=jnp.float32, **CFG
    )
    return list(np.asarray(out)[0, len(prompt):])


def make_paged(params, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("prompt_pad", 20)
    kw.setdefault("page_size", 4)
    kw.setdefault("pool_pages", 40)
    return PagedContinuousBatcher(params, dtype=jnp.float32, **CFG, **kw)


def spec_kw(params, k=2, **kw):
    return dict(
        draft_params=params, speculate_k=k,
        draft_num_layers=CFG["num_layers"],
        draft_num_heads=CFG["num_heads"], draft_hidden=CFG["hidden"],
        **kw,
    )


# ---------------------------------------------------------------------------
# Satellite: the hot path has ONE designated readback point (lint)
# ---------------------------------------------------------------------------

def test_decode_hot_path_single_readback_point():
    """The decode hot path must not grow back per-step host round-trips:
    ``serve_step`` and the dispatch functions contain NO asarray calls
    (state is device-resident, chained program-to-program), and the one
    designated readback lives in ``_process_entry``.  A stray
    ``np.asarray``/``jnp.asarray`` creeping into a dispatch function is
    exactly the per-token serialization this loop exists to kill."""
    hot = [
        PagedContinuousBatcher.serve_step,
        PagedContinuousBatcher._dispatch_step,
        PagedContinuousBatcher._dispatch_spec,
        PagedContinuousBatcher._loop_state,
        PagedContinuousBatcher._ledger_record,
        PagedContinuousBatcher._sweep,
    ]
    for fn in hot:
        src = inspect.getsource(fn)
        assert "asarray(" not in src, (
            f"{fn.__name__} grew a host round-trip: asarray outside the "
            "designated readback point (_process_entry)"
        )
    sync = inspect.getsource(PagedContinuousBatcher._process_entry)
    assert "np.asarray(" in sync and "READBACK" in sync, (
        "_process_entry is no longer the designated readback point"
    )
    # the per-step upload path survives ONLY as the synchronous
    # baseline, behind the pipeline_decode guard
    gate = inspect.getsource(PagedContinuousBatcher._loop_state)
    assert "if self.pipeline_decode" in gate
    assert "_host_loop_state" in gate


# ---------------------------------------------------------------------------
# Satellite (ISSUE 9): prefix-chain hashing lives at SUBMIT, not on the
# serving loop's admission probe
# ---------------------------------------------------------------------------

def test_prefix_chain_hashing_off_the_admission_hot_path():
    """Content hashing is O(prompt) sha256 work: it happens once at
    ``submit`` (chunk-incrementally, digest snapshotted per page
    boundary) and the serving loop's admission probe — which a deferred
    FIFO head re-runs EVERY sweep — does pure dict lookups.  Same lint
    pattern as the readback-point test above: hashing creeping back
    into the sweep path is exactly the per-probe rehash this hoist
    killed."""
    for fn in (
        PagedContinuousBatcher._try_begin_admit,
        PagedContinuousBatcher._sweep,
        PagedContinuousBatcher.serve_step,
        PagedContinuousBatcher._advance_prefill,
    ):
        src = inspect.getsource(fn)
        assert "sha256" not in src and "hashlib" not in src, (
            f"{fn.__name__} grew prefix hashing back onto the serving "
            "loop — it belongs in submit()"
        )
    submit_src = inspect.getsource(PagedContinuousBatcher.submit)
    assert "sha256" in submit_src, (
        "submit() no longer computes the prefix chain keys"
    )
    # retirement sealing keeps its own hash walk (it runs once per
    # retiring sequence, not per probe)
    assert "sha256" in inspect.getsource(
        PagedContinuousBatcher._seal_finished_pages
    )
    # and behavior: a prompt submitted, cancelled from the queue, then
    # resubmitted under the same seq_id still hits its prefix (the chain
    # keys ride the pending entry, so they die and recompute with it)
    params = trained_params()
    cb = make_paged(params)
    p = np.arange(9, dtype=np.int32) % 7
    out1 = cb.run([p], [3])[0]
    cb.submit(5, p, 3)
    cb.cancel(5)
    cb.submit(5, p, 3)
    done = {}
    while cb.has_work():
        done.update(cb.serve_step())
    assert done[5] == out1
    assert cb.stats["prefix_hit_tokens"] > 0
    cb.assert_page_accounting()
    # a seq_id queued TWICE (resubmit-while-queued, the supported
    # duplicate flow) must not crash or cross-wire chain keys: each
    # entry owns its own keys, both admissions serve
    cb.submit(7, p, 3)
    cb.submit(7, p, 3)
    done = {}
    while cb.has_work():
        done.update(cb.serve_step())
    assert done[7] == out1
    cb.assert_page_accounting()


# ---------------------------------------------------------------------------
# Satellite: the draft-ring gauge is set once, at construction
# ---------------------------------------------------------------------------

def test_draft_cache_rows_gauge_set_at_construction():
    """``serve_draft_cache_rows`` is a constant of the construction —
    it must be visible BEFORE any serve_step runs (and must not be
    re-set on the per-step path; the lint above keeps serve_step free
    of it)."""
    params = trained_params()
    m = Metrics()
    make_paged(params, metrics=m, **spec_kw(params, k=2, draft_window=24))
    assert m.gauge("serve_draft_cache_rows") == 4 * 24.0
    # a registry attached AFTER construction (the bench's
    # attach-after-warm pattern) still gets the gauge, from the first
    # ledger record
    cb = make_paged(params, **spec_kw(params, k=2, draft_window=24))
    late = Metrics()
    cb.metrics = late
    cb.run([np.array([1, 2, 3], np.int32)], [2])
    assert late.gauge("serve_draft_cache_rows") == 4 * 24.0
    # and it stays off the per-step path (the occupancy gauge is
    # per-step by design; this one is a construction constant)
    src = inspect.getsource(PagedContinuousBatcher.serve_step)
    assert "serve_draft_cache_rows" not in src


# ---------------------------------------------------------------------------
# Property: pipelined ≡ synchronous, across the matrix (slow tier below)
# ---------------------------------------------------------------------------

pipeline_matrix = pytest.mark.slow


@pipeline_matrix
def test_pipelined_token_identity_plain_and_spec():
    """Greedy fp32, mixed lengths straddling page boundaries, an
    in-burst duplicate (prefix hit), EOS retirement: the pipelined loop
    emits EXACTLY the synchronous loop's tokens — which are also the
    per-sequence oracle's — with and without speculation."""
    params = trained_params()
    rng = np.random.RandomState(1)
    lengths = (1, 3, 4, 5, 8, 9, 13)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=n), np.int32)
        for n in lengths
    ]
    prompts.append(prompts[4].copy())  # duplicate: prefix hit mid-burst
    budgets = [5, 4, 6, 3, 5, 6, 4, 5]
    expected = {
        i: oracle(params, p, n)
        for i, (p, n) in enumerate(zip(prompts, budgets))
    }
    for extra in (dict(), spec_kw(params, k=2)):
        sync = make_paged(params, pipeline_decode=False, **extra)
        got_sync = sync.run(prompts, budgets)
        assert got_sync == expected, ("sync", extra.keys())
        sync.assert_page_accounting()
        pipe = make_paged(params, pipeline_decode=True, **extra)
        got_pipe = pipe.run(prompts, budgets)
        assert got_pipe == expected, ("pipelined", extra.keys())
        pipe.assert_page_accounting()


@pipeline_matrix
def test_pipelined_first_token_syncs_eagerly():
    """A slot awaiting its FIRST token must not pay the pipeline lag:
    the serve_step that dispatches the first-token iteration also
    syncs it, so the step count to first emit matches sync mode (the
    TTFT phase-attribution gate's foundation)."""
    params = trained_params()
    rng = np.random.RandomState(2)
    prompt = np.array(rng.randint(0, CFG["vocab_size"], size=6), np.int32)

    def steps_to_first_token(pipeline):
        cb = make_paged(params, pipeline_decode=pipeline)
        cb.submit(0, prompt, 4)
        for step in range(50):
            cb.serve_step()
            if cb._seqs[0].tokens:
                return step
        raise AssertionError("no token in 50 steps")

    assert steps_to_first_token(True) == steps_to_first_token(False)


@pipeline_matrix
def test_lagged_eos_overhang_emits_nothing_past_eos_or_budget():
    """The overhang property: under pipelined readback the host learns
    of EOS/budget retirement one step late, but the emitted stream must
    still end exactly AT the EOS token (never past it) and never exceed
    max_new — with speculation and multi-turn sealing on, and the
    sealed-page chain identical to sync mode's."""
    params = trained_params()
    rng = np.random.RandomState(3)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=n), np.int32)
        for n in (3, 5, 7, 9, 4, 11)
    ]
    budgets = [8, 6, 9, 5, 7, 8]
    chains = {}
    sealed = {}
    outs = {}
    for pipeline in (False, True):
        for extra in (dict(), spec_kw(params, k=2)):
            label = (pipeline, bool(extra))
            # sweep EVERY eos id so some sequence genuinely retires on
            # EOS mid-stream (61-vocab argmaxes are dense in [0, 61))
            for eos in range(0, CFG["vocab_size"], 7):
                cb = make_paged(
                    params, pipeline_decode=pipeline, eos_id=eos,
                    decode_page_cache="fp32", **extra,
                )
                done = cb.run(prompts, budgets)
                for i, toks in done.items():
                    assert len(toks) <= budgets[i], (label, eos, i)
                    if eos in toks:
                        assert toks.index(eos) == len(toks) - 1, (
                            "token emitted past EOS", label, eos, i, toks
                        )
                cb.assert_page_accounting()
                if eos == 0:
                    chains[label] = set(cb.prefix_cache._entries.keys())
                    sealed[label] = cb.stats["decode_pages_sealed"]
                    outs[label] = done
    # pipelining must not change WHAT gets sealed (same streams, same
    # committed rows, same chain keys) nor the outputs
    for with_spec in (False, True):
        assert outs[(True, with_spec)] == outs[(False, with_spec)]
        assert chains[(True, with_spec)] == chains[(False, with_spec)]
        assert sealed[(True, with_spec)] == sealed[(False, with_spec)]
        assert sealed[(True, with_spec)] > 0, "schedule sealed nothing"


@pipeline_matrix
def test_pipelined_multiturn_hits_token_identical():
    """Turn-2 traffic through sealed decode pages, pipelined: the
    extended prompt hits the turn-1 chain (prompt AND decode pages) and
    the continuation is token-identical to a cold batcher's."""
    params = trained_params()
    rng = np.random.RandomState(4)
    turn1 = np.array(rng.randint(0, CFG["vocab_size"], size=6), np.int32)
    cb = make_paged(params, decode_page_cache="fp32", pipeline_decode=True)
    out1 = cb.run([turn1], [8])[0]
    assert cb.stats["decode_pages_sealed"] > 0
    turn2 = np.concatenate([
        turn1, np.asarray(out1, np.int32), np.array([9, 1, 4], np.int32),
    ])
    cold = make_paged(params, prefix_cache=False, pipeline_decode=True)
    expected = cold.run([turn2], [6])[0]
    got = cb.run([turn2], [6])[0]
    assert got == expected
    assert cb.stats["prefix_hit_tokens_decode"] > 0
    cb.assert_page_accounting()


@pipeline_matrix
def test_pipelined_cancel_churn_holds_accounting_and_outputs():
    """Random submit/cancel/step churn with pipelining, speculation and
    sealing on: every sequence that RETIRES normally emits its oracle
    stream (cancel timing may differ from sync mode — that only moves
    which requests die, never what survivors say), accounting balances
    at every step, and nothing leaks at drain."""
    params = trained_params()
    rng = np.random.RandomState(5)
    cb = make_paged(
        params, pool_pages=60, decode_page_cache="fp32",
        **spec_kw(params, k=2),
    )
    live, seq, submitted = [], 0, {}
    done = {}
    for _ in range(60):
        roll = rng.rand()
        if roll < 0.45:
            n = int(rng.randint(1, 14))
            prompt = np.array(
                rng.randint(0, CFG["vocab_size"], size=n), np.int32
            )
            max_new = int(rng.randint(1, 6))
            cb.submit(seq, prompt, max_new)
            submitted[seq] = (prompt, max_new)
            live.append(seq)
            seq += 1
        elif roll < 0.6 and live:
            cb.cancel(live.pop(rng.randint(len(live))))
        else:
            for s, toks in cb.serve_step().items():
                live.remove(s)
                done[s] = toks
        cb.assert_page_accounting()
    while cb.has_work():
        for s, toks in cb.serve_step().items():
            live.remove(s)
            done[s] = toks
    cb.assert_page_accounting()
    assert done, "churn retired nothing"
    for s, toks in done.items():
        prompt, max_new = submitted[s]
        assert toks == oracle(params, prompt, max_new), s


@pipeline_matrix
def test_overhang_window_cannot_corrupt_sealed_pages():
    """Regression (found by the decode-overhead bench): a slot the
    DEVICE retired keeps its table live until the host processes the
    retirement one step later, and the overhang speculative verify
    window writes rows past the sequence's reservation — where the
    table's padding points at the sequence's FIRST page, which pass 1
    sealed into the prefix cache.  Without the in-program dump-parking
    of inactive lanes, pass 2's hits read corrupted K/V: outputs
    drift between passes and accepts collapse.  Three passes of the
    same prompts through one warm batcher must stay token-identical
    (to each other and to sync mode), with accounting balanced."""
    params = trained_params()
    rng = np.random.RandomState(11)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=n), np.int32)
        for n in (8, 13, 17, 9)
    ]
    # budgets chosen so spec retirement is budget-CAPPED mid-window —
    # the uncapped device pos advance is what spills the overhang
    budgets = [6, 9, 11, 7]
    outs = {}
    for pipeline in (False, True):
        cb = make_paged(
            params, prompt_pad=20, pipeline_decode=pipeline,
            pool_pages=60, **spec_kw(params, k=2),
        )
        cb.submit(900, prompts[0][:5], 2)
        while cb.has_work():
            cb.serve_step()
        per_pass = []
        for _ in range(3):
            done = {}
            for j, p in enumerate(prompts):
                cb.submit(j, p, budgets[j])
            while cb.has_work():
                done.update(cb.serve_step())
            per_pass.append(done)
            cb.assert_page_accounting()
        assert per_pass[0] == per_pass[1] == per_pass[2], (
            "warm-cache passes drifted", pipeline
        )
        outs[pipeline] = per_pass[0]
    assert outs[True] == outs[False]


# ---------------------------------------------------------------------------
# Compile stability: 40-step schedule, one entry per program incl. buckets
# ---------------------------------------------------------------------------

@pipeline_matrix
def test_pipelined_compile_stability_fixed_jit_cache():
    """40 steps of cancels, prefix hits, speculation and station churn
    under pipelining: exactly ONE compiled entry per program — the
    chained step/draft/verify programs AND each bucketed multi-page
    gather/scatter width (run lengths pad to powers of two, so varied
    hit/flush sizes reuse a handful of programs instead of minting one
    per length)."""
    params = trained_params()
    rng = np.random.RandomState(6)
    cb = make_paged(
        params, station_slots=3, token_budget=11, prefill_chunk=8,
        pipeline_decode=True, **spec_kw(params, k=2),
    )
    seq, live = 0, []
    for _ in range(40):
        roll = rng.rand()
        if roll < 0.5:
            n = int(rng.randint(1, 13))
            max_new = int(rng.randint(0, 5))
            prompt = (
                np.arange(n, dtype=np.int32) % 7 if roll < 0.15
                else np.array(
                    rng.randint(0, CFG["vocab_size"], size=n), np.int32
                )
            )  # the arange prompts repeat -> prefix-cache hits
            cb.submit(seq, prompt, max_new)
            live.append(seq)
            seq += 1
        elif roll < 0.6 and live:
            cb.cancel(live.pop(rng.randint(len(live))))
        else:
            for s in cb.serve_step():
                live.remove(s)
    while cb.has_work():
        for s in cb.serve_step():
            live.remove(s)
    cb.assert_page_accounting()
    for name in ("_spec_draft", "_spec_verify", "_draft_admit", "_chunk"):
        assert getattr(cb, name)._cache_size() == 1, (
            f"{name}: {getattr(cb, name)._cache_size()} compiled entries"
        )
    assert cb._write_pages, "no multi-page scatter ran"
    for w, fn in cb._write_pages.items():
        assert fn._cache_size() == 1, f"scatter width {w} recompiled"
    for w, fn in cb._gather_pages.items():
        assert fn._cache_size() == 1, f"gather width {w} recompiled"
    # bucketing bounds the width set: powers of two up to the station's
    # page capacity (prompt_pad // page)
    cap = cb.prompt_pad // cb.page
    widths = set(cb._write_pages) | set(cb._gather_pages)
    assert all(
        (w & (w - 1)) == 0 or w == cap for w in widths
    ), widths
    assert all(w <= cap for w in widths), widths


# ---------------------------------------------------------------------------
# Ledger: the host/device overlap split is recorded per iteration
# ---------------------------------------------------------------------------

@pipeline_matrix
def test_ledger_records_host_device_split():
    params = trained_params()
    m = Metrics()
    cb = make_paged(params, metrics=m, pipeline_decode=True)
    rng = np.random.RandomState(7)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=5), np.int32)
        for _ in range(3)
    ]
    cb.run(prompts, [6, 6, 6])
    rows = cb.ledger_rows()
    assert rows
    for r in rows:
        assert r["host_ms"] >= 0.0 and r["device_ms"] >= 0.0
    # some iteration actually performed a readback
    assert any(r["device_ms"] > 0.0 for r in rows)
    assert m.gauge("serve_step_host_ms") >= 0.0
    assert m.gauge("serve_step_device_ms") >= 0.0


# ---------------------------------------------------------------------------
# Soak: kill schedule with pipelining + speculation + multiturn sealing
# ---------------------------------------------------------------------------

@pipeline_matrix
def test_gateway_soak_pipelined_kill_schedule():
    """The acceptance soak: GatewaySoak's kill/revive/hedge schedule
    with the multi-turn session op, over paged batchers with PIPELINED
    decode, speculation AND decode-page caching all enabled — invariant
    I5 plus page accounting on every surviving replica at quiescence.
    Kills and hedge-loser cancels land in the readback gap, so the
    lagged-retirement path is exactly what this schedule hunts."""
    from kubegpu_tpu.testing.soak import GatewaySoak

    tiny = dict(vocab_size=61, num_layers=1, num_heads=2, hidden=16,
                max_seq=32)
    params = TransformerLM(dtype=jnp.float32, **tiny).init(
        jax.random.PRNGKey(1), jnp.ones((1, 4), jnp.int32)
    )["params"]
    soak = GatewaySoak(
        seed=31, n_replicas=2, multiturn=True, follow_prompt_cap=12,
        batcher_factory=lambda key: PagedContinuousBatcher(
            params, slots=4, prompt_pad=12, page_size=4, pool_pages=48,
            station_slots=2, token_budget=8, dtype=jnp.float32,
            decode_page_cache="fp32", pipeline_decode=True,
            draft_params=params, speculate_k=2, draft_window=16,
            draft_num_layers=tiny["num_layers"],
            draft_num_heads=tiny["num_heads"],
            draft_hidden=tiny["hidden"], **tiny,
        ),
    )
    soak.run(steps=20)
