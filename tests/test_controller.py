"""The serving↔scheduling control loop (ISSUE 14): FleetController's
reconcile tick on a fake clock, the preemption checkpoint-and-requeue
contract, crash/restart resumption, the overload brownout ladder, and
shed-before-work on every plane.

The acceptance claims:

- hysteresis/cooldown/flap-damping make the decision stream calm: a
  pressure blip never scales the fleet, a reversal inside the flap
  window pays double cooldown;
- scale-up gang-schedules a REAL pod through the extender's filter/bind
  path; when the cluster is full it preempts strictly-lower-priority
  batch pods, checkpoints them, and recreates them PENDING so the
  release half of a later scale-down re-binds them (the full circle);
- a controller that crashes mid-reshape resumes idempotently: adopted
  drains release exactly once, unsettled write-ahead requeue snapshots
  replay without double-recreating;
- the brownout ladder climbs only when capacity cannot arrive in time
  (at max, or no placement even with preemption), degrades hedging →
  speculation → tenant shedding, and steps back down when calm;
- a request whose deadline expired while queued is shed BEFORE work on
  every plane: the gateway dispatcher, the in-memory replica inbox, and
  the HTTP replica endpoint (remaining deadline rides the wire) — all
  counted, all retryable.
"""

import json
import threading
import time
import types

import pytest

from kubegpu_tpu.controller import (
    ControllerConfig,
    EwmaSignal,
    FleetController,
    FleetObserver,
    JsonFileRequeueBackend,
    RequeueLedger,
    SignalSample,
)
from kubegpu_tpu.gateway import (
    AdmissionQueue,
    FailoverPolicy,
    Gateway,
    GatewayRequest,
    HttpReplicaClient,
    InMemoryReplicaClient,
    ReplicaServer,
    SimBatcher,
)
from kubegpu_tpu.testing.fake_serving import build_fake_serving_stack
from kubegpu_tpu.types import RES_TPU, annotations
from kubegpu_tpu.utils.metrics import Metrics

SERVING_PRIO = 50


def _cfg(**over):
    base = dict(
        min_replicas=1, max_replicas=4, queue_target_per_replica=4.0,
        ttft_target_s=0.5, ewma_alpha=1.0, up_ticks=1, down_ticks=1,
        up_cooldown_s=0.0, down_cooldown_s=0.0, flap_window_s=0.0,
        drain_grace_s=5.0, serving_priority=SERVING_PRIO,
        grow_retry_s=10.0,
    )
    base.update(over)
    return ControllerConfig(**base)


class _Harness:
    """Real control plane + gateway + in-memory data plane, fake clock."""

    def __init__(self, n_replicas=2, batcher=None, dispatchers=4,
                 queue_capacity=64, **cfg_over):
        self.metrics = Metrics()
        self.stack = build_fake_serving_stack(
            n_replicas, mesh=(4, 4), metrics=self.metrics,
            priority=SERVING_PRIO,
        )
        self.client = InMemoryReplicaClient(
            batcher_factory=batcher or (lambda key: SimBatcher(slots=8)),
            step_delay_s=0.001,
        )
        self.stack.registry.subscribe(self.client.sync_live)
        self.gw = Gateway(
            self.stack.registry, self.client,
            queue=AdmissionQueue(capacity=queue_capacity),
            policy=FailoverPolicy(deadline_s=30.0),
            metrics=self.metrics, dispatchers=dispatchers,
        )
        self.stack.registry.refresh()
        self.gw.start()
        self.now = 0.0
        self.checkpointed = []
        self.ctrl = self.make_controller(**cfg_over)

    def make_controller(self, requeue_ledger=None, **cfg_over):
        """A (re)started controller over the SAME observed state — the
        crash/restart tests build a second one of these."""
        return FleetController(
            api=self.stack.api, sched=self.stack.sched,
            registry=self.stack.registry, gateway=self.gw,
            client=self.client, metrics=self.metrics,
            clock=lambda: self.now,
            checkpointer=lambda obj: (
                self.checkpointed.append(obj["metadata"]["name"])
                or {"step": 7}
            ),
            requeue_ledger=requeue_ledger,
            config=_cfg(**cfg_over),
        )

    def free_chips(self) -> int:
        views = self.stack.sched.cache.views()
        return sum(len(v.free) for v in views.values())

    def fill_with_batch(self, priority=10, chips_each=1):
        """Bind batch pods on every free chip WITHOUT triggering any
        preemption (exactly as many as fit)."""
        nodes = sorted(
            n["metadata"]["name"] for n in self.stack.api.list_nodes()
        )
        names = []
        for i in range(self.free_chips() // chips_each):
            name = f"batch-{i}"
            self.stack.api.create_pod({
                "metadata": {"name": name, "namespace": "default",
                             "annotations": {
                                 annotations.POD_PRIORITY: str(priority),
                             }},
                "spec": {"containers": [{"name": "t", "resources": {
                    "limits": {RES_TPU: str(chips_each)}}}]},
            })
            r = self.stack.sched.filter(
                self.stack.api.get_pod("default", name), nodes
            )
            assert r.nodes, f"{name}: no placement ({r.failed})"
            assert self.stack.sched.bind(
                "default", name, r.nodes[0]
            ) is None
            names.append(name)
        assert self.free_chips() == 0
        return names

    def flood(self, k=40, max_new=4, tenant=""):
        return [
            self.gw.submit(GatewayRequest(
                prompt=[1, 2, 3], max_new_tokens=max_new,
                request_id=f"fl-{self.now}-{i}", tenant=tenant,
            ))
            for i in range(k)
        ]

    def settle(self, pends, timeout=30.0):
        for p in pends:
            assert p.wait(timeout), "request never resolved"

    def pods(self):
        return sorted(
            (o["metadata"] or {}).get("name", "")
            for o in self.stack.api.list_pods()
        )

    def stop(self):
        self.gw.stop()


@pytest.fixture
def h():
    harness = _Harness()
    yield harness
    harness.stop()


def _scripted(ctrl, samples):
    """Replace the controller's observer with a scripted sample stream
    (the last sample repeats) — the deterministic way to drive the
    decision arithmetic without real traffic timing."""
    it = {"i": 0}

    class _Obs:
        def sample(self):
            s = samples[min(it["i"], len(samples) - 1)]
            it["i"] += 1
            return s

        def gateways(self):
            return []

    ctrl.observer = _Obs()


def _high(routable=2):
    return SignalSample(queue_depth=100, routable=routable)


def _idle(routable=2):
    return SignalSample(queue_depth=0, routable=routable)


# ---------------------------------------------------------------------------
# 1. signal derivation
# ---------------------------------------------------------------------------

def test_ewma_seeds_with_first_sample_and_smooths():
    s = EwmaSignal(alpha=0.5)
    assert s.update(4.0) == 4.0       # no zero-bias warmup
    assert s.update(0.0) == 2.0
    assert s.update(0.0) == 1.0
    with pytest.raises(ValueError):
        EwmaSignal(alpha=0.0)
    with pytest.raises(ValueError):
        EwmaSignal(alpha=1.5)


def test_observer_ttft_window_is_the_diff_between_ticks():
    m = Metrics()
    stack = build_fake_serving_stack(1, metrics=m, priority=SERVING_PRIO)

    class _Gw:
        alive = True

        def in_flight(self):
            return 0

        queue = types.SimpleNamespace(depth=lambda: 0)

    obs = FleetObserver(stack.registry, _Gw(), m)
    obs.sample()                       # arm the window
    m.observe("gateway_ttft_seconds", 0.2)
    m.observe("gateway_ttft_seconds", 0.4)
    s = obs.sample()
    assert s.completed == 2
    assert s.ttft_mean_s == pytest.approx(0.3)
    # no new completions: the window is empty, NOT yesterday's mean
    s = obs.sample()
    assert s.completed == 0 and s.ttft_mean_s == 0.0


# ---------------------------------------------------------------------------
# 2. hysteresis / cooldown / flap damping (fake clock, scripted pressure)
# ---------------------------------------------------------------------------

def test_hysteresis_a_pressure_blip_never_scales(h):
    h.ctrl = h.make_controller(up_ticks=3, down_ticks=99)
    _scripted(h.ctrl, [_high(), _high(), _idle(), _high(), _high(),
                       _high()])
    before = h.pods()
    for _ in range(3):                 # high, high, BLIP — counter resets
        h.ctrl.tick()
        h.now += 1.0
    assert h.pods() == before
    h.ctrl.tick()                      # high x1
    h.now += 1.0
    h.ctrl.tick()                      # high x2
    h.now += 1.0
    assert h.pods() == before
    s = h.ctrl.tick()                  # high x3: NOW it scales
    assert s["action"] == "up"
    assert "asvc-0" in h.pods()


def test_cooldown_spaces_scale_ups(h):
    h.ctrl = h.make_controller(up_cooldown_s=10.0)
    _scripted(h.ctrl, [_high()])
    assert h.ctrl.tick()["action"] == "up"
    h.now += 5.0                       # inside the cooldown
    assert h.ctrl.tick()["action"] == ""
    h.now += 6.0                       # 11 s since the scale-up
    assert h.ctrl.tick()["action"] == "up"
    assert h.metrics.get("controller_scale_events_total", dir="up") == 2


def test_flap_damping_reversals_pay_double_cooldown(h):
    h.ctrl = h.make_controller(
        up_cooldown_s=5.0, down_cooldown_s=10.0, flap_window_s=100.0,
    )
    _scripted(h.ctrl, [_high(), _idle(routable=3)])
    assert h.ctrl.tick()["action"] == "up"       # t=0
    h.now += 15.0
    # 15 s > down_cooldown(10) but this is a REVERSAL inside the flap
    # window: the applicable cooldown doubles to 20 s
    assert h.ctrl.tick()["action"] == ""
    h.now += 6.0                                  # t=21 >= 20
    s = h.ctrl.tick()
    assert s["action"] == "down" and len(s["draining"]) == 1


# ---------------------------------------------------------------------------
# 3. scale-up: gang-schedule, preempt, checkpoint-and-requeue
# ---------------------------------------------------------------------------

def test_scale_up_schedules_a_real_pod_and_the_fleet_serves_on_it(h):
    pends = h.flood(40)
    s = h.ctrl.tick()
    assert s["action"] == "up"
    obj = h.stack.api.get_pod("default", "asvc-0")
    ann = obj["metadata"]["annotations"]
    assert ann[annotations.POD_SERVING_GROUP] == "decode"
    assert int(ann[annotations.POD_PRIORITY]) == SERVING_PRIO
    assert annotations.assignment_from_pod(obj) is not None
    assert (obj["spec"] or {}).get("nodeName"), "scale-up pod not bound"
    h.stack.registry.refresh()
    assert "default/asvc-0" in {
        r.key for r in h.stack.registry.routable()
    }
    # the data-plane factory brought the new replica's batcher up
    assert "default/asvc-0" in h.client.replicas()
    h.settle(pends)


def test_scale_up_preempts_batch_checkpoints_and_requeues(h):
    batch = h.fill_with_batch(priority=10)
    assert h.free_chips() == 0
    h.flood(40)
    s = h.ctrl.tick()
    assert s["action"] == "up"
    # exactly one batch pod was evicted, checkpointed, recreated PENDING
    assert len(h.checkpointed) == 1
    victim = h.checkpointed[0]
    assert victim in batch
    obj = h.stack.api.get_pod("default", victim)
    assert not (obj["spec"] or {}).get("nodeName"), "victim still bound"
    ck = json.loads(
        obj["metadata"]["annotations"][annotations.POD_REQUEUE_CHECKPOINT]
    )
    assert ck == {"preempted": True, "step": 7}
    assert annotations.assignment_from_pod(obj) is None
    assert h.metrics.get("controller_requeued_pods_total") == 1
    # nothing is pending in the write-ahead ledger once settled
    assert h.ctrl.requeue.pending() == []


def test_scale_down_drains_releases_and_requeued_batch_rebinds(h):
    """The full circle: preempted batch pod waits PENDING; a later
    drain-and-release frees its chips and the sweep re-binds it."""
    h.ctrl = h.make_controller(down_cooldown_s=50.0)
    h.fill_with_batch(priority=10)
    pends = h.flood(40)
    assert h.ctrl.tick()["action"] == "up"
    victim = h.checkpointed[0]
    h.settle(pends)
    # drought: the fleet shrinks — drain FIRST, release at grace
    _scripted(h.ctrl, [_idle(routable=3)])
    h.now += 100.0
    s = h.ctrl.tick()
    assert s["action"] == "down" and s["draining"]
    drained = s["draining"][0]
    assert h.stack.registry.get(drained).draining
    # nothing in flight on the drained replica: released NEXT tick,
    # WELL before the grace deadline (the cooldown keeps the next
    # scale-down decision out of this window)
    h.now += 0.1
    s = h.ctrl.tick()
    assert not s["draining"]
    assert h.metrics.get("controller_releases_total") == 1
    ns, _, name = drained.partition("/")
    assert name not in h.pods(), "released pod still exists"
    # the freed chips went back to batch: the victim re-bound
    obj = h.stack.api.get_pod("default", victim)
    assert (obj["spec"] or {}).get("nodeName"), "victim never re-bound"
    assert h.metrics.get(
        "controller_scale_events_total", dir="down"
    ) == 1


def test_scale_up_fails_fast_when_no_capacity_even_with_preemption(h):
    """Batch at priority >= serving is NOT preemptible: the scale-up
    must fail WITHOUT churning pod objects and block growth."""
    h.fill_with_batch(priority=SERVING_PRIO + 10)
    h.flood(40)
    before = h.pods()
    s = h.ctrl.tick()
    assert s["action"] == ""
    assert h.pods() == before, "failed scale-up churned pod objects"
    assert h.metrics.get("controller_scale_up_failed_total") == 1
    assert h.checkpointed == []
    # growth is blocked for grow_retry_s: the next over-pressure tick
    # does not retry the placement
    h.now += 1.0
    assert h.ctrl.tick()["action"] == ""
    assert h.metrics.get("controller_scale_up_failed_total") == 1


def test_no_scale_down_below_min_replicas(h):
    h.ctrl = h.make_controller(min_replicas=2)
    _scripted(h.ctrl, [_idle()])
    for _ in range(5):
        h.now += 100.0
        assert h.ctrl.tick()["action"] == ""
    assert len(h.stack.registry.routable()) == 2


# ---------------------------------------------------------------------------
# 4. crash/restart: every decision re-derivable from observed state
# ---------------------------------------------------------------------------

def test_restarted_controller_adopts_drain_and_releases_exactly_once(h):
    _scripted(h.ctrl, [_idle()])
    h.now += 100.0
    s = h.ctrl.tick()
    assert s["draining"], "drain never started"
    drained = s["draining"][0]
    # CRASH: a fresh controller over the same observed state
    ctrl2 = h.make_controller()
    assert h.metrics.get("controller_drains_resumed_total") == 1
    assert ctrl2.reshaping
    _scripted(ctrl2, [_idle()])
    h.now += 0.1
    ctrl2.tick()
    assert not ctrl2.reshaping
    assert h.metrics.get("controller_releases_total") == 1
    ns, _, name = drained.partition("/")
    with pytest.raises(Exception):
        h.stack.api.get_pod(ns, name)
    # releasing again (a second crashed-and-restarted controller, or a
    # replayed decision) is a NO-OP, never a double free
    ctrl3 = h.make_controller()
    assert not ctrl3.reshaping
    ctrl3._release(drained)
    assert h.metrics.get("controller_releases_total") == 1


def test_draining_mark_survives_process_restart(h):
    """The drain-adoption contract for REAL process death: the DRAINING
    mark is persisted on the pod annotation, so a restarted process's
    FRESH registry (empty in-memory set) adopts the in-flight drain
    instead of silently re-admitting the half-drained replica."""
    from kubegpu_tpu.gateway import ReplicaRegistry

    key = sorted(r.key for r in h.stack.registry.all())[0]
    h.stack.registry.set_draining(key, True)
    # process death: a brand-new registry over the same API server
    reg2 = ReplicaRegistry(h.stack.api, group="decode")
    reg2.refresh()
    assert key in reg2.draining_keys()
    assert key not in {r.key for r in reg2.routable()}
    # clearing the mark (drain finished) is durable too
    reg2.set_draining(key, False)
    reg3 = ReplicaRegistry(h.stack.api, group="decode")
    reg3.refresh()
    assert key not in reg3.draining_keys()
    # and a RECREATED pod under the same name starts with a clean slate
    h.stack.registry.set_draining(key, True)
    ns, _, name = key.partition("/")
    obj = h.stack.api.get_pod(ns, name)
    h.stack.api.delete_pod(ns, name)
    fresh = {
        "metadata": {
            "name": name, "namespace": ns,
            "annotations": {
                k: v for k, v in obj["metadata"]["annotations"].items()
                if k != annotations.POD_DRAINING
            },
        },
        "spec": dict(obj["spec"]),
    }
    h.stack.api.create_pod(fresh)
    reg4 = ReplicaRegistry(h.stack.api, group="decode")
    reg4.refresh()
    assert key not in reg4.draining_keys()


def test_brownout_spec_cap_applies_to_revived_replicas():
    """Rung 2 is applied on level CROSSINGS — a replica that cold-
    restarts while the fleet is browned out must come up capped too
    (the client remembers the cap and re-applies it at bring-up)."""
    client = InMemoryReplicaClient(
        batcher_factory=lambda key: SimBatcher(slots=4, speculate_k=3),
        step_delay_s=0.0,
    )
    try:
        client.add_replica("default/r0")
        assert client.set_speculation(1) == 1
        assert client._workers["default/r0"].batcher.speculate_k == 1
        # kill + revive while capped: the fresh factory batcher comes
        # up at the CONFIGURED width and must be re-capped
        client.fail_replica("default/r0")
        client.add_replica("default/r0")
        assert client._workers["default/r0"].batcher.speculate_k == 1
        # restore, then revive again: back to the configured width
        client.set_speculation(None)
        assert client._workers["default/r0"].batcher.speculate_k == 3
        client.fail_replica("default/r0")
        client.add_replica("default/r0")
        assert client._workers["default/r0"].batcher.speculate_k == 3
    finally:
        client.stop()


def test_restarted_controller_replays_unsettled_requeue_snapshot(h):
    """The crash window the write-ahead ledger closes: eviction done,
    recreation NOT — the restarted controller must finish the diff-and-
    recreate from the durable snapshot."""
    h.fill_with_batch(priority=10)
    ledger = RequeueLedger()
    snapshot = h.ctrl._preemptible_bound_pods()
    assert snapshot
    ledger.begin(snapshot)
    # the "eviction": one snapshotted pod vanishes from the API server
    victim = snapshot[0]["metadata"]["name"]
    obj = h.stack.api.get_pod("default", victim)
    h.stack.api.delete_pod("default", victim)
    h.stack.sched.on_pod_deleted(obj)
    # CRASH + restart with the same ledger: _resume replays
    ctrl2 = h.make_controller(requeue_ledger=ledger)
    back = h.stack.api.get_pod("default", victim)
    assert not (back["spec"] or {}).get("nodeName")
    assert annotations.POD_REQUEUE_CHECKPOINT in (
        back["metadata"]["annotations"]
    )
    assert ledger.pending() == [], "snapshot not settled after replay"
    assert h.checkpointed == [victim]
    # replaying again (idempotency): survivors present, nothing recreated
    ctrl3 = h.make_controller(requeue_ledger=ledger)
    assert h.checkpointed == [victim]
    assert ctrl3 is not None


def test_requeue_ledger_json_backend_survives_restart(tmp_path):
    path = str(tmp_path / "requeue.json")
    ledger = RequeueLedger(JsonFileRequeueBackend(path))
    tok = ledger.begin([{"metadata": {"name": "p", "namespace": "d"}}])
    # a NEW ledger over the same file sees the unsettled entry
    again = RequeueLedger(JsonFileRequeueBackend(path))
    assert [t for t, _ in again.pending()] == [tok]
    again.settle(tok)
    assert RequeueLedger(JsonFileRequeueBackend(path)).pending() == []
    # a corrupt/absent file reads as empty, never a crash
    with open(path, "w") as f:
        f.write("{not json")
    assert RequeueLedger(JsonFileRequeueBackend(path)).pending() == []


# ---------------------------------------------------------------------------
# 5. the brownout ladder
# ---------------------------------------------------------------------------

def test_brownout_climbs_at_max_and_steps_back_down_when_calm(h):
    h.client.add_replica("default/spec", SimBatcher(slots=8, speculate_k=2))
    h.ctrl = h.make_controller(
        max_replicas=2, brownout_threshold=2.0,
        brownout_clear_threshold=0.5, brownout_clear_ticks=2,
        brownout_step_s=5.0,
        # isolate the ladder: calm ticks must not ALSO shrink the fleet
        # (a registry change would cold-restart the side-loaded spec
        # replica's worker mid-assert)
        down_ticks=99,
    )
    _scripted(h.ctrl, [_high()] * 5 + [_idle()] * 12)
    h.ctrl.tick()
    assert h.ctrl.brownout == 1, "rung 1 must engage at max capacity"
    assert h.gw.dispatcher.hedge_disabled
    h.ctrl.tick()                      # same instant: step time gates
    assert h.ctrl.brownout == 1
    h.now += 5.0
    h.ctrl.tick()
    assert h.ctrl.brownout == 2        # speculation shrinks fleet-wide
    assert h.client._workers["default/spec"].batcher.speculate_k == 1
    h.now += 5.0
    h.ctrl.tick()
    assert h.ctrl.brownout == 3
    h.now += 5.0
    h.ctrl.tick()                      # the ladder tops out at 3
    assert h.ctrl.brownout == 3
    assert h.metrics.gauge("gateway_brownout_level") == 3
    # calm: one rung down per clear_ticks calm ticks
    h.now += 5.0
    h.ctrl.tick()
    assert h.ctrl.brownout == 3        # 1 calm tick: not yet
    h.ctrl.tick()
    assert h.ctrl.brownout == 2
    h.ctrl.tick()
    h.ctrl.tick()
    assert h.ctrl.brownout == 1
    assert h.client._workers["default/spec"].batcher.speculate_k == 2, (
        "speculation must restore below rung 2"
    )
    h.ctrl.tick()
    h.ctrl.tick()
    assert h.ctrl.brownout == 0
    assert not h.gw.dispatcher.hedge_disabled


def test_brownout_arms_when_capacity_cannot_arrive_in_time(h):
    """Under max but the cluster is full of UNpreemptible work: the
    failed scale-up blocks growth and the ladder engages."""
    h.fill_with_batch(priority=SERVING_PRIO + 10)
    h.ctrl = h.make_controller(
        max_replicas=4, brownout_threshold=2.0, brownout_step_s=0.0,
    )
    _scripted(h.ctrl, [_high()])
    h.ctrl.tick()                      # scale-up fails -> growth blocked
    assert h.metrics.get("controller_scale_up_failed_total") == 1
    assert h.ctrl.brownout >= 1
    assert h.gw.dispatcher.hedge_disabled


def test_restarted_controller_reads_brownout_back_from_the_gateway(h):
    h.gw.set_brownout(2)
    ctrl2 = h.make_controller()
    assert ctrl2.brownout == 2


def test_brownout_level3_sheds_lowest_priority_and_over_quota_tenants():
    """Admission-time shedding, counted and retryable: shed_tenants
    always; a tenant already holding its fair share of queue capacity
    sheds too, while light tenants keep flowing."""
    harness = _Harness(
        batcher=lambda key: SimBatcher(slots=8),
        queue_capacity=8, dispatchers=2,
    )
    try:
        gw, m = harness.gw, harness.metrics
        gw.set_brownout(3, shed_tenants={"free"})
        p = gw.submit(GatewayRequest(
            prompt=[1], max_new_tokens=2, request_id="f1", tenant="free",
        ))
        assert p.wait(10)
        res = p.result()
        assert res.status == "rejected" and "brownout" in res.error
        assert m.get("gateway_shed_total", reason="brownout") == 1
        # a hog at/over its fair share (capacity // active tenants = 8)
        # sheds; the light tenant flows
        harness.client.set_step_delay("default/dec-0", 0.05)
        harness.client.set_step_delay("default/dec-1", 0.05)
        hogs = [
            gw.submit(GatewayRequest(
                prompt=[1, 2], max_new_tokens=8,
                request_id=f"h{i}", tenant="hog",
            ))
            for i in range(8)
        ]
        # the dispatchers must pop a couple first: outstanding counts
        # queued + in-flight, but the light tenant below still needs
        # queue headroom to be admitted at all
        deadline = time.monotonic() + 10.0
        while gw.queue.depth() > 6 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert gw.queue.depth() <= 6, "dispatchers never picked up hogs"
        extra = gw.submit(GatewayRequest(
            prompt=[1, 2], max_new_tokens=8, request_id="h9",
            tenant="hog",
        ))
        assert extra.wait(0.5), "over-quota shed must resolve instantly"
        res = extra.result()
        assert res.status == "rejected", "over-quota hog was admitted"
        assert "brownout" in res.error
        light = gw.submit(GatewayRequest(
            prompt=[3], max_new_tokens=2, request_id="l1", tenant="lite",
        ))
        assert light.wait(30)
        assert light.result().status == "ok", light.result()
        for p in hogs:
            assert p.wait(30)
        # level 0 restores: the shed tenant flows again
        gw.set_brownout(0)
        p = gw.submit(GatewayRequest(
            prompt=[1], max_new_tokens=2, request_id="f2", tenant="free",
        ))
        assert p.wait(30)
        assert p.result().status == "ok"
    finally:
        harness.stop()


# ---------------------------------------------------------------------------
# 6. shed-before-work: expired deadlines never burn prefill
# ---------------------------------------------------------------------------

def test_dispatcher_sheds_queue_expired_requests_before_dispatch(h):
    req = GatewayRequest(
        prompt=[1, 2], max_new_tokens=4, request_id="aged",
        deadline_s=0.05,
    )
    req.enqueued_at = time.monotonic() - 1.0
    out = h.gw.dispatcher.dispatch(req, h.stack.registry.routable)
    assert out.status == "rejected"
    assert "deadline expired" in out.error and "retry" in out.error
    assert h.metrics.get(
        "gateway_shed_total", reason="deadline_expired"
    ) == 1
    # nothing was attempted: no replica decoded a token for it
    assert out.attempts == 0


def test_inmemory_replica_inbox_refuses_expired_admissions(h):
    req = types.SimpleNamespace(
        request_id="aged", prompt=[1, 2], max_new_tokens=4,
        temperature=0.0, session=None, deadline_s=0.05,
        enqueued_at=time.monotonic() - 1.0,
    )
    a = h.client.submit("default/dec-0", req)
    assert a.wait(10)
    res = a.result()
    assert not res.ok
    assert "deadline expired before admission" in res.error


def test_remaining_deadline_rides_the_wire_and_replica_refuses():
    """The HTTP replica's shed-before-work: the gateway ships the
    REMAINING deadline; an admission that is already doomed is refused
    before any prefill, counted replica-side."""
    import http.client as _http

    m = Metrics()
    srv = ReplicaServer(SimBatcher(slots=4), metrics=m,
                        step_delay_s=0.001).start()
    client = HttpReplicaClient(endpoints={"r": srv.endpoint})
    try:
        # the gateway's client ships max(0, deadline - now): an aged
        # request arrives with 0 s remaining.  Drive the wire verb
        # directly so the CLIENT's own deadline guard can't race the
        # replica's refusal — this is the replica-side contract.
        host, port = srv.address
        conn = _http.HTTPConnection(host, port, timeout=10.0)
        conn.request(
            "POST", "/v1/submit",
            json.dumps({
                "request_id": "aged", "prompt": [1, 2, 3],
                "max_new_tokens": 8, "temperature": 0.0,
                "deadline_s": 0.0,
            }),
            {"Content-Type": "application/json"},
        )
        body = conn.getresponse().read().decode()
        conn.close()
        assert "deadline expired before admission" in body, body
        assert "event: error" in body, body
        assert '"tokens"' not in body, "a doomed admission decoded"
        assert m.get("replica_http_expired_refusals_total") == 1
        # a healthy-deadline admission on the same wire still serves
        ok = types.SimpleNamespace(
            request_id="ok", prompt=[1, 2, 3], max_new_tokens=8,
            temperature=0.0, session=None, deadline_s=30.0,
            enqueued_at=time.monotonic(),
        )
        a = client.submit("r", ok)
        assert a.wait(20) and a.result().ok, a.result()
        assert len(a.result().tokens) == 8
        assert m.get("replica_http_expired_refusals_total") == 1
    finally:
        client.stop()
        srv.stop()


# ---------------------------------------------------------------------------
# 7. the self-reshaping soak lane
# ---------------------------------------------------------------------------

def test_gateway_soak_controller_lane_single_gateway():
    from kubegpu_tpu.testing.soak import GatewaySoak

    GatewaySoak(seed=1400, controller=True).run(40)


def test_gateway_soak_controller_lane_tier():
    from kubegpu_tpu.testing.soak import GatewaySoak

    GatewaySoak(seed=1401, gateways=2, controller=True).run(30)


@pytest.mark.slow
def test_gateway_soak_controller_paged_kill_schedule():
    """The acceptance schedule with REAL paged batchers: surges flood
    the queue, reconcile ticks scale the fleet up (fresh
    PagedContinuousBatchers come up cold through the factory — the
    scale-up pod's process), drain and release it on the way down —
    through replica kills, speculation, fp32 decode-page sealing and
    the migration verbs.  At quiescence ``assert_page_accounting``
    balances on EVERY replica that ever served (scale-ups included)
    and I5 + the trace oracles hold."""
    import jax
    import jax.numpy as jnp

    from kubegpu_tpu.models import TransformerLM
    from kubegpu_tpu.models.paging import PagedContinuousBatcher
    from kubegpu_tpu.testing.soak import GatewaySoak

    tiny = dict(vocab_size=61, num_layers=1, num_heads=2, hidden=16,
                max_seq=32)
    params = TransformerLM(dtype=jnp.float32, **tiny).init(
        jax.random.PRNGKey(1), jnp.ones((1, 4), jnp.int32)
    )["params"]
    soak = GatewaySoak(
        seed=1406, n_replicas=2, controller=True, multiturn=True,
        follow_prompt_cap=12, migration=True,
        batcher_factory=lambda key: PagedContinuousBatcher(
            params, slots=4, prompt_pad=12, page_size=4, pool_pages=48,
            station_slots=2, token_budget=8, dtype=jnp.float32,
            decode_page_cache="fp32",
            draft_params=params, speculate_k=2, draft_window=16,
            draft_num_layers=tiny["num_layers"],
            draft_num_heads=tiny["num_heads"],
            draft_hidden=tiny["hidden"], **tiny,
        ),
    )
    soak.run(steps=20)


def test_controller_lane_rejects_http_soak():
    from kubegpu_tpu.testing.soak import GatewaySoak

    with pytest.raises(ValueError):
        GatewaySoak(seed=1402, http=True, controller=True)
