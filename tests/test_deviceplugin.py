"""Kubelet Device Plugin API tests: real gRPC over unix sockets in a
tmpdir, fake kubelet on the other end — no cluster, no TPUs (SURVEY.md §4)."""

import threading
from concurrent import futures

import grpc
import pytest

from kubegpu_tpu.plugins import DevicePluginServer, FakeSlice
from kubegpu_tpu.plugins.deviceplugin import (
    HEALTHY,
    SVC_ALLOCATE,
    SVC_LIST_AND_WATCH,
    SVC_OPTIONS,
    SVC_PREFERRED,
    SVC_REGISTRATION,
    UNHEALTHY,
    decode_devices,
)
from kubegpu_tpu.types import RES_TPU, is_contiguous_submesh
from kubegpu_tpu.utils import protowire as pw

IDENT = lambda b: b  # noqa: E731


class FakeKubelet:
    """Registration service that records RegisterRequests."""

    def __init__(self, socket_path):
        self.requests = []
        self._event = threading.Event()

        kubelet_self = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, hcd):
                if hcd.method == SVC_REGISTRATION:
                    def register(req, ctx):
                        kubelet_self.requests.append(bytes(req))
                        kubelet_self._event.set()
                        return b""

                    return grpc.unary_unary_rpc_method_handler(
                        register, request_deserializer=IDENT, response_serializer=IDENT
                    )
                return None

        self.server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
        self.server.add_generic_rpc_handlers((Handler(),))
        self.server.add_insecure_port(f"unix://{socket_path}")
        self.server.start()

    def wait(self, timeout=5.0) -> bool:
        return self._event.wait(timeout)

    def stop(self):
        # wait for teardown: grpc unlinks its unix socket on stop, and a
        # racing successor kubelet's fresh socket must not be the one
        # deleted
        self.server.stop(0.1).wait()


@pytest.fixture()
def plugin_env(tmp_path):
    fs = FakeSlice(slice_id="s0", mesh_shape=(4, 4), host_block=(2, 2))
    host = fs.hosts()[0]
    provider = fs.provider_for(host)
    kubelet = FakeKubelet(str(tmp_path / "kubelet.sock"))
    plugin = DevicePluginServer(
        provider, socket_dir=str(tmp_path), poll_interval_s=0.1
    )
    plugin.start()
    yield fs, host, plugin, kubelet, tmp_path
    plugin.stop()
    kubelet.stop()


def plugin_channel(plugin):
    return grpc.insecure_channel(f"unix://{plugin.socket_path}")


def unary(channel, method, payload=b""):
    return channel.unary_unary(
        method, request_serializer=IDENT, response_deserializer=IDENT
    )(payload, timeout=5.0)


def test_registration_handshake(plugin_env):
    _, _, plugin, kubelet, _ = plugin_env
    plugin.register_with_kubelet()
    assert kubelet.wait()
    req = kubelet.requests[0]
    assert bytes(pw.get_field(req, 1)).decode() == "v1beta1"
    assert bytes(pw.get_field(req, 2)).decode() == plugin.endpoint
    assert bytes(pw.get_field(req, 3)).decode() == RES_TPU
    # options advertise GetPreferredAllocation
    opts = bytes(pw.get_field(req, 4))
    assert pw.get_field(opts, 2) == 1


def test_options_and_list_and_watch_inventory(plugin_env):
    fs, host, plugin, _, _ = plugin_env
    with plugin_channel(plugin) as ch:
        opts = unary(ch, SVC_OPTIONS)
        assert pw.get_field(opts, 2) == 1  # preferred-allocation available
        stream = ch.unary_stream(
            SVC_LIST_AND_WATCH, request_serializer=IDENT, response_deserializer=IDENT
        )(b"", timeout=5.0)
        first = decode_devices(next(stream))
        assert set(first) == {"0", "1", "2", "3"}  # 4 chips on this host
        assert all(h == HEALTHY for h in first.values())
        stream.cancel()


def test_list_and_watch_streams_health_transitions(plugin_env):
    fs, host, plugin, _, _ = plugin_env
    dead_coord = fs.topology.host_chips(host)[0].coords
    with plugin_channel(plugin) as ch:
        stream = ch.unary_stream(
            SVC_LIST_AND_WATCH, request_serializer=IDENT, response_deserializer=IDENT
        )(b"", timeout=10.0)
        first = decode_devices(next(stream))
        assert all(h == HEALTHY for h in first.values())
        fs.kill_chip(dead_coord)
        second = decode_devices(next(stream))  # pushed on change, no restart
        assert second["0"] == UNHEALTHY
        assert second["1"] == HEALTHY
        stream.cancel()


def test_allocate_returns_visibility_env_and_devices(plugin_env):
    _, _, plugin, _, _ = plugin_env
    # AllocateRequest{container_requests=1{devices_ids=1}}
    creq = pw.encode_string_field(1, "1") + pw.encode_string_field(1, "2")
    req = pw.encode_len_field(1, creq)
    with plugin_channel(plugin) as ch:
        resp = unary(ch, SVC_ALLOCATE, req)
    containers = pw.get_all(resp, 1)
    assert len(containers) == 1
    envs = pw.decode_string_map(pw.get_all(bytes(containers[0]), 1))
    assert envs["TPU_VISIBLE_CHIPS"] == "1,2"


def test_preferred_allocation_picks_contiguous_subset(plugin_env):
    fs, host, plugin, _, _ = plugin_env
    frag_chips = fs.topology.host_chips(host)
    coords_of = {str(c.device_index): c.coords for c in frag_chips}
    # ContainerPreferredAllocationRequest{available=1, must=2, size=3}
    creq = b"".join(pw.encode_string_field(1, d) for d in ("0", "1", "2", "3"))
    creq += pw.encode_varint((3 << 3) | 0) + pw.encode_varint(2)
    req = pw.encode_len_field(1, creq)
    with plugin_channel(plugin) as ch:
        resp = unary(ch, SVC_PREFERRED, req)
    chosen = [bytes(i).decode() for i in pw.get_all(bytes(pw.get_all(resp, 1)[0]), 1)]
    assert len(chosen) == 2
    assert is_contiguous_submesh({coords_of[d] for d in chosen}, (4, 4))


def test_allocate_unknown_device_id_fails_rpc(plugin_env):
    _, _, plugin, _, _ = plugin_env
    req = pw.encode_len_field(1, pw.encode_string_field(1, "99"))
    with plugin_channel(plugin) as ch:
        with pytest.raises(grpc.RpcError):
            unary(ch, SVC_ALLOCATE, req)


def test_reregistration_through_kubelet_restart_churn(tmp_path):
    """VERDICT r3 missing #3: the device-plugin contract's LIFECYCLE —
    kubelet restarts wipe /var/lib/kubelet/device-plugins and recreate
    kubelet.sock; a plugin that registered once silently falls out of the
    allocatable set.  serve_forever must re-serve + re-register through
    the churn, including a window where kubelet is down entirely."""
    import os
    import time

    fs = FakeSlice(slice_id="s0", mesh_shape=(4, 4), host_block=(2, 2))
    provider = fs.provider_for(fs.hosts()[0])
    kubelet_sock = str(tmp_path / "kubelet.sock")
    kubelet1 = FakeKubelet(kubelet_sock)
    plugin = DevicePluginServer(
        provider, socket_dir=str(tmp_path), poll_interval_s=0.1
    )
    plugin.start()
    stop = threading.Event()
    t = threading.Thread(
        target=plugin.serve_forever, args=(stop,),
        kwargs={"watch_interval_s": 0.1}, daemon=True,
    )
    t.start()
    try:
        assert kubelet1.wait(5.0), "initial registration never arrived"
        n1 = len(kubelet1.requests)

        # kubelet restarts: wipes the plugin dir (including OUR socket)
        # and its own socket goes away for a window
        kubelet1.stop()
        for path in (kubelet_sock, plugin.socket_path):
            if os.path.exists(path):
                os.unlink(path)
        time.sleep(0.4)  # several watch ticks with kubelet DOWN (no crash)

        kubelet2 = FakeKubelet(kubelet_sock)  # new socket, new inode
        try:
            assert kubelet2.wait(5.0), "no re-registration after restart"
            # and the plugin re-served its own socket: RPCs work again
            deadline = time.monotonic() + 5.0
            devices = None
            while time.monotonic() < deadline:
                try:
                    with plugin_channel(plugin) as ch:
                        stream = ch.unary_stream(
                            SVC_LIST_AND_WATCH,
                            request_serializer=IDENT,
                            response_deserializer=IDENT,
                        )(b"", timeout=5.0)
                        devices = decode_devices(next(stream))
                    break
                except Exception:  # noqa: BLE001 - socket mid-rebuild
                    time.sleep(0.1)
            assert devices and len(devices) == 4, devices
        finally:
            kubelet2.stop()

        # kubelet restarts AGAIN without wiping the dir (containerized
        # kubelet recreating only its own socket): inode change alone
        # must trigger re-registration
        if os.path.exists(kubelet_sock):
            os.unlink(kubelet_sock)
        kubelet3 = FakeKubelet(kubelet_sock)
        try:
            assert kubelet3.wait(5.0), (
                "no re-registration on kubelet socket inode change"
            )
        finally:
            kubelet3.stop()
        assert n1 >= 1
    finally:
        stop.set()
        t.join(timeout=5.0)
        plugin.stop()
