"""Seeded + threaded control-plane soaks over the shared Soak harness
(kubegpu_tpu/testing/soak.py); the deterministic-interleaving variant lives
in tests/test_soak_deterministic.py."""

import random

import pytest

from kubegpu_tpu.testing.soak import GatewaySoak, Soak, settle_and_check

@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_control_plane_soak(seed):
    Soak(seed).run(120)


@pytest.mark.parametrize("seed", [0, 1])
def test_gateway_soak_exactly_once_or_backpressure(seed):
    """Invariant I5 under chaos: request bursts, mid-flight replica
    kills, stragglers provoking hedges — at quiescence every admitted
    request was served exactly once or rejected with explicit
    backpressure (never hedge-duplicated, never silently dropped)."""
    GatewaySoak(seed).run(30)


@pytest.mark.parametrize("rep", [0, 1, 2])
def test_control_plane_soak_threaded(rep):
    """Concurrent chaos (SURVEY §5.2's go-test-race analog): four threads —
    two racing schedule sweeps, one pod creator/deleter, one chip
    killer/reviver firing watch-style on_node_updated — hammer one
    Scheduler; invariants are checked at quiescence.  Exercises the cache
    lock + lifecycle lock interplay the single-threaded soak cannot.

    ONE green run of the 3-rep set is the regression signal (VERDICT r3
    weak #6 — this test used to need manual re-runs): the workload is an
    OP BUDGET per thread (machine-independent, unlike the old wall-clock
    window), each rep drives a distinct churn seed, and the GIL switch
    interval is dropped 1000x so every rep explores orders of magnitude
    more interleavings than a default-settings run did.  Thread
    scheduling itself stays nondeterministic — that is the point of a
    race test — but the coverage per green run no longer depends on
    machine speed or luck-of-the-draw timing."""
    import sys
    import threading

    s = Soak(99 + rep)
    # steady workload to fight over
    for _ in range(6):
        s.op_create_gang()
    for _ in range(8):
        s.op_create_pod()
    stop = threading.Event()
    errors = []

    def guard(fn, budget):
        def run():
            try:
                for _ in range(budget):
                    if stop.is_set():
                        return
                    fn()
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))
                stop.set()
        return run

    def sweeps():
        s.op_schedule_sweep()

    rng = random.Random(7 + rep)

    def churn():
        r = rng.random()
        if r < 0.3:
            s.op_create_pod()
        elif r < 0.5:
            s.op_delete_pod()
        elif r < 0.65:
            s.op_create_gang()
        elif r < 0.8:
            s.op_recreate_member()
        elif r < 0.9:
            s.op_complete_pod()
        else:
            s.op_stale_delete_event()

    def chaos():
        if rng.random() < 0.5:
            s.op_kill_chip()
        else:
            s.op_revive_chip()
        # watch-style delivery: push the fresh node objects straight into
        # the scheduler from this thread, racing the sweeps
        for obj in s.api.list_nodes():
            s.sched.on_node_updated(obj)

    threads = [
        threading.Thread(target=guard(sweeps, 22)),
        threading.Thread(target=guard(sweeps, 22)),
        threading.Thread(target=guard(churn, 45)),
        threading.Thread(target=guard(chaos, 8)),
    ]
    prev_switch = sys.getswitchinterval()
    sys.setswitchinterval(5e-6)  # dense preemption: many orders per rep
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "soak thread wedged (deadlock?)"
    finally:
        stop.set()
        sys.setswitchinterval(prev_switch)
    assert not errors, errors

    settle_and_check(s, f"threaded soak (seed {99 + rep})")
