"""Property-based allocator tests (hypothesis): the invariants that must
hold for EVERY topology and request mix, not just the hand-picked cases in
test_grpalloc.py — the deepest version of the reference's crown-jewel
allocator coverage (SURVEY.md §4)."""

from typing import Dict

import pytest

# optional property-testing dependency: a box without it SKIPS the whole
# module cleanly instead of erroring collection (noise drowning real
# regressions in the tier-1 run)
pytest.importorskip("hypothesis")

from hypothesis import given, settings, strategies as st  # noqa: E402

from kubegpu_tpu.grpalloc import (
    build_slice_views,
    fit_gang,
    fit_gang_multislice,
    pod_fits_group_constraints,
    return_pod_resources,
    take_pod_resources,
)
from kubegpu_tpu.types import NodeInfo, SliceTopology, TpuGeneration, is_contiguous_submesh
from kubegpu_tpu.types.info import ContainerInfo, PodInfo, TpuRequest


# -- topology strategy -------------------------------------------------------

@st.composite
def topologies(draw):
    """Small v5e-style meshes with host blocks that divide them, plus an
    arbitrary set of dead chips."""
    hx = draw(st.sampled_from([1, 2]))
    hy = draw(st.sampled_from([1, 2]))
    gx = draw(st.integers(1, 3))
    gy = draw(st.integers(1, 3))
    mesh = (hx * gx, hy * gy)
    all_coords = [(x, y) for x in range(mesh[0]) for y in range(mesh[1])]
    dead = draw(st.sets(st.sampled_from(all_coords), max_size=len(all_coords) // 2))
    topo = SliceTopology.build(
        "s0", TpuGeneration.V5E, mesh, host_block=(hx, hy), unhealthy=dead
    )
    nodes = {}
    for h in topo.hosts():
        n = NodeInfo(
            name=h, slice_id="s0", generation=topo.generation,
            mesh_shape=topo.mesh_shape, wrap=topo.wrap, chips=topo.host_chips(h),
        )
        n.rebuild_capacity()
        nodes[h] = n
    return topo, nodes


def make_pod(name, chips, contiguous=True, group=None, size=1):
    return PodInfo(
        name=name,
        containers=[ContainerInfo(name="main", tpu_chips=chips)],
        require_contiguous=contiguous,
        pod_group=group,
        pod_group_size=size,
    )


# -- single-pod fit invariants -----------------------------------------------

@settings(max_examples=150, deadline=None)
@given(topologies(), st.integers(1, 6), st.booleans())
def test_fit_assignment_is_valid_and_scored(topo_nodes, chips, contiguous):
    topo, nodes = topo_nodes
    views = build_slice_views(nodes.values())
    view = views.get("s0")
    for node in nodes.values():
        r = pod_fits_group_constraints(
            node, TpuRequest.from_pod(make_pod("p", chips, contiguous)), view
        )
        if not r.fits:
            continue
        a = r.assignment
        refs = a.all_chips()
        # exactly the requested count, all on this node, no duplicates
        assert len(refs) == chips
        assert {c.host for c in refs} == {node.name}
        assert len({c.device_index for c in refs}) == chips
        # every granted chip is healthy
        healthy = {c.coords for c in node.chips if c.healthy}
        assert {c.coords for c in refs} <= healthy
        if contiguous:
            assert is_contiguous_submesh(
                {c.coords for c in refs}, topo.mesh_shape, topo.wrap
            )
        assert 0.0 <= r.score <= 100.0


@settings(max_examples=100, deadline=None)
@given(topologies(), st.integers(1, 4))
def test_take_then_return_roundtrips(topo_nodes, chips):
    _, nodes = topo_nodes
    views = build_slice_views(nodes.values())
    view = views.get("s0")
    for node in nodes.values():
        before = node.used.to_flat()
        r = pod_fits_group_constraints(
            node, TpuRequest.from_pod(make_pod("p", chips)), view
        )
        if not r.fits:
            continue
        take_pod_resources(node, r.assignment)
        # double-take of the same chips must raise and change nothing
        mid = node.used.to_flat()
        try:
            take_pod_resources(node, r.assignment)
            raise AssertionError("double-take did not raise")
        except ValueError:
            pass
        assert node.used.to_flat() == mid
        return_pod_resources(node, r.assignment)
        assert node.used.to_flat() == before
        # return is idempotent
        return_pod_resources(node, r.assignment)
        assert node.used.to_flat() == before


# -- gang invariants ----------------------------------------------------------

@settings(max_examples=100, deadline=None)
@given(topologies(), st.integers(1, 4), st.integers(1, 3))
def test_gang_never_double_allocates(topo_nodes, n_pods, chips):
    topo, nodes = topo_nodes
    views = build_slice_views(nodes.values())
    if "s0" not in views:
        return
    pods = [make_pod(f"w{i}", chips, group="g", size=n_pods) for i in range(n_pods)]
    g = fit_gang(views["s0"], pods)
    if not g.success:
        return
    assert set(g.per_pod) == {p.key for p in pods}
    seen = set()
    for a in g.per_pod.values():
        coords = {c.coords for c in a.all_chips()}
        assert len(coords) == chips
        assert not (coords & seen), "two pods share a chip"
        seen |= coords
        # per-pod host-locality + contiguity
        assert len({c.host for c in a.all_chips()}) == 1
        assert is_contiguous_submesh(coords, topo.mesh_shape, topo.wrap)
    # the union is one contiguous rectangle (the gang contract)
    assert is_contiguous_submesh(seen, topo.mesh_shape, topo.wrap)
    # nothing the gang took was dead or already used
    assert seen <= views["s0"].free


@settings(max_examples=60, deadline=None)
@given(topologies(), topologies(), st.integers(2, 4))
def test_multislice_equal_shapes_property(tn_a, tn_b, n_pods):
    _, nodes_a = tn_a
    topo_b, nodes_b = tn_b
    # second slice under a different id
    for n in nodes_b.values():
        n.slice_id = "s1"
        n.name = "b-" + n.name
        for i, ch in enumerate(n.chips):
            n.chips[i] = type(ch)(
                coords=ch.coords, chip_id=ch.chip_id, host_id=n.name,
                device_index=ch.device_index, healthy=ch.healthy,
            )
        n.rebuild_capacity()
    views = build_slice_views(list(nodes_a.values()) + list(nodes_b.values()))
    pods = [
        make_pod(f"w{i}", 1, group="g", size=n_pods) for i in range(n_pods)
    ]
    res = fit_gang_multislice(views, pods, allow_multislice=True)
    if not res.success:
        return
    if res.num_slices == 1:
        return
    # equal per-slice chip counts and identical rectangle shape
    per_slice: Dict[str, set] = {}
    for a in res.per_pod.values():
        per_slice.setdefault(a.slice_id, set()).update(
            c.coords for c in a.all_chips()
        )
    counts = {len(v) for v in per_slice.values()}
    assert len(counts) == 1
    for sid, coords in per_slice.items():
        assert is_contiguous_submesh(
            coords, views[sid].mesh_shape, views[sid].wrap
        )
