"""L3 CRI shim tests: wire codec, injection logic, full gRPC proxy path."""

from concurrent import futures

import grpc
import pytest

from kubegpu_tpu.crishim import (
    CriProxy,
    ShimDaemon,
    compute_injection,
    mutate_create_request,
    parse_create_request,
    worker_env,
)
from kubegpu_tpu.crishim.proxy import CREATE_CONTAINER
from kubegpu_tpu.plugins import FakeSlice
from kubegpu_tpu.types import annotations
from kubegpu_tpu.types.info import PodInfo
from kubegpu_tpu.utils import protowire as pw

from test_scheduler import fake_cluster, make_sched, nodes_of, pod_obj


# -- protowire --------------------------------------------------------------

def test_varint_roundtrip():
    for n in (0, 1, 127, 128, 300, 2**32, 2**60):
        data = pw.encode_varint(n)
        val, pos = pw.decode_varint(data, 0)
        assert val == n and pos == len(data)


def test_field_iteration_and_maps():
    msg = (
        pw.encode_string_field(1, "hello")
        + pw.encode_varint((2 << 3) | 0) + pw.encode_varint(42)
        + pw.encode_len_field(7, pw.encode_key_value("k1", "v1"))
        + pw.encode_len_field(7, pw.encode_key_value("k2", "v2"))
    )
    assert pw.get_field(msg, 1) == b"hello"
    assert pw.get_field(msg, 2) == 42
    assert pw.decode_string_map(pw.get_all(msg, 7)) == {"k1": "v1", "k2": "v2"}


def test_append_and_replace_preserve_unknown_fields():
    inner = pw.encode_string_field(1, "ctr")
    msg = pw.encode_len_field(1, inner) + pw.encode_string_field(99, "unknown-field")
    appended = pw.append_to_message_field(msg, 6, [pw.encode_key_value("A", "B")])
    assert pw.get_field(appended, 99) == b"unknown-field"
    envs = pw.decode_string_map(pw.get_all(appended, 6))
    assert envs == {"A": "B"}
    replaced = pw.replace_field(appended, 1, pw.encode_string_field(1, "other"))
    assert pw.get_field(pw.get_field(replaced, 1), 1) == b"other"
    assert pw.get_field(replaced, 99) == b"unknown-field"


# -- worker env contract ----------------------------------------------------

def test_worker_env_stable_across_members():
    members = ["job-w2", "job-w0", "job-w1"]
    envs = []
    for name in members:
        pod = PodInfo(name=name, namespace="ml", pod_group="job", pod_group_size=3)
        envs.append(worker_env(pod, members, subdomain="job-svc"))
    # every member derives the same worker table
    assert len({e["TPU_WORKER_HOSTNAMES"] for e in envs}) == 1
    assert len({e["JAX_COORDINATOR_ADDRESS"] for e in envs}) == 1
    assert sorted(e["TPU_WORKER_ID"] for e in envs) == ["0", "1", "2"]
    assert sorted(e["JAX_PROCESS_ID"] for e in envs) == ["0", "1", "2"]
    assert all(e["JAX_NUM_PROCESSES"] == "3" for e in envs)
    assert envs[1]["TPU_WORKER_ID"] == "0"  # job-w0 sorts first
    assert envs[1]["JAX_COORDINATOR_ADDRESS"] == "job-w0.job-svc.ml.svc:8476"


def test_worker_env_without_subdomain_uses_pod_names():
    pod = PodInfo(name="a", pod_group="g")
    env = worker_env(pod, ["a", "b"])
    assert env["TPU_WORKER_HOSTNAMES"] == "a,b"


# -- injection logic --------------------------------------------------------

def bound_tpu_pod(api, sched, name="p0", chips=2, group=None, group_size=1):
    obj = pod_obj(name, chips, group=group, group_size=group_size)
    api.create_pod(obj)
    r = sched.filter(obj, nodes_of(api))
    assert r.nodes, r.failed
    assert sched.bind("default", name, r.nodes[0]) is None
    return annotations.pod_from_k8s(api.get_pod("default", name)), r.nodes[0]


def test_compute_injection_for_scheduled_pod():
    api, fs, _ = fake_cluster()
    sched = make_sched(api)
    pod, node = bound_tpu_pod(api, sched, chips=2)
    inj = compute_injection(pod, "main", fs.provider_for(node))
    assert inj.env["TPU_VISIBLE_CHIPS"].count(",") == 1
    assert len(inj.devices) == 2
    assert inj.env["JAX_NUM_PROCESSES"] == "1"


def test_compute_injection_passthrough_for_plain_pod():
    api, fs, _ = fake_cluster()
    pod = annotations.pod_from_k8s(pod_obj("web", 0))
    inj = compute_injection(pod, "main", fs.provider_for(fs.hosts()[0]))
    assert inj.env == {} and inj.devices == []


def test_compute_injection_sidecar_gets_nothing():
    api, fs, _ = fake_cluster()
    sched = make_sched(api)
    pod, node = bound_tpu_pod(api, sched, chips=2)
    inj = compute_injection(pod, "sidecar", fs.provider_for(node))
    assert inj.env == {} and inj.devices == []


# -- CreateContainer wire surgery -------------------------------------------

def make_create_request(ns, pod_name, container, ann=None, hostname=""):
    sandbox_meta = pw.encode_string_field(1, pod_name) + pw.encode_string_field(3, ns)
    sandbox = pw.encode_len_field(1, sandbox_meta)
    if hostname:
        sandbox += pw.encode_string_field(2, hostname)
    for k, v in (ann or {}).items():
        sandbox += pw.encode_len_field(7, pw.encode_key_value(k, v))
    cmeta = pw.encode_string_field(1, container)
    config = pw.encode_len_field(1, cmeta) + pw.encode_string_field(2, "img:latest")
    config += pw.encode_len_field(6, pw.encode_key_value("EXISTING", "1"))
    return (
        pw.encode_string_field(1, "sandbox-123")
        + pw.encode_len_field(2, config)
        + pw.encode_len_field(3, sandbox)
    )


def test_parse_and_mutate_create_request():
    req = make_create_request("ml", "w0", "train", ann={"a": "b"}, hostname="w0")
    ns, pod, cname, ann, hostname = parse_create_request(req)
    assert (ns, pod, cname, hostname) == ("ml", "w0", "train", "w0")
    assert ann == {"a": "b"}
    from kubegpu_tpu.crishim.inject import Injection

    mutated = mutate_create_request(
        req, Injection(env={"TPU_VISIBLE_CHIPS": "0,1"}, devices=["/dev/accel0", "/dev/accel1"])
    )
    config = bytes(pw.get_field(mutated, 2))
    envs = pw.decode_string_map(pw.get_all(config, 6))
    assert envs == {"EXISTING": "1", "TPU_VISIBLE_CHIPS": "0,1"}
    devices = pw.get_all(config, 8)
    assert len(devices) == 2
    assert pw.get_field(bytes(devices[0]), 2) == b"/dev/accel0"
    # unrelated fields untouched
    assert pw.get_field(mutated, 1) == b"sandbox-123"
    assert pw.get_field(bytes(pw.get_field(mutated, 2)), 2) == b"img:latest"


def test_mounts_injected():
    from kubegpu_tpu.crishim.inject import Injection

    req = make_create_request("ml", "w0", "train")
    mutated = mutate_create_request(
        req, Injection(mounts=[("/var/lib/libtpu", "/usr/lib/libtpu")])
    )
    config = bytes(pw.get_field(mutated, 2))
    mounts = pw.get_all(config, 7)
    assert len(mounts) == 1
    assert pw.get_field(bytes(mounts[0]), 1) == b"/usr/lib/libtpu"
    assert pw.get_field(bytes(mounts[0]), 2) == b"/var/lib/libtpu"


# -- full gRPC proxy path ---------------------------------------------------

_IDENT = lambda b: b  # noqa: E731


class FakeCriBackend(grpc.GenericRpcHandler):
    """Upstream 'containerd': records every request, returns a canned
    CreateContainerResponse."""

    def __init__(self):
        self.requests = {}

    def service(self, hcd):
        method = hcd.method

        def handler(req, ctx):
            self.requests.setdefault(method, []).append(req)
            return pw.encode_string_field(1, "ctr-1")

        return grpc.unary_unary_rpc_method_handler(
            handler, request_deserializer=_IDENT, response_serializer=_IDENT
        )


@pytest.fixture()
def cri_stack():
    backend = FakeCriBackend()
    upstream = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    upstream.add_generic_rpc_handlers((backend,))
    up_port = upstream.add_insecure_port("127.0.0.1:0")
    upstream.start()

    api, fs, _ = fake_cluster()
    sched = make_sched(api)
    # the shim runs on a node: pick host-0's provider
    daemon = ShimDaemon(api, fs.provider_for(fs.hosts()[0]))
    proxy = CriProxy(
        upstream_target=f"127.0.0.1:{up_port}",
        decide=daemon.decide,
        listen_target="127.0.0.1:0",
    )
    proxy.start()
    channel = grpc.insecure_channel(f"127.0.0.1:{proxy.port}")
    yield api, sched, fs, backend, channel
    channel.close()
    proxy.stop(0)
    upstream.stop(0)


def _call(channel, method, payload):
    return channel.unary_unary(
        method, request_serializer=_IDENT, response_deserializer=_IDENT
    )(payload, timeout=5)


def test_proxy_passthrough_unrelated_method(cri_stack):
    api, sched, fs, backend, channel = cri_stack
    payload = pw.encode_string_field(1, "v1")
    resp = _call(channel, "/runtime.v1.RuntimeService/Version", payload)
    assert backend.requests["/runtime.v1.RuntimeService/Version"] == [payload]
    assert pw.get_field(resp, 1) == b"ctr-1"


def test_proxy_injects_for_scheduled_pod(cri_stack):
    api, sched, fs, backend, channel = cri_stack
    # schedule a pod onto host-0 specifically (the shim's node)
    host0 = fs.hosts()[0]
    obj = pod_obj("w0", 2)
    api.create_pod(obj)
    assert sched.filter(obj, [host0]).nodes == [host0]
    assert sched.bind("default", "w0", host0) is None
    stored = api.get_pod("default", "w0")
    req = make_create_request("default", "w0", "main",
                              ann=stored["metadata"]["annotations"])
    _call(channel, CREATE_CONTAINER, req)
    got = backend.requests[CREATE_CONTAINER][0]
    config = bytes(pw.get_field(got, 2))
    envs = pw.decode_string_map(pw.get_all(config, 6))
    assert envs["EXISTING"] == "1"
    assert envs["TPU_VISIBLE_CHIPS"] == "0,1"
    assert envs["JAX_NUM_PROCESSES"] == "1"
    assert len(pw.get_all(config, 8)) == 2


def test_proxy_passthrough_for_non_tpu_pod(cri_stack):
    api, sched, fs, backend, channel = cri_stack
    obj = pod_obj("web", 0)
    api.create_pod(obj)
    req = make_create_request("default", "web", "main")
    _call(channel, CREATE_CONTAINER, req)
    got = backend.requests[CREATE_CONTAINER][0]
    assert got == req  # byte-identical passthrough


def test_proxy_gang_api_outage_fails_create_not_corrupts(cri_stack):
    # regression (review finding): API down during a gang worker's
    # CreateContainer must fail the call, not inject standalone env
    api, sched, fs, backend, channel = cri_stack
    objs = [pod_obj(f"w{i}", 1, group="job", group_size=4) for i in range(4)]
    for o in objs:
        api.create_pod(o)
    for o in objs:
        name = o["metadata"]["name"]
        r = sched.filter(o, nodes_of(api))
        assert sched.bind("default", name, r.nodes[0]) is None
    stored = api.get_pod("default", "w1")
    # break list_pods only (get_pod still works): partial API failure
    def broken_list(namespace=None):
        raise OSError("api server unreachable")

    api.list_pods = broken_list
    req = make_create_request("default", "w1", "main",
                              ann=stored["metadata"]["annotations"])
    with pytest.raises(grpc.RpcError) as ei:
        _call(channel, CREATE_CONTAINER, req)
    assert ei.value.code() == grpc.StatusCode.INTERNAL
    assert "gang members" in ei.value.details()
    # the request never reached containerd
    assert CREATE_CONTAINER not in backend.requests


def test_proxy_gang_worker_env(cri_stack):
    api, sched, fs, backend, channel = cri_stack
    objs = [pod_obj(f"w{i}", 1, group="job", group_size=4) for i in range(4)]
    for o in objs:
        o["spec"]["subdomain"] = "job-svc"
        api.create_pod(o)
    for o in objs:
        name = o["metadata"]["name"]
        r = sched.filter(o, nodes_of(api))
        assert sched.bind("default", name, r.nodes[0]) is None
    # create container for w2 (whichever node it landed on; the provider is
    # host-0's but allocate only needs device indices)
    stored = api.get_pod("default", "w2")
    req = make_create_request("default", "w2", "main",
                              ann=stored["metadata"]["annotations"])
    _call(channel, CREATE_CONTAINER, req)
    got = backend.requests[CREATE_CONTAINER][-1]
    envs = pw.decode_string_map(pw.get_all(bytes(pw.get_field(got, 2)), 6))
    assert envs["TPU_WORKER_ID"] == "2"
    assert envs["JAX_NUM_PROCESSES"] == "4"
    assert envs["JAX_COORDINATOR_ADDRESS"].startswith("w0.job-svc.default.svc:")
    assert envs["TPU_WORKER_HOSTNAMES"].split(",")[2].startswith("w2.")
