"""Session KV reuse: decode-page prefix caching (ISSUE 5).

The contract under test: with ``decode_page_cache`` on, a retiring
sequence's complete pages — prompt AND generated — seal into the
content-hash chain, so a turn-2 prompt of ``turn1_prompt + turn1_output
+ new_text`` hits straight through the generated region and prefill
starts at the first genuinely new token, while staying INVISIBLE in the
output at fp32 (the policy's "fp32" promise): greedy tokens identical to
an entirely uncached batcher, across page sizes, chunk widths, page-
boundary-straddling extensions, speculation, cancels, LRU eviction, and
the GatewaySoak multi-turn kill schedule.

Numerics note (measured, not assumed): the sealed decode rows' K/V was
written by the paged decode kernel (f32 online softmax), a fresh
prefill's by the dense station (one-shot softmax).  At fp32 layer 0's
K/V is byte-identical (pure projections — any chain-hash or page-mapping
bug shows up as gross row mismatches there); layers >= 1 differ by ~1
fp32 ULP because the two softmaxes reassociate differently, which is
exactly why sharing is policy-gated per dtype.  The property test below
pins both facts plus token-identity, the invariant the acceptance
criteria gate on.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubegpu_tpu.models import TransformerLM, greedy_generate
from kubegpu_tpu.models.paging import PagedContinuousBatcher
from kubegpu_tpu.models.serving import resolve_decode_page_cache
from kubegpu_tpu.utils.metrics import Metrics

CFG = dict(vocab_size=61, num_layers=2, num_heads=4, hidden=32, max_seq=64)
DRAFT = dict(draft_num_layers=1, draft_num_heads=2, draft_hidden=16)


def trained_params():
    model = TransformerLM(dtype=jnp.float32, **CFG)
    return model.init(jax.random.PRNGKey(0), jnp.ones((2, 8), jnp.int32))[
        "params"
    ]


def oracle(params, prompt, n):
    out = greedy_generate(
        params, jnp.asarray(prompt)[None, :], n, dtype=jnp.float32, **CFG
    )
    return list(np.asarray(out)[0, len(prompt):])


def make_paged(params, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("prompt_pad", 40)
    kw.setdefault("page_size", 4)
    kw.setdefault("pool_pages", 40)
    kw.setdefault("decode_page_cache", "fp32")
    return PagedContinuousBatcher(params, dtype=jnp.float32, **CFG, **kw)


# ---------------------------------------------------------------------------
# Policy knob: resolution and validation (fast — tier-1)
# ---------------------------------------------------------------------------

def test_decode_page_cache_policy_resolution():
    assert not resolve_decode_page_cache("off", jnp.float32)
    assert resolve_decode_page_cache("fp32", jnp.float32)
    assert not resolve_decode_page_cache("fp32", jnp.bfloat16)
    assert resolve_decode_page_cache("all", jnp.bfloat16)
    assert resolve_decode_page_cache("all", jnp.float32)
    with pytest.raises(ValueError, match="decode_page_cache"):
        resolve_decode_page_cache("fp16", jnp.float32)


def test_decode_page_cache_construction_contract():
    params = trained_params()
    with pytest.raises(ValueError, match="decode_page_cache"):
        make_paged(params, decode_page_cache="sometimes")
    # "fp32" at bf16 serving precision resolves to prompt-only sealing
    bf = PagedContinuousBatcher(
        params, slots=1, prompt_pad=8, page_size=4, pool_pages=12,
        decode_page_cache="fp32", dtype=jnp.bfloat16, **CFG,
    )
    assert not bf._seal_decode
    assert make_paged(params)._seal_decode
    assert PagedContinuousBatcher(
        params, slots=1, prompt_pad=8, page_size=4, pool_pages=12,
        decode_page_cache="all", dtype=jnp.bfloat16, **CFG,
    )._seal_decode
    # sealing needs a cache to seal into
    assert not make_paged(params, prefix_cache=False)._seal_decode
    # the draft ring is a speculative-only knob
    with pytest.raises(ValueError, match="draft_window"):
        make_paged(params, draft_window=16)


def test_sim_batcher_validates_policy():
    from kubegpu_tpu.gateway.client import SimBatcher

    SimBatcher(decode_page_cache="all")  # valid values construct
    with pytest.raises(ValueError, match="decode_page_cache"):
        SimBatcher(decode_page_cache="on")


def test_policy_tuple_pinned_across_layers():
    """The gateway layer is jax-free, so it mirrors the policy tuple
    instead of importing the model stack; this pin is what keeps the
    mirror honest when a policy value is added."""
    from kubegpu_tpu.gateway import client
    from kubegpu_tpu.models import serving

    assert (
        client.DECODE_PAGE_CACHE_POLICIES
        == serving.DECODE_PAGE_CACHE_POLICIES
    )


# ---------------------------------------------------------------------------
# Tentpole property: turn 2 hits through generated pages, output-invisible
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_two_turn_decode_page_hits_token_identical():
    """Turn 2 extends turn 1's full stream; with decode-page caching its
    probe must reach past the prompt region into sealed DECODE pages
    (prefix_hit_tokens_decode > 0) and still emit exactly what a
    cache-less batcher emits — for second-turn extensions straddling the
    page boundary, and for page sizes x chunk widths."""
    params = trained_params()
    rng = np.random.RandomState(1)
    turn1 = np.array(rng.randint(0, CFG["vocab_size"], size=6), np.int32)
    for page, chunk in ((4, None), (4, 8), (8, None)):
        cb = make_paged(params, page_size=page, prefill_chunk=chunk)
        out1 = cb.run([turn1], [10])[0]
        assert out1 == oracle(params, turn1, 10)
        # stream = 16 rows, committed 15: floor(15/page) full pages
        # sealed, of which all past (6-1)//page are decode kind
        assert cb.stats["decode_pages_sealed"] > 0, (page, chunk)
        cb.assert_page_accounting()
        for extra in (1, 3, 4, 6):
            turn2 = np.concatenate([
                turn1, np.asarray(out1, np.int32),
                np.array(
                    rng.randint(0, CFG["vocab_size"], size=extra), np.int32
                ),
            ])
            expected = oracle(params, turn2, 5)
            cold = make_paged(
                params, page_size=page, prefill_chunk=chunk,
                prefix_cache=False,
            )
            assert cold.run([turn2], [5])[0] == expected
            got = cb.run([turn2], [5])[0]  # run() resets stats per call
            assert got == expected, (page, chunk, extra, got, expected)
            assert cb.stats["prefix_hit_tokens_decode"] > 0, (
                page, chunk, extra,
                "turn 2 did not reuse turn 1's generated pages",
            )
            # prompt-region hits split separately from decode-region
            assert cb.stats["prefix_hit_tokens"] == (
                cb.stats["prefix_hit_tokens_prompt"]
                + cb.stats["prefix_hit_tokens_decode"]
            )
            cb.assert_page_accounting()


@pytest.mark.slow
def test_two_turn_with_speculation_token_identical():
    """Decode-page sealing composes with speculative decode: the spec
    path's host-truncated streams (EOS / budget caps drop device-emitted
    surplus) must seal only COMMITTED rows, so a turn-2 prompt extending
    the truncated stream still matches the oracle exactly."""
    params = trained_params()
    dmodel = TransformerLM(
        vocab_size=CFG["vocab_size"], max_seq=CFG["max_seq"],
        num_layers=DRAFT["draft_num_layers"],
        num_heads=DRAFT["draft_num_heads"], hidden=DRAFT["draft_hidden"],
        dtype=jnp.float32,
    )
    dparams = dmodel.init(
        jax.random.PRNGKey(7), jnp.ones((2, 8), jnp.int32)
    )["params"]
    rng = np.random.RandomState(2)
    turn1 = np.array(rng.randint(0, CFG["vocab_size"], size=7), np.int32)
    for eos in (None, 7):
        cb = make_paged(
            params, slots=4, prompt_pad=20, draft_params=dparams,
            speculate_k=3, eos_id=eos, **DRAFT,
        )
        out1 = cb.run([turn1], [9])[0]
        plain = make_paged(params, slots=4, prompt_pad=20, eos_id=eos)
        assert plain.run([turn1], [9])[0] == out1
        turn2 = np.concatenate([
            turn1, np.asarray(out1, np.int32), np.array([3, 11], np.int32),
        ])
        cold = make_paged(
            params, slots=4, prompt_pad=20, prefix_cache=False, eos_id=eos,
        )
        expected = cold.run([turn2], [6])[0]
        got = cb.run([turn2], [6])[0]
        assert got == expected, (eos, got, expected)
        if len(out1) >= cb.page:  # enough committed rows to seal past
            assert cb.stats["prefix_hit_tokens_decode"] > 0, eos
        cb.assert_page_accounting()


# ---------------------------------------------------------------------------
# Satellite: cache-chain hashing across page boundaries — gathered K/V
# ---------------------------------------------------------------------------

def _kv_rows(cb, slot, nrows):
    """Read rows [0, nrows) of each layer's K/V through the slot's page
    table (the exact gather a chunk or decode step attends)."""
    table = cb.tables[slot]
    page = cb.page
    out = []
    for kp, vp in cb.pools:
        kp, vp = np.asarray(kp), np.asarray(vp)
        n_pages = -(-nrows // page)
        k = np.concatenate(
            [np.moveaxis(kp[table[j]], 0, 1) for j in range(n_pages)]
        )[:nrows]
        v = np.concatenate(
            [np.moveaxis(vp[table[j]], 0, 1) for j in range(n_pages)]
        )[:nrows]
        out.append((k, v))
    return out


def _prefill_and_capture(cb, prompt):
    """Submit, drive to activation (prompt rows [0, plen-1) resident),
    capture the gathered K/V, then drain."""
    cb.submit(0, prompt, 2)
    for _ in range(200):
        if cb._seqs[0].active:
            break
        cb.serve_step()
    assert cb._seqs[0].active
    kv = _kv_rows(cb, 0, len(prompt) - 1)
    while cb.has_work():
        cb.serve_step()
    return kv


@pytest.mark.slow
def test_chain_hash_across_page_boundaries_gathered_kv():
    """A turn-2 prompt hitting through generated pages gathers K/V that
    matches a fresh prefill's at fp32: byte-identical at layer 0 (K/V
    there is a pure projection of the token+position embedding — a wrong
    page or wrong row from a chain-hash bug is a GROSS mismatch, not an
    ULP), and within ~1 fp32 ULP at deeper layers (the paged decode
    kernel's online softmax vs the dense station's one-shot softmax
    reassociate differently — the measured kernel-path class the dtype
    policy exists for).  Across page sizes and chunk widths."""
    params = trained_params()
    rng = np.random.RandomState(3)
    turn1 = np.array(rng.randint(0, CFG["vocab_size"], size=6), np.int32)
    for page, chunk in ((4, None), (4, 8), (8, None)):
        cb = make_paged(params, page_size=page, prefill_chunk=chunk)
        out1 = cb.run([turn1], [10])[0]
        turn2 = np.concatenate([
            turn1, np.asarray(out1, np.int32), np.array([5, 2], np.int32),
        ])
        kv_hit = _prefill_and_capture(cb, turn2)
        assert cb.stats["prefix_hit_tokens_decode"] > 0, (page, chunk)
        cold = make_paged(
            params, page_size=page, prefill_chunk=chunk, prefix_cache=False,
        )
        kv_fresh = _prefill_and_capture(cold, turn2)
        for li, ((hk, hv), (fk, fv)) in enumerate(zip(kv_hit, kv_fresh)):
            if li == 0:
                assert np.array_equal(hk, fk) and np.array_equal(hv, fv), (
                    page, chunk, "layer-0 K/V not byte-identical: chain "
                    "key mapped to wrong page content",
                )
            np.testing.assert_allclose(
                hk, fk, atol=1e-5, rtol=0,
                err_msg=f"layer {li} K drift beyond the fp32 ULP class",
            )
            np.testing.assert_allclose(
                hv, fv, atol=1e-5, rtol=0,
                err_msg=f"layer {li} V drift beyond the fp32 ULP class",
            )
        cb.assert_page_accounting()
        cold.assert_page_accounting()


# ---------------------------------------------------------------------------
# Satellite: cancel releases sealed/acquired decode pages
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_cancel_midturn_releases_sealed_and_acquired_pages():
    """Three cancel shapes against the decode-page refcounts: a turn-2
    session cancelled MID-DECODE (holding acquired decode pages), one
    cancelled MID-PREFILL right after its hit pages gathered, and a
    turn-1 cancelled AFTER COMMIT (sealing its own pages on the way
    out).  Every page must end free or cached-idle, refcounts zero."""
    params = trained_params()
    rng = np.random.RandomState(4)
    turn1 = np.array(rng.randint(0, CFG["vocab_size"], size=6), np.int32)
    cb = make_paged(params, slots=3)
    out1 = cb.run([turn1], [10])[0]
    sealed = cb.stats["decode_pages_sealed"]
    assert sealed > 0
    turn2 = np.concatenate([
        turn1, np.asarray(out1, np.int32), np.array([9, 1, 4], np.int32),
    ])
    # (a) cancel mid-decode: acquired decode pages must decref
    cb.submit(50, turn2, 8)
    for _ in range(50):
        cb.serve_step()
        if cb._seqs[0].active and len(cb._seqs[0].tokens) >= 2:
            break
    assert cb.stats["prefix_hit_tokens_decode"] > 0
    assert cb.cancel(50)
    cb.assert_page_accounting()
    assert all(
        cb.prefix_cache.refcount(p) == 0 for p in cb.prefix_cache.pages()
    )
    # (b) cancel mid-prefill after the hit gather
    cb.submit(51, turn2, 8)
    cb.serve_step()
    if not cb._seqs[0].active:  # still prefilling the tail
        assert cb.cancel(51)
    else:
        cb.cancel(51)
    cb.assert_page_accounting()
    assert all(
        cb.prefix_cache.refcount(p) == 0 for p in cb.prefix_cache.pages()
    )
    # (c) cancel-after-commit SEALS: a fresh stream cancelled mid-decode
    # registers its complete pages, then releases them to idle
    fresh = np.array(rng.randint(0, CFG["vocab_size"], size=5), np.int32)
    cb.submit(52, fresh, 12)
    for _ in range(60):
        cb.serve_step()
        s = next(q for q in cb._seqs if q.seq_id == 52)
        if s.active and len(s.tokens) >= 8:
            break
    before = len(cb.prefix_cache)
    assert cb.cancel(52)
    assert len(cb.prefix_cache) > before, "cancel-after-commit sealed nothing"
    cb.assert_page_accounting()
    # the sealed chain is genuinely reusable: extend the cancelled
    # stream's committed tokens (greedy, so the oracle reproduces them)
    replay = oracle(params, fresh, 8)
    turn2c = np.concatenate(
        [fresh, np.asarray(replay, np.int32), np.array([2], np.int32)]
    )
    expected = oracle(params, turn2c, 4)
    got = cb.run([turn2c], [4])[0]
    assert got == expected
    assert cb.stats["prefix_hit_tokens_decode"] > 0
    cb.assert_page_accounting()


@pytest.mark.slow
def test_lru_eviction_of_sealed_decode_pages_recomputes():
    """Pool pressure evicts idle sealed decode pages LRU like any other
    cache entry; a turn-2 whose sealed region was evicted recomputes it
    and still matches the oracle."""
    params = trained_params()
    rng = np.random.RandomState(5)
    turn1 = np.array(rng.randint(0, CFG["vocab_size"], size=6), np.int32)
    # room for ~one live request + a couple of cached pages
    cb = make_paged(params, slots=1, pool_pages=9)
    out1 = cb.run([turn1], [10])[0]
    # churn unrelated prompts through the tiny pool to evict the chain
    for j in range(3):
        other = np.array(
            rng.randint(0, CFG["vocab_size"], size=9), np.int32
        )
        cb.run([other], [6])
        cb.assert_page_accounting()
    turn2 = np.concatenate([
        turn1, np.asarray(out1, np.int32), np.array([8], np.int32),
    ])
    expected = oracle(params, turn2, 4)
    assert cb.run([turn2], [4])[0] == expected
    cb.assert_page_accounting()


# ---------------------------------------------------------------------------
# Satellite: multi-turn compile stability — one entry per program
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_multiturn_compile_stability_fixed_jit_cache():
    """A 40-step multi-turn schedule — turn-2/-3 submissions extending
    completed streams, fresh admits, cancels mid-flight, zero-budget
    admits, decode-page hits and misses — must leave exactly ONE
    compiled entry per program: sealing is host-side accounting and
    hits ride the existing gather program, so session KV reuse mints no
    new shapes."""
    params = trained_params()
    rng = np.random.RandomState(6)
    cb = make_paged(params, slots=3, station_slots=2, token_budget=9,
                    pool_pages=48)
    seq = 0
    live = []
    done_streams = []  # (prompt, tokens) of completed requests
    submitted = {}
    for _ in range(40):
        roll = rng.rand()
        if roll < 0.35:
            n = int(rng.randint(1, 12))
            prompt = np.array(
                rng.randint(0, CFG["vocab_size"], size=n), np.int32
            )
            cb.submit(seq, prompt, int(rng.randint(0, 6)))
            submitted[seq] = prompt
            live.append(seq)
            seq += 1
        elif roll < 0.55 and done_streams:
            # a session's next turn: extend a completed stream
            prompt, tokens = done_streams[
                rng.randint(len(done_streams))
            ]
            follow = np.concatenate([
                prompt, np.asarray(tokens, np.int32),
                np.array([int(rng.randint(0, CFG["vocab_size"]))],
                         np.int32),
            ])[: cb.prompt_pad]
            cb.submit(seq, follow, int(rng.randint(1, 5)))
            submitted[seq] = follow
            live.append(seq)
            seq += 1
        elif roll < 0.65 and live:
            cb.cancel(live.pop(rng.randint(len(live))))
        else:
            for s, toks in cb.serve_step().items():
                live.remove(s)
                done_streams.append((submitted[s], toks))
    while cb.has_work():
        for s, toks in cb.serve_step().items():
            live.remove(s)
            done_streams.append((submitted[s], toks))
    cb.assert_page_accounting()
    assert cb.stats["prefix_hit_tokens_decode"] > 0, (
        "schedule never exercised a decode-page hit"
    )
    for name in ("_chunk", "_step"):
        assert getattr(cb, name)._cache_size() == 1, (
            f"{name}: {getattr(cb, name)._cache_size()} compiled entries"
        )
    # bucketed multi-page programs: one compiled entry per padded width
    assert cb._write_pages, "no multi-page scatter ran"
    for w, fn in cb._write_pages.items():
        assert fn._cache_size() == 1, f"scatter width {w} recompiled"
    for w, fn in cb._gather_pages.items():
        assert fn._cache_size() == 1, f"gather width {w} recompiled"


# ---------------------------------------------------------------------------
# Satellite: hit metrics split prompt-page vs decode-page
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_prefix_hit_metrics_split_by_kind():
    """``serve_prefix_hit_tokens_total`` is split by hit-page kind —
    labeled series ONLY, so summing the family yields the true total
    (an unlabeled sibling would double-count); sealing feeds
    ``serve_decode_pages_sealed_total``."""
    params = trained_params()
    rng = np.random.RandomState(7)
    turn1 = np.array(rng.randint(0, CFG["vocab_size"], size=6), np.int32)
    m = Metrics()
    cb = make_paged(params, metrics=m)
    out1 = cb.run([turn1], [10])[0]
    turn2 = np.concatenate([
        turn1, np.asarray(out1, np.int32), np.array([3], np.int32),
    ])
    cb.run([turn2], [4])
    prompt_hits = m.get("serve_prefix_hit_tokens_total", kind="prompt")
    decode_hits = m.get("serve_prefix_hit_tokens_total", kind="decode")
    assert decode_hits > 0
    assert prompt_hits > 0
    assert m.get("serve_prefix_hit_tokens_total") == 0  # no unlabeled twin
    assert prompt_hits + decode_hits == cb.stats["prefix_hit_tokens"]
    assert m.get("serve_decode_pages_sealed_total") > 0
    text = m.render()
    assert 'serve_prefix_hit_tokens_total{kind="decode"}' in text
    assert 'serve_prefix_hit_tokens_total{kind="prompt"}' in text
    assert "serve_decode_pages_sealed_total" in text
    cb.assert_page_accounting()


# ---------------------------------------------------------------------------
# Acceptance: multi-turn GatewaySoak kill schedule, caching + speculation
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_gateway_soak_multiturn_kill_schedule():
    """The GatewaySoak kill/revive/hedge schedule extended with the
    multi-turn session op, over REAL paged batchers with decode-page
    caching AND speculation on (plus a wrap-forcing draft ring):
    invariant I5, and page accounting — refcounts, LRU, COW tails — on
    every surviving replica at quiescence.  This is the acceptance
    schedule: sessions cancelled mid-turn by kills and hedge losers must
    release every sealed decode page they registered or acquired."""
    from kubegpu_tpu.testing.soak import GatewaySoak

    tiny = dict(vocab_size=61, num_layers=1, num_heads=2, hidden=16,
                max_seq=32)
    params = TransformerLM(dtype=jnp.float32, **tiny).init(
        jax.random.PRNGKey(1), jnp.ones((1, 4), jnp.int32)
    )["params"]
    soak = GatewaySoak(
        seed=29, n_replicas=2, multiturn=True, follow_prompt_cap=12,
        batcher_factory=lambda key: PagedContinuousBatcher(
            params, slots=4, prompt_pad=12, page_size=4, pool_pages=48,
            station_slots=2, token_budget=8, dtype=jnp.float32,
            decode_page_cache="fp32",
            draft_params=params, speculate_k=2, draft_window=16,
            draft_num_layers=tiny["num_layers"],
            draft_num_heads=tiny["num_heads"],
            draft_hidden=tiny["hidden"], **tiny,
        ),
    )
    soak.run(steps=20)
