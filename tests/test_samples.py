"""The sample specs (samples/*.yaml) are live, scheduled artifacts — each
BASELINE graded config's YAML is parsed and driven through the real control
plane (advertiser → filter → prioritize → bind → CRI injection) on a
fabricated v5e-16, mirroring SURVEY.md §3.4.  A drifting sample (bad
annotation key, wrong resource name) fails here, not on a cluster."""

import pathlib

import pytest
import yaml

from kubegpu_tpu.crishim import ShimDaemon
from kubegpu_tpu.plugins import Advertiser, FakeSlice
from kubegpu_tpu.scheduler import Scheduler
from kubegpu_tpu.types import RES_TPU, annotations, is_contiguous_submesh
from kubegpu_tpu.utils import InMemoryApiServer

SAMPLES = pathlib.Path(__file__).resolve().parent.parent / "samples"
MESH = (4, 4)  # v5e-16


def load_pods(name):
    docs = list(yaml.safe_load_all((SAMPLES / name).read_text()))
    pods = [d for d in docs if d and d.get("kind") == "Pod"]
    assert pods, f"{name} contains no Pod documents"
    return pods


def make_cluster():
    api = InMemoryApiServer()
    fs = FakeSlice(slice_id="v5e-16", mesh_shape=MESH, host_block=(2, 2))
    providers = fs.providers()
    for prov in providers.values():
        Advertiser(prov, api).advertise_once()
    sched = Scheduler(api)
    sched.cache.refresh()
    return api, sched, providers


def schedule_all(api, sched, pods):
    """kube-scheduler's per-pod flow over the whole manifest."""
    for obj in pods:
        api.create_pod(obj)
    nodes = sorted(n["metadata"]["name"] for n in api.list_nodes())
    out = {}
    for obj in pods:
        name = obj["metadata"]["name"]
        r = sched.filter(obj, nodes)
        assert r.nodes, f"{name}: no feasible node ({r.failed})"
        scores = dict(sched.prioritize(obj, r.nodes))
        target = max(r.nodes, key=lambda n: (scores.get(n, 0), n))
        err = sched.bind("default", name, target)
        assert not err, f"{name}: bind failed: {err}"
        out[name] = annotations.assignment_from_pod(api.get_pod("default", name))
    return out


def sample_files():
    return sorted(p.name for p in SAMPLES.glob("*.yaml"))


def test_sample_dir_covers_all_graded_configs():
    assert sample_files() == [
        "cpu-pod.yaml",
        "four-chip.yaml",
        "jax-decode.yaml",
        "jax-lm-cp.yaml",
        "jax-lm-tp.yaml",
        "jax-multislice.yaml",
        "jax-resnet.yaml",
        "jax-serve-gateway.yaml",
        "multi-tenant.yaml",
        "single-chip.yaml",
    ]


@pytest.mark.parametrize("name", ["cpu-pod.yaml", "single-chip.yaml", "four-chip.yaml"])
def test_sample_yaml_is_well_formed(name):
    for pod in load_pods(name):
        info = annotations.pod_from_k8s(pod)
        assert info.name and info.namespace == "default"


def test_cpu_pod_is_pure_passthrough():
    api, sched, providers = make_cluster()
    pods = load_pods("cpu-pod.yaml")
    assigned = schedule_all(api, sched, pods)
    assert assigned["cpu-passthrough"] is None  # no assignment annotation
    prov = next(iter(providers.values()))
    daemon = ShimDaemon(api, prov)
    pod = api.get_pod("default", "cpu-passthrough")
    inj = daemon.decide("default", "cpu-passthrough", "main",
                        pod["metadata"].get("annotations") or {}, "h0")
    assert inj is None or inj.empty


def test_single_chip_sample_injects_one_chip():
    api, sched, _ = make_cluster()
    assigned = schedule_all(api, sched, load_pods("single-chip.yaml"))
    a = assigned["single-chip"]
    assert a is not None and len(a.all_chips()) == 1


def test_four_chip_sample_lands_contiguous():
    api, sched, _ = make_cluster()
    assigned = schedule_all(api, sched, load_pods("four-chip.yaml"))
    chips = assigned["four-chip-contiguous"].all_chips()
    assert len(chips) == 4
    assert is_contiguous_submesh({c.coords for c in chips}, MESH)


def test_jax_resnet_sample_gang_schedules_contiguously():
    api, sched, providers = make_cluster()
    pods = load_pods("jax-resnet.yaml")
    assert len(pods) == 4
    assigned = schedule_all(api, sched, pods)
    union = set()
    for name, a in assigned.items():
        assert a is not None, f"{name} unassigned"
        chips = a.all_chips()
        assert len(chips) == 1
        union.update(c.coords for c in chips)
    assert len(union) == 4
    assert is_contiguous_submesh(union, MESH)

    # CRI injection: every worker gets visibility + the same rendezvous table
    tables = set()
    for name, a in assigned.items():
        node = a.node
        prov = providers[node]
        daemon = ShimDaemon(api, prov)
        pod = api.get_pod("default", name)
        inj = daemon.decide("default", name, "worker",
                            pod["metadata"].get("annotations") or {}, node)
        assert inj is not None and not inj.empty
        assert "TPU_VISIBLE_CHIPS" in inj.env
        assert inj.env["JAX_NUM_PROCESSES"] == "4"
        assert inj.env["JAX_PROCESS_ID"] == inj.env["TPU_WORKER_ID"]
        tables.add(inj.env["TPU_WORKER_HOSTNAMES"])
        # headless-service DNS names from the manifest's subdomain
        assert ".jax-resnet.default.svc" in inj.env["JAX_COORDINATOR_ADDRESS"]
    assert len(tables) == 1  # every member derived the identical worker table


@pytest.mark.parametrize(
    "fname,gang,expect_flag",
    [
        ("jax-lm-tp.yaml", "jax-lm-tp", "lm"),
        ("jax-lm-cp.yaml", "jax-lm-cp", "lm-cp"),
    ],
)
def test_lm_sample_gang_schedules_with_worker_mode(fname, gang, expect_flag):
    """The non-ResNet workload samples (SURVEY §2.2 TP/SP + CP): the gang
    lands ICI-contiguous and the manifest launches the matching worker
    mode."""
    api, sched, providers = make_cluster()
    pods = load_pods(fname)
    assert len(pods) == 4
    # the pod command actually selects the right workload family
    for obj in pods:
        cmd = obj["spec"]["containers"][0]["command"]
        assert cmd[cmd.index("--model") + 1] == expect_flag, cmd
    assigned = schedule_all(api, sched, pods)
    union = set()
    for name, a in assigned.items():
        assert a is not None, f"{name} unassigned"
        union.update(c.coords for c in a.all_chips())
    assert len(union) == 4
    assert is_contiguous_submesh(union, MESH)
    # injection: the same gang env contract the worker's mesh bringing-up
    # consumes (jax.distributed + per-mode axis split over 4 processes)
    name, a = sorted(assigned.items())[0]
    daemon = ShimDaemon(api, providers[a.node])
    pod = api.get_pod("default", name)
    inj = daemon.decide("default", name, "worker",
                        pod["metadata"].get("annotations") or {}, a.node)
    assert inj.env["JAX_NUM_PROCESSES"] == "4"
    assert f".{gang}.default.svc" in inj.env["JAX_COORDINATOR_ADDRESS"]


def test_jax_decode_sample_schedules_and_maps_to_worker_serve_mode():
    """The serving replica spec: schedules on one chip through the real
    control plane, and its command is the worker's decode --serve mode
    with a request that fits its own cache size."""
    api, sched, _ = make_cluster()
    pods = load_pods("jax-decode.yaml")
    assert len(pods) == 1
    assigned = schedule_all(api, sched, pods)
    a = assigned["jax-decode"]
    assert a is not None and len(a.all_chips()) == 1
    cmd = pods[0]["spec"]["containers"][0]["command"]
    assert "--model=decode" in cmd and "--serve" in cmd
    flags = dict(
        f.removeprefix("--").split("=", 1) for f in cmd if "=" in f
    )
    # prompt + steps must fit the cache (seq+1) or the worker exits
    assert int(flags["prompt-len"]) + int(flags["steps"]) <= int(flags["seq"]) + 1
    assert pods[0]["spec"]["restartPolicy"] == "Always"  # serving replica


def test_jax_serve_gateway_sample_schedules_gang_and_registers():
    """The serving-path sample: the 3-replica decode gang lands
    ICI-contiguous through the real control plane, the gateway Deployment
    references a real module, and the gateway's ReplicaRegistry discovers
    exactly the bound replicas from their annotations."""
    import importlib

    from kubegpu_tpu.gateway import ReplicaRegistry

    api, sched, _ = make_cluster()
    docs = list(yaml.safe_load_all(
        (SAMPLES / "jax-serve-gateway.yaml").read_text()
    ))
    pods = [d for d in docs if d and d.get("kind") == "Pod"]
    assert len(pods) == 3
    # the gang is a real gang (atomic capacity) AND a serving group
    for obj in pods:
        ann = obj["metadata"]["annotations"]
        assert ann["kubegpu-tpu/serving-group"] == "decode"
        assert ann["kubegpu-tpu/pod-group"] == "decode-replicas"
    assigned = schedule_all(api, sched, pods)
    union = set()
    for name, a in assigned.items():
        assert a is not None and len(a.all_chips()) == 1
        union.update(c.coords for c in a.all_chips())
    assert len(union) == 3
    assert is_contiguous_submesh(union, MESH)

    registry = ReplicaRegistry(api, group="decode")
    registry.refresh()
    assert [r.pod for r in registry.live()] == [
        "decode-replica-0", "decode-replica-1", "decode-replica-2"
    ]

    # the DATA-PLANE contract: every replica serves the HTTP endpoint
    # (--serve-http) on the port the gateway dispatches to
    # (--replica-port), and its readiness probe hits the same /healthz
    # the gateway registry probes
    replica_ports = set()
    for obj in pods:
        c = obj["spec"]["containers"][0]
        cmd = c["command"]
        assert "--serving=paged" in cmd, cmd
        flags = dict(
            f.removeprefix("--").split("=", 1) for f in cmd if "=" in f
        )
        port = int(flags["serve-http"])
        replica_ports.add(port)
        assert port in [p["containerPort"] for p in c["ports"]]
        probe = c["readinessProbe"]["httpGet"]
        assert probe["path"] == "/healthz" and int(probe["port"]) == port
        # the paged replica's cache geometry must fit its traffic
        assert (int(flags["prompt-len"]) + int(flags["steps"])
                <= int(flags["seq"]) + 1)

    # the gateway Deployment's entrypoint is a real module with a main()
    deployments = [d for d in docs if d and d.get("kind") == "Deployment"]
    assert len(deployments) == 1
    gw_container = deployments[0]["spec"]["template"]["spec"]["containers"][0]
    cmd = gw_container["command"]
    assert cmd[:2] == ["python", "-m"]
    mod = importlib.import_module(cmd[2])
    assert hasattr(mod, "main")
    gw_flags = dict(
        f.removeprefix("--").split("=", 1) for f in cmd if "=" in f
    )
    assert replica_ports == {int(gw_flags["replica-port"])}
    # /readyz gates Service membership on live HTTP replica health
    assert (gw_container["readinessProbe"]["httpGet"]["path"]
            == "/readyz")


def test_multi_tenant_sample_both_gangs_fit():
    api, sched, _ = make_cluster()
    pods = load_pods("multi-tenant.yaml")
    assert len(pods) == 4
    assigned = schedule_all(api, sched, pods)
    per_gang = {}
    for obj in pods:
        name = obj["metadata"]["name"]
        gang = obj["metadata"]["annotations"]["kubegpu-tpu/pod-group"]
        per_gang.setdefault(gang, set()).update(
            c.coords for c in assigned[name].all_chips()
        )
    assert set(per_gang) == {"tenant-a", "tenant-b"}
    for gang, coords in per_gang.items():
        assert len(coords) == 8, f"{gang} got {len(coords)} chips"
        assert is_contiguous_submesh(coords, MESH), f"{gang} not contiguous"
    assert not (per_gang["tenant-a"] & per_gang["tenant-b"])


def test_jax_multislice_sample_spans_two_slices_with_megascale_env():
    # two v5e-16 slices: the 32-chip gang cannot fit either alone
    api = InMemoryApiServer()
    slices = {
        sid: FakeSlice(slice_id=sid, mesh_shape=MESH, host_block=(2, 2))
        for sid in ("v5e-16-a", "v5e-16-b")
    }
    providers = {}
    for fs in slices.values():
        for h, p in fs.providers().items():
            providers[h] = p
            Advertiser(p, api).advertise_once()
    sched = Scheduler(api)
    sched.cache.refresh()
    pods = load_pods("jax-multislice.yaml")
    assert len(pods) == 8
    assigned = schedule_all(api, sched, pods)
    per_slice = {}
    for name, a in assigned.items():
        assert a is not None and len(a.all_chips()) == 4
        assert is_contiguous_submesh({c.coords for c in a.all_chips()}, MESH)
        per_slice.setdefault(a.slice_id, set()).update(
            c.coords for c in a.all_chips()
        )
    assert set(per_slice) == {"v5e-16-a", "v5e-16-b"}
    for coords in per_slice.values():
        assert len(coords) == 16 and is_contiguous_submesh(coords, MESH)

    # megascale env on top of the usual rendezvous table
    name, a = sorted(assigned.items())[0]
    daemon = ShimDaemon(api, providers[a.node])
    pod = api.get_pod("default", name)
    inj = daemon.decide("default", name, "worker",
                        pod["metadata"].get("annotations") or {}, a.node)
    assert inj.env["MEGASCALE_NUM_SLICES"] == "2"
    assert inj.env["JAX_NUM_PROCESSES"] == "8"
    assert ".jax-ms.default.svc:8081" in inj.env["MEGASCALE_COORDINATOR_ADDRESS"]


def test_deploy_manifests_parse_and_reference_real_modules():
    deploy = SAMPLES.parent / "deploy"
    import importlib
    import json

    policy = json.loads((deploy / "extender-policy.json").read_text())
    assert policy["extenders"][0]["managedResources"][0]["name"] == RES_TPU
    for f in deploy.glob("*.yaml"):
        docs = [d for d in yaml.safe_load_all(f.read_text()) if d]
        assert docs, f"{f.name} empty"
        for d in docs:
            for c in (
                d.get("spec", {})
                .get("template", {})
                .get("spec", {})
                .get("containers", [])
            ):
                cmd = c.get("command") or []
                if len(cmd) >= 3 and cmd[:2] == ["python", "-m"]:
                    mod = importlib.import_module(cmd[2])
                    assert hasattr(mod, "main"), f"{f.name}: {cmd[2]} has no main()"
