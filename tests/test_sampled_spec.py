"""Lossless speculative sampling + seed-pinned determinism (ISSUE 19).

Layers under test:

1. the rejection-sample kernel — the committed marginal at every block
   position is EXACTLY the target softmax (chi-square), through both
   the accept path and the residual-resample path, and the rejected
   token never reappears from the residual;
2. seed-pinned dense decoding — the (seed, absolute position) key
   schedule makes a sampled stream invariant to slot assignment, batch
   composition, slot count, prefill chunking, and process restart,
   while unpinned requests keep the legacy byte-identical behavior;
3. the sampled speculative batcher — greedy rows ride the same step
   untouched, pinned sampled rows replay deterministically, top_k=1
   provably degenerates to greedy, and hedged duplicate execution
   (two independent engines) emits identical streams;
4. the gateway consequence — a seed-pinned SAMPLED stream survives a
   gateway kill mid-stream through the sibling's watermark resume with
   every token delivered exactly once, and a straggling primary's
   sampled hedge is issued and counted;
5. the bf16 tie-flip class — the standing spec_lossless_b8=false /
   spec_serving_match_dense=false bench flags are pinned to near-tie
   argmax flips (tiny top1-top2 margin at the first divergence), never
   a wide-margin bookkeeping bug.
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubegpu_tpu.models import TransformerLM
from kubegpu_tpu.models.decoding import (
    KEY_TAG_ACCEPT,
    KEY_TAG_SAMPLE,
    block_keys,
)
from kubegpu_tpu.models.serving import ContinuousBatcher
from kubegpu_tpu.models.spec_serving import SpeculativeContinuousBatcher
from kubegpu_tpu.models.speculative import rejection_sample_block
from kubegpu_tpu.utils.metrics import Metrics

CFG = dict(vocab_size=61, num_layers=2, num_heads=4, hidden=32, max_seq=64)


def trained_params():
    model = TransformerLM(dtype=jnp.float32, **CFG)
    return model.init(jax.random.PRNGKey(0), jnp.ones((2, 8), jnp.int32))[
        "params"
    ]


def draft_params():
    model = TransformerLM(
        vocab_size=CFG["vocab_size"], num_layers=1, num_heads=2, hidden=16,
        max_seq=CFG["max_seq"], dtype=jnp.float32,
    )
    return model.init(jax.random.PRNGKey(3), jnp.ones((2, 8), jnp.int32))[
        "params"
    ]


def _chi_square(counts: np.ndarray, probs: np.ndarray) -> float:
    n = counts.sum()
    expected = probs * n
    mask = expected > 0
    return float(
        ((counts[mask] - expected[mask]) ** 2 / expected[mask]).sum()
    )


# ---------------------------------------------------------------------------
# 1. the rejection-sample kernel: exact target marginals
# ---------------------------------------------------------------------------

def _run_block(t_logits, d_logits, n, k, seed=0):
    """Propose from q with per-row draft keys, then rejection-sample:
    returns the (n, k+1) committed block over n independent rows."""
    v = t_logits.shape[-1]
    base = jax.vmap(jax.random.PRNGKey)(jnp.arange(n) + seed * 1_000_003)
    start = jnp.zeros((n,), jnp.int32)
    dkeys = block_keys(base, start, k, 7)           # any distinct tag
    proposals = jax.vmap(
        lambda keys: jax.vmap(jax.random.categorical)(
            keys, jnp.broadcast_to(d_logits, (k, v))
        )
    )(dkeys)
    a_keys = block_keys(base, start, k, KEY_TAG_ACCEPT)
    s_keys = block_keys(base, start, k + 1, KEY_TAG_SAMPLE)
    t = jnp.broadcast_to(t_logits, (n, k + 1, v))
    d = jnp.broadcast_to(d_logits, (n, k, v))
    block, accepted = rejection_sample_block(
        t, d, proposals, a_keys, s_keys
    )
    return np.asarray(block), np.asarray(accepted)


# chi-square critical values at alpha=0.001 — a deterministic test must
# essentially never flake, and a biased sampler overshoots by orders
_CHI2_999 = {5: 20.5, 6: 22.5, 7: 24.3}


def test_rejection_sampler_matches_target_softmax():
    """Position-0 marginal == target softmax under a DISAGREEING draft:
    both the accept path (p ~ q mass) and the residual path (q mass
    where p is thin) are exercised, and the mix must still be exactly
    p.  The bonus position (k, no draft) must also be exactly p."""
    v, k, n = 7, 2, 40_000
    rng = np.random.RandomState(5)
    t_logits = jnp.asarray(rng.randn(v) * 1.5, jnp.float32)
    d_logits = jnp.asarray(rng.randn(v) * 1.5, jnp.float32)
    p = np.asarray(jax.nn.softmax(t_logits))
    block, accepted = _run_block(t_logits, d_logits, n, k)
    # some rows must take each path or the test proves nothing
    assert (accepted == 0).sum() > n // 20, "residual path starved"
    assert (accepted > 0).sum() > n // 20, "accept path starved"
    counts = np.bincount(block[:, 0], minlength=v)
    chi2 = _chi_square(counts, p)
    assert chi2 < _CHI2_999[v - 1], (
        f"position-0 marginal diverged from target softmax: chi2={chi2}"
    )
    # bonus slot: rows whose drafts were ALL accepted sampled position k
    # from the pure target (q padded with 0 ⇒ residual IS p)
    full = block[accepted >= k]
    assert len(full) > n // 20
    chi2_bonus = _chi_square(np.bincount(full[:, k], minlength=v), p)
    assert chi2_bonus < _CHI2_999[v - 1], chi2_bonus


def test_rejection_residual_never_replays_the_rejected_token():
    """Where the draft OVER-proposes (q > p), a rejection's resample
    comes from max(0, p-q)/Z — the rejected token has zero residual
    mass there, so it can never be re-emitted at its own position."""
    v, k, n = 6, 1, 30_000
    # q piles mass on token 0; p spreads it — token 0 satisfies q > p
    t_logits = jnp.asarray(np.zeros(v), jnp.float32)
    d_logits = jnp.asarray([4.0] + [0.0] * (v - 1), jnp.float32)
    block, accepted = _run_block(t_logits, d_logits, n, k, seed=1)
    rejected_rows = accepted == 0
    assert rejected_rows.sum() > n // 10
    # every rejection in this geometry rejected token 0 or a uniform
    # token; where the PROPOSAL was 0 (q>p there), the resample at
    # position 0 must never be 0 again
    base = jax.vmap(jax.random.PRNGKey)(
        jnp.arange(n) + 1 * 1_000_003
    )
    dkeys = block_keys(base, jnp.zeros((n,), jnp.int32), k, 7)
    proposals = np.asarray(jax.vmap(
        lambda keys: jax.vmap(jax.random.categorical)(
            keys, jnp.broadcast_to(d_logits, (k, v))
        )
    )(dkeys))
    over = rejected_rows & (proposals[:, 0] == 0)
    assert over.sum() > n // 20
    assert (block[over, 0] != 0).all(), (
        "a rejected over-proposed token resurfaced from the residual"
    )
    # and the position-0 marginal is still exactly p (uniform)
    p = np.asarray(jax.nn.softmax(t_logits))
    chi2 = _chi_square(np.bincount(block[:, 0], minlength=v), p)
    assert chi2 < _CHI2_999[v - 1], chi2


# ---------------------------------------------------------------------------
# 2. seed-pinned dense decoding: the determinism grid
# ---------------------------------------------------------------------------

PROMPTS = None
BUDGETS = [8, 6, 7, 5]
TEMPS = [0.9, 0.0, 1.2, 0.8]
SEEDS = [41, None, 42, 43]


def _prompts():
    global PROMPTS
    if PROMPTS is None:
        rng = np.random.RandomState(9)
        PROMPTS = [
            np.array(rng.randint(0, CFG["vocab_size"], size=n), np.int32)
            for n in (3, 5, 7, 4)
        ]
    return PROMPTS


def _dense(params, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("prompt_pad", 8)
    return ContinuousBatcher(params, dtype=jnp.float32, **CFG, **kw)


def test_dense_seed_pinned_grid():
    """One pinned run is THE stream: invariant to slot count (forced
    slot reuse), batch composition (solo re-run), prefill chunking
    (monolithic vs 4-row chunks), and restart (a fresh batcher).  The
    greedy row rides along byte-identical, and a no-seeds run equals
    the explicit all-None run (the legacy key schedule untouched)."""
    params = trained_params()
    prompts = _prompts()
    ref = _dense(params).run(
        prompts, BUDGETS, temperatures=TEMPS, seeds=SEEDS
    )
    # restart + slot-count invariance
    again = _dense(params, slots=2).run(
        prompts, BUDGETS, temperatures=TEMPS, seeds=SEEDS
    )
    assert again == ref
    # prefill chunking invariance (monolithic admit program)
    mono = _dense(params, prefill_chunk=None).run(
        prompts, BUDGETS, temperatures=TEMPS, seeds=SEEDS
    )
    assert mono == ref
    # batch-composition invariance: the pinned sampled row solo
    solo = _dense(params).run(
        [prompts[2]], [BUDGETS[2]], temperatures=[TEMPS[2]], seeds=[42]
    )
    assert solo[0] == ref[2]
    # greedy row unchanged by its sampled neighbors
    greedy_solo = _dense(params).run([prompts[1]], [BUDGETS[1]])
    assert greedy_solo[0] == ref[1]
    # legacy: no seeds kwarg == all-None seeds, byte-identical
    leg_a = _dense(params).run(prompts, BUDGETS, temperatures=TEMPS)
    leg_b = _dense(params).run(
        prompts, BUDGETS, temperatures=TEMPS, seeds=[None] * 4
    )
    assert leg_a == leg_b
    # different seeds give different streams (the pin is not a no-op)
    other = _dense(params).run(
        [prompts[2]], [BUDGETS[2]], temperatures=[TEMPS[2]], seeds=[777]
    )
    assert other[0] != ref[2]


# ---------------------------------------------------------------------------
# 3. the sampled speculative batcher
# ---------------------------------------------------------------------------

def _spec(params, dparams, sampling=True, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("prompt_pad", 8)
    kw.setdefault("k", 3)
    return SpeculativeContinuousBatcher(
        params, dparams, draft_num_layers=1, draft_num_heads=2,
        draft_hidden=16, dtype=jnp.float32, sampling=sampling, **CFG, **kw,
    )


def test_spec_sampled_determinism_and_greedy_unchanged():
    params, dparams = trained_params(), draft_params()
    prompts = _prompts()
    m = Metrics()
    sb = _spec(params, dparams, metrics=m)
    ref = sb.run(prompts, BUDGETS, temperatures=TEMPS, seeds=SEEDS)
    # greedy rows == the greedy-only batcher's (compiled program parity)
    greedy = _spec(params, dparams, sampling=False).run(
        [prompts[1]], [BUDGETS[1]]
    )
    assert greedy[0] == ref[1]
    # restart + slot-reassignment invariance
    again = _spec(params, dparams, slots=2).run(
        prompts, BUDGETS, temperatures=TEMPS, seeds=SEEDS
    )
    assert again == ref
    # hedged duplicate execution: an independent engine (the hedge
    # twin on another replica) replays the pinned stream exactly
    twin = _spec(params, dparams).run(
        [prompts[2]], [BUDGETS[2]], temperatures=[TEMPS[2]], seeds=[42]
    )
    assert twin[0] == ref[2]
    # both modes observed the labeled accept-rate histogram
    assert m.histogram_count("serve_spec_accept_rate", mode="sampled") > 0
    assert m.histogram_count("serve_spec_accept_rate", mode="greedy") > 0


def test_spec_top_k_one_degenerates_to_greedy():
    """top_k=1 truncates the warped distribution to a point mass: the
    sampled machinery must emit the greedy stream token for token."""
    params, dparams = trained_params(), draft_params()
    prompts = _prompts()
    greedy = _spec(params, dparams, sampling=False).run(prompts, BUDGETS)
    pinned = _spec(params, dparams, top_k=1).run(
        prompts, BUDGETS, temperatures=[1.3] * 4, seeds=[1, 2, 3, 4]
    )
    assert pinned == greedy


def test_spec_greedy_only_guard():
    params, dparams = trained_params(), draft_params()
    sb = _spec(params, dparams, sampling=False)
    with pytest.raises(ValueError, match="greedy-only"):
        sb.run([np.array([1, 2], np.int32)], [2], temperatures=[0.7])


# ---------------------------------------------------------------------------
# 4. the gateway consequence: kill-mid-stream + sampled hedge
# ---------------------------------------------------------------------------

def _build_tier(n_replicas=3, n_gateways=2, step_delay_s=0.004,
                metrics=None):
    from kubegpu_tpu.gateway import (
        FailoverPolicy,
        GatewayTier,
        InMemoryReplicaClient,
        SimBatcher,
    )
    from kubegpu_tpu.testing.fake_serving import build_fake_serving_stack

    stack = build_fake_serving_stack(n_replicas)
    client = InMemoryReplicaClient(
        batcher_factory=lambda key: SimBatcher(slots=8),
        step_delay_s=step_delay_s,
    )
    stack.registry.subscribe(client.sync_live)
    tier = GatewayTier(
        stack.registry, client, n_gateways=n_gateways,
        metrics=metrics or Metrics(),
        policy=FailoverPolicy(
            deadline_s=30.0, hedge_after_s=0.05, max_attempts=6,
            retry_budget_ratio=1.0, budget_floor=100,
        ),
    )
    stack.registry.refresh()
    tier.start()
    return stack, client, tier


def _wait(predicate, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {msg}")


def test_seed_pinned_sampled_stream_survives_kill_mid_stream():
    """The regression ISSUE 19 exists to close: a SAMPLED stream with a
    pinned seed is killed mid-stream (home gateway dies); the sibling
    retry resumes at the relay watermark with DEDUP ON — sound only
    because the pinned mill stream is replica-independent — and the
    caller's stream is the full token list exactly once, no gap, no
    duplicate.  Before seed pinning this traffic ran dedup=False and
    could not resume at a watermark at all."""
    from kubegpu_tpu.gateway import GatewayRequest, GatewayTier, StreamRelay

    metrics = Metrics()
    stack, client, tier = _build_tier(metrics=metrics)
    try:
        relay = StreamRelay(metrics, dedup=True)
        request = GatewayRequest(
            prompt=[7, 8, 9], max_new_tokens=40, request_id="smp",
            session="sess-s", temperature=0.9, seed=1234,
        )
        request.on_tokens = relay.on_tokens
        request.stream_watermark = relay.emitted
        request.no_hedge = False
        gid, pending = tier.submit(request)
        _wait(lambda: relay.emitted() >= 3, msg="first streamed tokens")
        tier.kill(gid)
        assert pending.wait(20), "dead gateway never resolved the handle"
        assert pending.result().status == "error"
        clone = GatewayTier._clone(request)
        assert clone.seed == 1234  # the pin must survive the retry clone
        gid2, pending2 = tier.submit(clone)
        assert gid2 != gid
        assert pending2.wait(30) and pending2.result().status == "ok", (
            pending2.result()
        )
        result = pending2.result()
        assert len(result.tokens) == 40
        time.sleep(0.05)
        delivered = relay.drain()
        assert delivered == result.tokens, (
            f"seed-pinned sampled stream across the failover delivered "
            f"{len(delivered)} tokens vs result {len(result.tokens)}"
        )
    finally:
        tier.stop()
        client.stop()


def test_sampled_hedge_issues_and_is_counted():
    """A straggling primary on a seed-pinned sampled stream provokes a
    hedge (no_hedge False — the server only clears it when a seed is
    pinned), the twin's stream dedups cleanly, and the hedge is counted
    under gateway_sampled_hedges_total."""
    from kubegpu_tpu.gateway import GatewayRequest, StreamRelay

    metrics = Metrics()
    stack, client, tier = _build_tier(
        n_replicas=2, n_gateways=1, metrics=metrics,
    )
    try:
        keys = [r.key for r in stack.registry.routable()]
        relay = StreamRelay(metrics, dedup=True)
        request = GatewayRequest(
            prompt=[3, 1, 4], max_new_tokens=24, request_id="shg",
            temperature=1.1, seed=77,
        )
        request.on_tokens = relay.on_tokens
        request.stream_watermark = relay.emitted
        request.no_hedge = False
        client.set_step_delay(sorted(keys)[0], 0.2)
        _, pending = tier.submit(request)
        assert pending.wait(30) and pending.result().status == "ok", (
            pending.result()
        )
        result = pending.result()
        time.sleep(0.05)
        assert relay.drain() == result.tokens
        assert metrics.get("gateway_hedges_total") >= 1
        assert metrics.get("gateway_sampled_hedges_total") >= 1
    finally:
        tier.stop()
        client.stop()


def test_sim_batcher_seed_pins_the_mill_stream():
    """Two mill replicas given the same (prompt, seed) emit identical
    streams; a different seed (or no seed) emits a different one — the
    property the hedge/resume machinery above rides on."""
    from kubegpu_tpu.gateway.client import SimBatcher, sim_stream_seed

    def mill(seed, seq=0):
        sb = SimBatcher(slots=2)
        sb.submit(seq, [5, 6, 7], 10, 1.0,
                  stream_seed=sim_stream_seed([5, 6, 7]), seed=seed)
        out = []
        while sb.has_work():
            for _, toks in sb.serve_step().items():
                out = toks
        return out

    assert mill(9, seq=0) == mill(9, seq=1)   # replica/slot independent
    assert mill(9) != mill(10)
    assert mill(None) != mill(9)


# ---------------------------------------------------------------------------
# 5. the bf16 tie-flip class
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_bf16_spec_divergence_is_tie_flip_class():
    """bench.py's standing spec_lossless_b8=false /
    spec_serving_match_dense=false flags at bf16: the (b,k+1) verify
    GEMM re-blocks differently from the (b,1) step GEMM, drifting the
    cache ~1 ULP and flipping near-tie argmaxes.  Pin the class: at the
    first dense-vs-spec divergence the dense top1-top2 logit margin
    must be TINY (a tie), never wide (which would mean real breakage —
    fp32 identity is hard-gated separately in bench serving lanes)."""
    cfg = dict(CFG)
    model = TransformerLM(dtype=jnp.bfloat16, **cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((2, 8), jnp.int32)
    )["params"]
    prompts = _prompts()
    budgets = [12, 12, 12, 12]
    dense = ContinuousBatcher(
        params, dtype=jnp.bfloat16, slots=4, prompt_pad=8, **cfg
    ).run(prompts, budgets)
    spec = SpeculativeContinuousBatcher(
        params, params, k=3, draft_num_layers=cfg["num_layers"],
        draft_num_heads=cfg["num_heads"], draft_hidden=cfg["hidden"],
        dtype=jnp.bfloat16, slots=4, prompt_pad=8, **cfg,
    ).run(prompts, budgets)
    if dense == spec:
        return  # no flip on this box — identity is the best outcome
    for i in dense:
        if dense[i] == spec[i]:
            continue
        div = next(
            j for j in range(min(len(dense[i]), len(spec[i])))
            if dense[i][j] != spec[i][j]
        )
        # teacher-force the agreed prefix and read the dense margin at
        # the divergence position
        stream = np.concatenate([
            prompts[i], np.asarray(dense[i][:div], np.int32)
        ])
        logits = model.apply(
            {"params": params}, jnp.asarray(stream[None, :])
        )[0, -1].astype(jnp.float32)
        top2 = jax.lax.top_k(logits, 2)[0]
        margin = float(top2[0] - top2[1])
        assert margin < 0.05, (
            f"req {i} diverged at +{div} with margin {margin:.4f} — "
            "wider than the bf16 tie-flip class, a real bug"
        )
