"""L0 substrate tests: resource trees, topology geometry, annotation codecs."""

import json

import pytest

from kubegpu_tpu.types import (
    Chip,
    NodeInfo,
    PodInfo,
    ResourcePath,
    ResourceTree,
    RES_TPU,
    LEAF_TPU,
    SliceTopology,
    Submesh,
    TpuGeneration,
    annotations,
    coords_bounding_box,
    enumerate_rectangles,
    is_contiguous_submesh,
)
from kubegpu_tpu.types.info import Assignment, ChipRef
from kubegpu_tpu.types.topology import factor_shapes


# -- ResourcePath -----------------------------------------------------------

def test_path_roundtrip():
    # 'google.com/tpu' contains a slash, so tree paths use the slash-free
    # LEAF_TPU leaf; RES_TPU appears only in k8s specs.
    p = ResourcePath.parse("tpu-slice/s0/chip/3/tpu")
    assert str(p) == "tpu-slice/s0/chip/3/tpu"
    assert p.groups == (("tpu-slice", "s0"), ("chip", "3"))
    assert p.leaf == "tpu"


def test_path_wildcard_match():
    req = ResourcePath.parse("tpu-slice/*/chip/*/tpu")
    con = ResourcePath.parse("tpu-slice/s0/chip/2/tpu")
    other = ResourcePath.parse("tpu-slice/s0/host/2/tpu")
    assert req.has_wildcard
    assert req.matches(con)
    assert not req.matches(other)


def test_path_malformed():
    with pytest.raises(ValueError):
        ResourcePath.parse("a/b")  # even segment count
    with pytest.raises(ValueError):
        ResourcePath.parse("a//b")


# -- ResourceTree -----------------------------------------------------------

def test_tree_add_get_walk_deterministic():
    t = ResourceTree()
    for i in (2, 0, 1):
        t.add(ResourcePath.parse(f"chip/{i}/tpu"), 1)
    walked = [str(p) for p, _ in t.walk()]
    assert walked == ["chip/0/tpu", "chip/1/tpu", "chip/2/tpu"]
    assert t.get(ResourcePath.parse("chip/1/tpu")) == 1
    assert t.total("tpu") == 3


def test_tree_take_return_roundtrip():
    cap = ResourceTree.from_flat({"chip/0/tpu": 1, "chip/1/tpu": 1})
    used = ResourceTree.from_flat({"chip/0/tpu": 1})
    avail = cap.clone()
    avail.add_tree(used, sign=-1)
    assert avail.to_flat() == {"chip/1/tpu": 1}
    avail.add_tree(used, sign=1)
    assert avail == cap
    with pytest.raises(ValueError):
        bad = ResourceTree.from_flat({"chip/5/tpu": 2})
        avail.add_tree(bad, sign=-1)


def test_tree_flat_roundtrip():
    flat = {"tpu-slice/s0/chip/0/tpu": 1, "tpu-slice/s0/chip/1/tpu": 1}
    t = ResourceTree.from_flat(flat)
    assert t.to_flat() == flat


# -- topology geometry ------------------------------------------------------

def test_factor_shapes():
    assert factor_shapes(4, 2) == [(1, 4), (2, 2), (4, 1)]
    assert (2, 2, 2) in factor_shapes(8, 3)


def test_enumerate_rectangles_v5e16():
    rects = list(enumerate_rectangles(4, (4, 4)))
    shapes = {r.shape for r in rects}
    assert shapes == {(1, 4), (2, 2), (4, 1)}
    # 2x2 has 3x3 origins, 1x4/4x1 have 4 each → 9 + 4 + 4
    assert len(rects) == 17


def test_enumerate_rectangles_wrap():
    rects = list(enumerate_rectangles(4, (4, 4), wrap=(True, True)))
    # wraparound: every origin is legal in dims where shape < extent; a
    # full-extent dim has exactly one distinct origin.
    # (1,4): 4×1, (2,2): 4×4, (4,1): 1×4 → 24
    assert len(rects) == 24
    sub = Submesh(origin=(3, 0), shape=(2, 2))
    coords = sub.coords((4, 4), (True, True))
    assert (0, 0) in coords and (3, 1) in coords


def test_is_contiguous():
    assert is_contiguous_submesh({(0, 0), (0, 1), (1, 0), (1, 1)}, (4, 4))
    assert not is_contiguous_submesh({(0, 0), (0, 1), (1, 0), (2, 2)}, (4, 4))
    assert not is_contiguous_submesh({(0, 0), (1, 1)}, (4, 4))
    # L-shape of 4
    assert not is_contiguous_submesh({(0, 0), (0, 1), (0, 2), (1, 0)}, (4, 4))
    # wraparound rectangle on a torus
    wrapped = {(3, 0), (3, 1), (0, 0), (0, 1)}
    assert not is_contiguous_submesh(wrapped, (4, 4))
    assert is_contiguous_submesh(wrapped, (4, 4), wrap=(True, False))


def test_bounding_box():
    origin, shape = coords_bounding_box({(1, 2), (2, 3)})
    assert origin == (1, 2) and shape == (2, 2)


# -- SliceTopology ----------------------------------------------------------

def test_build_v5e16():
    topo = SliceTopology.build("s0", TpuGeneration.V5E, (4, 4), host_block=(2, 2))
    assert topo.num_chips == 16
    assert len(topo.hosts()) == 4
    for h in topo.hosts():
        chips = topo.host_chips(h)
        assert len(chips) == 4
        assert [c.device_index for c in chips] == [0, 1, 2, 3]
        # each host's block is itself contiguous
        assert is_contiguous_submesh({c.coords for c in chips}, (4, 4))


def test_build_with_unhealthy():
    topo = SliceTopology.build(
        "s0", TpuGeneration.V5E, (4, 4), host_block=(2, 2), unhealthy=[(0, 0)]
    )
    assert len(topo.healthy_coords()) == 15


def test_topology_dict_roundtrip():
    topo = SliceTopology.build("s0", TpuGeneration.V5E, (4, 4), host_block=(2, 2))
    topo2 = SliceTopology.from_dict(json.loads(json.dumps(topo.to_dict())))
    assert topo2.mesh_shape == (4, 4)
    assert topo2.chips == topo.chips


# -- NodeInfo / annotations -------------------------------------------------

def _node_from_slice(topo: SliceTopology, host: str) -> NodeInfo:
    node = NodeInfo(
        name=host,
        slice_id=topo.slice_id,
        generation=topo.generation,
        mesh_shape=topo.mesh_shape,
        wrap=topo.wrap,
        chips=topo.host_chips(host),
    )
    node.rebuild_capacity()
    return node


def test_nodeinfo_capacity_excludes_unhealthy():
    topo = SliceTopology.build(
        "s0", TpuGeneration.V5E, (4, 4), host_block=(2, 2), unhealthy=[(0, 0)]
    )
    host = topo.chips[(0, 0)].host_id
    node = _node_from_slice(topo, host)
    assert node.capacity.total(LEAF_TPU) == 3
    assert node.allocatable().total(LEAF_TPU) == 3
    # wire-format regression: capacity trees must round-trip through flat form
    assert ResourceTree.from_flat(node.capacity.to_flat()) == node.capacity


def test_node_annotation_roundtrip():
    topo = SliceTopology.build("s0", TpuGeneration.V5E, (4, 4), host_block=(2, 2))
    host = topo.hosts()[0]
    node = _node_from_slice(topo, host)
    payload = annotations.encode_node_topology(node)
    node2 = annotations.decode_node_topology(host, payload)
    assert node2.slice_id == "s0"
    assert node2.mesh_shape == (4, 4)
    assert node2.chips == node.chips
    assert node2.capacity.total(LEAF_TPU) == 4


def test_pod_from_k8s_and_assignment_roundtrip():
    obj = {
        "metadata": {
            "name": "w0",
            "namespace": "ml",
            "uid": "u1",
            "annotations": {
                annotations.POD_GROUP: "job-a",
                annotations.POD_GROUP_SIZE: "4",
                annotations.POD_CONTIGUOUS: "true",
                annotations.POD_PRIORITY: "10",
            },
        },
        "spec": {
            "containers": [
                {"name": "train", "resources": {"limits": {RES_TPU: "4"}}},
                {"name": "sidecar"},
            ]
        },
    }
    pod = annotations.pod_from_k8s(obj)
    assert pod.key == "ml/w0"
    assert pod.total_tpu_chips() == 4
    assert pod.pod_group == "job-a" and pod.pod_group_size == 4
    assert pod.priority == 10
    a = Assignment(
        node="n0",
        slice_id="s0",
        per_container={"train": [ChipRef("n0", 0, 0, (0, 0)), ChipRef("n0", 1, 1, (0, 1))]},
        score=1.5,
    )
    pod.annotations[annotations.POD_ASSIGNMENT] = annotations.encode_assignment(a)
    a2 = annotations.assignment_from_pod(pod.annotations)
    assert a2 is not None
    assert a2.node == "n0" and len(a2.all_chips()) == 2
    assert a2.per_container["train"][1].coords == (0, 1)


def test_non_tpu_node_passthrough():
    node = annotations.node_from_k8s({"metadata": {"name": "cpu-node"}})
    assert not node.is_tpu_node
    assert node.capacity.total(LEAF_TPU) == 0


def test_assignment_from_annotation_map_with_metadata_key():
    # a legal annotation literally named "metadata" must not derail the
    # pod-object/annotation-map disambiguation
    a = Assignment(node="n0", slice_id="s0", per_container={})
    ann = {"metadata": "someval", annotations.POD_ASSIGNMENT: annotations.encode_assignment(a)}
    got = annotations.assignment_from_pod(ann)
    assert got is not None and got.node == "n0"
