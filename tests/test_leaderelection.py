"""Leader election (VERDICT r3 #1): Lease CAS semantics, the elector's
mutual exclusion, and THE safety proof — two extender replicas over one
API server racing binds commit through exactly one of them, with zero
double-allocations, including across a rolling-update handoff."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from kubegpu_tpu.plugins import Advertiser, FakeSlice
from kubegpu_tpu.scheduler import ExtenderServer, Scheduler
from kubegpu_tpu.types import RES_TPU, annotations
from kubegpu_tpu.utils import Conflict, InMemoryApiServer, LeaderElector, NotFound
from kubegpu_tpu.utils.metrics import Metrics


# ---------------------------------------------------------------------------
# Lease object semantics (the CAS everything rests on)
# ---------------------------------------------------------------------------

def lease_obj(name="l", ns="kube-system", holder="a", rv=None):
    obj = {
        "apiVersion": "coordination.k8s.io/v1",
        "kind": "Lease",
        "metadata": {"name": name, "namespace": ns},
        "spec": {"holderIdentity": holder, "leaseDurationSeconds": 15},
    }
    if rv is not None:
        obj["metadata"]["resourceVersion"] = rv
    return obj


def test_lease_create_conflicts_and_update_cas():
    api = InMemoryApiServer()
    with pytest.raises(NotFound):
        api.get_lease("kube-system", "l")
    created = api.create_lease(lease_obj())
    assert created["metadata"]["resourceVersion"] == "1"
    with pytest.raises(Conflict):
        api.create_lease(lease_obj())  # exists
    # stale resourceVersion loses the CAS
    with pytest.raises(Conflict):
        api.update_lease("kube-system", "l", lease_obj(holder="b", rv="0"))
    ok = api.update_lease("kube-system", "l", lease_obj(holder="b", rv="1"))
    assert ok["metadata"]["resourceVersion"] == "2"
    assert api.get_lease("kube-system", "l")["spec"]["holderIdentity"] == "b"
    # the losing writer's read is now stale again
    with pytest.raises(Conflict):
        api.update_lease("kube-system", "l", lease_obj(holder="c", rv="1"))


# ---------------------------------------------------------------------------
# elector semantics
# ---------------------------------------------------------------------------

def make_elector(api, ident, **kw):
    kw.setdefault("lease_duration_s", 0.6)
    kw.setdefault("renew_period_s", 0.1)
    kw.setdefault("retry_period_s", 0.1)
    return LeaderElector(api, ident, name="test-lease", **kw)


def test_single_elector_acquires_renews_releases():
    api = InMemoryApiServer()
    e = make_elector(api, "a")
    assert e.try_acquire_or_renew() == "ok"
    e._set_held(True)
    assert e.is_leader()
    # renewal succeeds repeatedly (holder renewing its own lease)
    assert e.try_acquire_or_renew() == "ok"
    lease = api.get_lease("kube-system", "test-lease")
    assert lease["spec"]["holderIdentity"] == "a"
    assert lease["spec"]["leaseTransitions"] == 0
    e.release()
    assert not e.is_leader()
    assert api.get_lease("kube-system", "test-lease")["spec"]["holderIdentity"] == ""
    # a second identity can now take over immediately
    b = make_elector(api, "b")
    assert b.try_acquire_or_renew() == "ok"
    assert api.get_lease("kube-system", "test-lease")["spec"]["holderIdentity"] == "b"
    assert api.get_lease("kube-system", "test-lease")["spec"]["leaseTransitions"] == 1


def test_standby_defers_to_live_holder_and_takes_over_expired():
    """Observation-based expiry (client-go observedRenewTime): a standby
    defers while the holder's record keeps CHANGING, and takes over only
    after it has sat unchanged for the lease duration on the standby's
    own clock — never by comparing the lease's wall-clock stamps."""
    api = InMemoryApiServer()
    # wide window so scheduler-of-this-test stalls can't fake expiry
    a = make_elector(api, "a", lease_duration_s=30.0, renew_period_s=5.0)
    assert a.try_acquire_or_renew() == "ok"
    b = make_elector(api, "b", lease_duration_s=30.0, renew_period_s=5.0)
    assert b.try_acquire_or_renew() == "lost"  # first observation
    assert b.try_acquire_or_renew() == "lost"  # unchanged, within window
    # a renews: the record changes, so b's observation timer restarts
    assert a.try_acquire_or_renew() == "ok"
    b._observed_at -= 31.0  # would have expired under the OLD observation
    assert b.try_acquire_or_renew() == "lost"  # renewal reset the timer
    # a dies (no more renews): rewind b's observation clock past the
    # duration — the deterministic stand-in for waiting it out
    b._observed_at -= 31.0
    assert b.try_acquire_or_renew() == "ok"
    assert api.get_lease("kube-system", "test-lease")["spec"]["holderIdentity"] == "b"
    assert api.get_lease("kube-system", "test-lease")["spec"]["leaseTransitions"] == 1


def test_two_electors_never_both_leader():
    """Run both electors' real loops concurrently and sample leadership:
    at no sampled instant do both claim it (the invariant the verb gate
    relies on)."""
    api = InMemoryApiServer()
    a, b = make_elector(api, "a"), make_elector(api, "b")
    stop = threading.Event()
    threads = [
        threading.Thread(target=e.run, args=(stop,), daemon=True)
        for e in (a, b)
    ]
    for t in threads:
        t.start()
    both, either = 0, 0
    try:
        deadline = time.monotonic() + 1.5
        while time.monotonic() < deadline:
            la, lb = a.is_leader(), b.is_leader()
            both += la and lb
            either += la or lb
            time.sleep(0.01)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
    assert both == 0, f"both replicas claimed leadership {both} times"
    assert either > 0, "nobody ever led"


def test_transient_api_error_does_not_flap_but_times_out():
    """client-go renewDeadline semantics: one failed renew keeps the claim
    (the lease window covers it); sustained failure retires leadership
    before a standby could legitimately acquire."""
    api = InMemoryApiServer()
    e = make_elector(api, "a")
    assert e.try_acquire_or_renew() == "ok"
    e._set_held(True)
    assert e.is_leader()
    broken = lambda *a, **k: (_ for _ in ()).throw(OSError("api down"))
    orig = api.get_lease
    api.get_lease = broken
    try:
        assert e.try_acquire_or_renew() == "error"
        # claim survives the blip...
        assert e.is_leader()
        # ...but times out within the lease duration
        time.sleep(0.7)
        assert not e.is_leader()
    finally:
        api.get_lease = orig


# ---------------------------------------------------------------------------
# THE two-replica safety proof (VERDICT r3 #1 done-condition)
# ---------------------------------------------------------------------------

def fake_cluster():
    api = InMemoryApiServer()
    fs = FakeSlice(slice_id="s0", mesh_shape=(4, 4), host_block=(2, 2))
    for h, p in fs.providers().items():
        Advertiser(p, api).advertise_once()
    return api


def pod_obj(name, chips=1):
    return {
        "metadata": {"name": name, "namespace": "default",
                     "uid": f"uid-{name}", "annotations": {}},
        "spec": {"containers": [
            {"name": "m", "resources": {"limits": {RES_TPU: str(chips)}}}]},
    }


def make_replica(api, ident):
    sched = Scheduler(api, metrics=Metrics())
    elector = LeaderElector(
        api, ident, name="extender-ha",
        # wide lease, tight renew/retry: leadership cannot flap mid-test
        # under scheduler stalls, but clean-release handoff is still fast
        lease_duration_s=5.0, renew_period_s=0.2, retry_period_s=0.2,
        on_started_leading=sched.cache.refresh,
    )
    server = ExtenderServer(
        sched, listen=("127.0.0.1", 0), resync_interval_s=3600.0,
        watch=False, elector=elector,
    )
    return server


def post(addr, path, payload):
    req = urllib.request.Request(
        f"http://{addr[0]}:{addr[1]}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status, json.loads(r.read())


def wait_for_one_leader(servers, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [s for s in servers if s.elector.is_leader()]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.02)
    raise AssertionError("no single leader emerged")


def test_two_replicas_racing_binds_commit_exactly_once():
    """The test that fails without leader election: two extender replicas
    over one API server are driven with the same filter+bind for 8 pods;
    only the leader commits, the standby answers 503 non-fatally, and no
    chip is ever charged twice."""
    api = fake_cluster()
    r1, r2 = make_replica(api, "replica-1"), make_replica(api, "replica-2")
    r1.start()
    r2.start()
    try:
        leader = wait_for_one_leader([r1, r2])
        standby = r2 if leader is r1 else r1
        nodes = sorted(n["metadata"]["name"] for n in api.list_nodes())
        statuses = {"leader": [], "standby": []}
        for i in range(8):
            obj = pod_obj(f"p{i}")
            api.create_pod(obj)
            # drive BOTH replicas with the same verbs, standby first (the
            # misconfigured-client order most likely to double-commit)
            for who, srv in (("standby", standby), ("leader", leader)):
                try:
                    code, body = post(
                        srv.address, "/filter",
                        {"Pod": obj, "NodeNames": nodes},
                    )
                except urllib.error.HTTPError as e:
                    code, body = e.code, json.loads(e.read())
                if code == 200 and body.get("NodeNames"):
                    code2, b2 = 200, None
                    try:
                        code2, b2 = post(
                            srv.address, "/bind",
                            {"PodNamespace": "default", "PodName": f"p{i}",
                             "Node": body["NodeNames"][0]},
                        )
                        ok = code2 == 200 and not b2.get("Error")
                    except urllib.error.HTTPError as e:
                        ok = False
                    statuses[who].append("bound" if ok else "refused")
                else:
                    statuses[who].append("refused")
        assert statuses["leader"] == ["bound"] * 8, statuses
        assert statuses["standby"] == ["refused"] * 8, statuses
        # ZERO double-allocations: every charged chip is unique
        seen = set()
        for i in range(8):
            a = annotations.assignment_from_pod(api.get_pod("default", f"p{i}"))
            assert a is not None
            for c in a.all_chips():
                key = (c.host, c.device_index)
                assert key not in seen, f"chip {key} double-allocated"
                seen.add(key)
        assert len(seen) == 8
    finally:
        r1.stop()
        r2.stop()


def test_rolling_update_handoff_promotes_standby():
    """Rolling-update overlap (the window replicas:1 could never cover):
    the leader stops cleanly, releasing the lease; the standby promotes,
    replays API-server state into its cache, and serves the next bind —
    with the already-bound pod's chips correctly charged (no reuse)."""
    api = fake_cluster()
    r1, r2 = make_replica(api, "replica-1"), make_replica(api, "replica-2")
    r1.start()
    r2.start()
    try:
        leader = wait_for_one_leader([r1, r2])
        standby = r2 if leader is r1 else r1
        nodes = sorted(n["metadata"]["name"] for n in api.list_nodes())
        # bind a 4-chip pod through the first leader
        obj = pod_obj("before", 4)
        api.create_pod(obj)
        code, body = post(leader.address, "/filter", {"Pod": obj, "NodeNames": nodes})
        assert code == 200 and body["NodeNames"]
        first_node = body["NodeNames"][0]
        _, b = post(leader.address, "/bind",
                    {"PodNamespace": "default", "PodName": "before",
                     "Node": first_node})
        assert not b.get("Error"), b
        # rolling update: old leader goes away (clean release on stop)
        leader.stop()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not standby.elector.is_leader():
            time.sleep(0.02)
        assert standby.elector.is_leader(), "standby never promoted"
        # the promoted replica serves, and its replayed cache still charges
        # the first pod's chips: a full-node request no longer fits there
        obj2 = pod_obj("after", 4)
        api.create_pod(obj2)
        code, body = post(standby.address, "/filter", {"Pod": obj2, "NodeNames": nodes})
        assert code == 200 and body["NodeNames"], body
        _, b = post(standby.address, "/bind",
                    {"PodNamespace": "default", "PodName": "after",
                     "Node": body["NodeNames"][0]})
        assert not b.get("Error"), b
        a1 = annotations.assignment_from_pod(api.get_pod("default", "before"))
        a2 = annotations.assignment_from_pod(api.get_pod("default", "after"))
        chips1 = {(c.host, c.device_index) for c in a1.all_chips()}
        chips2 = {(c.host, c.device_index) for c in a2.all_chips()}
        assert not (chips1 & chips2), "handoff double-allocated chips"
    finally:
        for s in (r1, r2):
            try:
                s.stop()
            except Exception:  # noqa: BLE001 - first already stopped
                pass


def test_promotion_callback_runs_before_verb_gate_opens():
    """Code-review r4 regression: on_started_leading (the cache replay)
    must COMPLETE before is_leader() first returns True — a promoted
    replica serving binds against a stale cache is the double-allocation
    HA exists to prevent.  Also: a failing callback defers promotion to
    the next cycle instead of leading unready."""
    api = InMemoryApiServer()
    e = make_elector(api, "a")
    state = {"fail_once": True, "gate_open_during_callback": None}

    def on_started():
        if state["fail_once"]:
            state["fail_once"] = False
            raise RuntimeError("replay failed")
        state["gate_open_during_callback"] = e.is_leader()

    e.on_started_leading = on_started
    stop = threading.Event()
    t = threading.Thread(target=e.run, args=(stop,), daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not e.is_leader():
            time.sleep(0.01)
        assert e.is_leader(), "never promoted after callback retry"
    finally:
        stop.set()
        t.join(timeout=5.0)
    assert state["fail_once"] is False  # first attempt ran and failed
    assert state["gate_open_during_callback"] is False, (
        "verb gate was already open while the promotion callback ran"
    )


def test_readyz_reflects_leadership_and_fencing_gate_aborts_bind():
    """Code-review r4 regressions: (a) /readyz is leadership-aware so only
    the leader sits in the Service's Endpoints (a Ready standby would eat
    ~half of all extender calls with 503s); (b) the fencing re-check
    aborts a bind whose leadership lapsed between the HTTP gate and the
    durable annotation write, rolling the reservation back."""
    api = fake_cluster()
    r1, r2 = make_replica(api, "replica-1"), make_replica(api, "replica-2")
    r1.start()
    r2.start()
    try:
        leader = wait_for_one_leader([r1, r2])
        standby = r2 if leader is r1 else r1

        def get(addr, path):
            return urllib.request.urlopen(
                f"http://{addr[0]}:{addr[1]}{path}", timeout=10
            ).status

        assert get(leader.address, "/healthz") == 200
        assert get(standby.address, "/healthz") == 200  # liveness: both up
        assert get(leader.address, "/readyz") == 200
        with pytest.raises(urllib.error.HTTPError) as ei:
            get(standby.address, "/readyz")
        assert ei.value.code == 503

        # fencing: leadership lapses after filter but before the durable
        # commit — the bind must abort and free its reservation
        obj = pod_obj("fence", 1)
        api.create_pod(obj)
        nodes = sorted(n["metadata"]["name"] for n in api.list_nodes())
        code, body = post(leader.address, "/filter", {"Pod": obj, "NodeNames": nodes})
        assert code == 200 and body["NodeNames"]
        leader.sched.serving_gate = lambda: False  # lease window closed
        try:
            code, b = post(
                leader.address, "/bind",
                {"PodNamespace": "default", "PodName": "fence",
                 "Node": body["NodeNames"][0]},
            )
        except urllib.error.HTTPError as e:
            code, b = e.code, {}
        assert b.get("Error") and "lost leadership" in b["Error"], b
        assert annotations.assignment_from_pod(api.get_pod("default", "fence")) is None
        assert "default/fence" not in leader.sched.cache.assignments_snapshot()
    finally:
        r1.stop()
        r2.stop()


def test_tls_stalled_client_does_not_block_other_requests(tmp_path):
    """Code-review r4 regression: the TLS handshake must run on the
    per-connection thread, not the accept loop — a client that connects
    and never speaks must not stall every verb and the health probes."""
    import socket
    import ssl

    pytest.importorskip("cryptography")  # optional TLS test dependency
    from kubegpu_tpu.testing.tlsutil import make_self_signed

    api = fake_cluster()
    cert, key = make_self_signed(str(tmp_path))
    srv = ExtenderServer(
        Scheduler(api, metrics=Metrics()), listen=("127.0.0.1", 0),
        tls_cert=cert, tls_key=key,
    )
    srv.start()
    try:
        # the attack: open TCP, send nothing (handshake never starts)
        mute = socket.create_connection(srv.address, timeout=5)
        try:
            ctx = ssl.create_default_context(cafile=cert)
            t0 = time.monotonic()
            status = urllib.request.urlopen(
                f"https://{srv.address[0]}:{srv.address[1]}/healthz",
                timeout=10, context=ctx,
            ).status
            assert status == 200
            assert time.monotonic() - t0 < 5.0, (
                "healthz stalled behind a mute TLS client"
            )
        finally:
            mute.close()
    finally:
        srv.stop()


def test_stop_releases_lease_synchronously():
    """Code-review r4: stop() must release the lease ITSELF — the elector
    thread is a daemon and can die at interpreter exit before its own
    release runs; the deployed SIGTERM path routes through stop(), so the
    holder must be cleared by the time stop() returns (no leaderless
    lease-window wait for the standby)."""
    api = fake_cluster()
    r1 = make_replica(api, "replica-1")
    r1.start()
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not r1.elector.is_leader():
            time.sleep(0.02)
        assert r1.elector.is_leader()
    finally:
        r1.stop()
    lease = api.get_lease("kube-system", "extender-ha")
    assert lease["spec"]["holderIdentity"] == "", lease["spec"]
