"""Live KV-page migration (ISSUE 11): drains, failovers and re-pins
become TRANSFERS instead of cold restarts.

Layers under test:

- the batcher verb pair — ``export_pages``/``import_pages`` (live
  sequence: committed pages + chain keys + decode cursor) and
  ``export_sealed_chain``/``import_sealed_chain`` (failover insurance)
  — held to fp32 token identity of a migrated-mid-decode sequence vs a
  never-migrated one, across page sizes × speculation × multi-turn
  sealing, and to ATOMIC accounting: export is read-only, a refused
  import moves zero refcounts, an orphaned export leaks nothing, a
  double import SHARES chain pages instead of duplicating them;
- tensor parallelism — a TP=2→TP=2 migration moves tp shard-local
  copies (same head-sharded layout both ends) and stays token-identical
  to the single-device stream; a TP=2→TP=1 import works too (the
  payload is layout-agnostic host bytes);
- the registry lifecycle — probe failures back off exponentially with
  jitter (fake clock) and reset on success; DRAINING replicas leave
  ``routable()`` without leaving ``live()``;
- the gateway lifecycle — ``drain_replica`` migrates live sequences
  (stream continuity proven by the SimBatcher's seed arithmetic) and
  stops new admissions; a session whose pinned replica DIES restores
  its turn-2 state from the captured sealed export on the new pin;
- GatewaySoak ``migration=True`` — drains, bare migrates,
  kill-mid-migration (exporter or importer dies between export and
  import ack) and importer refusals, in the in-memory and HTTP lanes,
  with ``assert_page_accounting`` holding on BOTH ends at quiescence
  in the paged lanes.
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubegpu_tpu.models import TransformerLM
from kubegpu_tpu.models.paging import PagedContinuousBatcher
from kubegpu_tpu.parallel import device_mesh

# heads divisible by the tested TP widths; vocab by the lm_head split
CFG = dict(vocab_size=64, num_layers=2, num_heads=8, hidden=32, max_seq=64)


@pytest.fixture(scope="module")
def params():
    model = TransformerLM(dtype=jnp.float32, **CFG)
    return model.init(
        jax.random.PRNGKey(0), jnp.ones((2, 8), jnp.int32)
    )["params"]


def make_paged(params, tp=1, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("prompt_pad", 16)
    kw.setdefault("page_size", 4)
    kw.setdefault("pool_pages", 48)
    kw.setdefault("decode_page_cache", "fp32")
    mesh = None
    if tp > 1:
        if jax.device_count() < tp:
            pytest.skip(f"need {tp} devices, have {jax.device_count()}")
        mesh = device_mesh({"model": tp}, devices=jax.devices()[:tp])
    return PagedContinuousBatcher(
        params, dtype=jnp.float32, mesh=mesh, **CFG, **kw
    )


def spec_kw(params, k=2):
    return dict(
        draft_params=params, speculate_k=k,
        draft_num_layers=CFG["num_layers"],
        draft_num_heads=CFG["num_heads"], draft_hidden=CFG["hidden"],
    )


def drive_until(cb, seq_id, n_tokens, max_steps=200):
    """Step until the sequence committed >= n_tokens (still live)."""
    for _ in range(max_steps):
        cb.serve_step()
        s = next((s for s in cb._seqs if s.seq_id == seq_id), None)
        if s is not None and s.active and len(s.tokens) >= n_tokens:
            return
    raise AssertionError(
        f"seq {seq_id} never reached {n_tokens} live tokens"
    )


def drain(cb):
    done = {}
    while cb.has_work():
        done.update(cb.serve_step())
    return done


PROMPT = np.array([3, 1, 4, 1, 5, 9, 2, 6], np.int32)


# ---------------------------------------------------------------------------
# fp32 token identity: migrated mid-decode == never-migrated
# ---------------------------------------------------------------------------

def _identity_case(params, page_size, spec):
    kw = dict(page_size=page_size)
    if spec:
        kw.update(spec_kw(params))
    src = make_paged(params, **kw)
    dst = make_paged(params, **kw)
    budget = 20
    ref = src.run([PROMPT], [budget])[0]     # never-migrated reference
    assert len(ref) == budget
    # same prompt again: admission may hit the sealed chain — migrating
    # a sequence whose pages are partly CACHE-OWNED is the interesting
    # case (export reads shared pages, detach decrefs them)
    src.submit(1, PROMPT, budget)
    drive_until(src, 1, 5)
    payload = src.export_pages(1)
    assert len(payload["tokens"]) >= 5
    assert payload["tokens"] == ref[: len(payload["tokens"])]
    src.cancel(1)                            # detach
    src.assert_page_accounting()
    dst.import_pages(11, payload)
    dst.assert_page_accounting()             # mid-transfer, importer side
    out = drain(dst)
    assert out[11] == ref
    src.assert_page_accounting()
    dst.assert_page_accounting()


@pytest.mark.parametrize("page_size,spec", [(4, False), (4, True)])
def test_live_migration_identity(params, page_size, spec):
    _identity_case(params, page_size, spec)


@pytest.mark.slow
@pytest.mark.parametrize("page_size,spec", [(8, False), (8, True)])
def test_live_migration_identity_page8(params, page_size, spec):
    _identity_case(params, page_size, spec)


def test_multiturn_sealed_migration(params):
    """The multi-turn axis: turn 1 seals on the source; a turn-2
    sequence (whose admission HITS the sealed chain) migrates
    mid-decode and must finish token-identical to the never-migrated
    turn 2."""
    src = make_paged(params)
    dst = make_paged(params)
    t1 = src.run([PROMPT], [7])[0]
    stream = [int(t) for t in PROMPT] + t1
    p2 = np.asarray(stream[:14] + [11], np.int32)
    ref = src.run([p2], [8])[0]              # never-migrated turn 2
    src.submit(5, p2, 8)
    drive_until(src, 5, 3)
    payload = src.export_pages(5)
    src.cancel(5)
    dst.import_pages(50, payload)
    out = drain(dst)
    assert out[50] == ref
    src.assert_page_accounting()
    dst.assert_page_accounting()
    # the replayed chain made the importer warm: a THIRD turn on dst
    # hits through the imported region
    p3 = np.asarray(stream[:12], np.int32)
    dst.run([p3], [4])
    assert dst.stats["prefix_hit_tokens"] > 0


def test_sealed_chain_restore_roundtrip(params):
    """The failover insurance flow at batcher level: capture turn 1's
    sealed chain, import it into a cold replica, and turn 2 there must
    hit the decode region and match the stayed-home turn 2."""
    src = make_paged(params, prompt_pad=24)
    dst = make_paged(params, prompt_pad=24)
    t1 = src.run([PROMPT], [9])[0]
    stream = [int(t) for t in PROMPT] + t1
    payload = src.export_sealed_chain(stream)
    assert payload is not None
    assert len(payload["page_keys"]) == (len(stream) - 1) // 4
    n = dst.import_sealed_chain(payload)
    assert n == len(payload["page_keys"])
    dst.assert_page_accounting()
    # idempotent: a second import dedups to zero fresh pages
    assert dst.import_sealed_chain(payload) == 0
    p2 = np.asarray(stream + [13], np.int32)
    ref = src.run([p2], [6])[0]
    out = dst.run([p2], [6])[0]
    assert out == ref
    assert dst.stats["prefix_hit_tokens_decode"] > 0
    src.assert_page_accounting()
    dst.assert_page_accounting()


def test_export_is_read_only_and_orphan_safe(params):
    """Export must not perturb the exporter: a sequence exported
    mid-decode and NOT detached finishes byte-identical, and an
    orphaned payload (never imported) leaks nothing on either end."""
    src = make_paged(params)
    ref = src.run([PROMPT], [15])[0]
    src.submit(2, PROMPT, 15)
    drive_until(src, 2, 4)
    payload = src.export_pages(2)
    src.assert_page_accounting()             # mid-transfer, exporter side
    out = drain(src)                         # keep serving: no detach
    assert out[2] == ref
    src.assert_page_accounting()
    del payload                              # orphaned export: just bytes
    src.assert_page_accounting()


def test_double_import_shares_chain_pages(params):
    src = make_paged(params)
    dst = make_paged(params)
    ref = src.run([PROMPT], [16])[0]
    src.submit(1, PROMPT, 16)
    drive_until(src, 1, 9)                   # past 2 full pages
    payload = src.export_pages(1)
    src.cancel(1)
    dst.import_pages(21, payload)
    dst.import_pages(22, payload)            # the double import
    dst.assert_page_accounting()
    shared = [
        p for s in dst._seqs if s.seq_id in (21, 22) for p in s.shared
    ]
    assert len(shared) > len(set(shared)), (
        "double import duplicated chain pages instead of sharing them"
    )
    out = drain(dst)
    assert out[21] == ref and out[22] == ref
    src.assert_page_accounting()
    dst.assert_page_accounting()


def test_import_into_chain_with_a_hole(params):
    """LRU eviction can pop a chain's FIRST page while later pages stay
    cached (entries are independent key→page maps).  An import meeting
    that hole must SHARE the surviving pages and freshly register only
    the missing one — never crash on a duplicate key, never leak
    (regression: the insert used to assert mid-commit, stranding the
    already-acquired pages)."""
    src = make_paged(params)
    dst = make_paged(params)
    ref = src.run([PROMPT], [16])[0]
    src.submit(1, PROMPT, 16)
    drive_until(src, 1, 9)                   # >= 2 full chain pages
    payload = src.export_pages(1)
    src.cancel(1)
    n_keys = sum(1 for k in payload["page_keys"] if k is not None)
    assert n_keys >= 2
    # warm dst with the full chain, then punch the hole: evict exactly
    # the oldest entry — the chain's first page
    assert dst.import_sealed_chain(
        src.export_sealed_chain(
            payload["prompt"] + payload["tokens"]
        )
    ) > 0
    first = dst.prefix_cache.evict_lru()
    assert first is not None
    dst.free_pages.add(first)
    dst.assert_page_accounting()
    dst.import_pages(30, payload)            # used to AssertionError here
    dst.assert_page_accounting()
    s = next(s for s in dst._seqs if s.seq_id == 30)
    assert len(s.shared) >= n_keys - 1       # survivors shared, not copied
    out = drain(dst)
    assert out[30] == ref
    dst.assert_page_accounting()
    src.assert_page_accounting()


def test_import_refusal_is_atomic(params):
    src = make_paged(params)
    src.submit(1, PROMPT, 12)
    drive_until(src, 1, 4)
    payload = src.export_pages(1)

    # no free slot
    dst = make_paged(params, slots=1)
    dst.submit(9, np.array([7, 7, 7], np.int32), 30)
    drive_until(dst, 9, 1)
    before = (set(dst.free_pages), len(dst.prefix_cache))
    with pytest.raises(RuntimeError, match="no free sequence slot"):
        dst.import_pages(40, payload)
    assert (set(dst.free_pages), len(dst.prefix_cache)) == before
    dst.assert_page_accounting()

    # a payload that can NEVER fit this pool is a ValueError (the
    # shared admission contract), still with zero refcounts moved
    never = make_paged(params, pool_pages=4)
    before = (set(never.free_pages), len(never.prefix_cache))
    with pytest.raises(ValueError, match="pages"):
        never.import_pages(41, payload)
    assert (set(never.free_pages), len(never.prefix_cache)) == before
    never.assert_page_accounting()

    # pool PRESSURE (fits in principle, not right now) refuses with
    # zero refcounts moved — the retriable case
    tiny = make_paged(params, pool_pages=8)
    tiny.submit(1, np.array([7, 7, 7], np.int32), 12)
    drive_until(tiny, 1, 1)
    before = (set(tiny.free_pages), len(tiny.prefix_cache))
    with pytest.raises(RuntimeError, match="import refused"):
        tiny.import_pages(41, payload)
    assert (set(tiny.free_pages), len(tiny.prefix_cache)) == before
    drain(tiny)
    tiny.assert_page_accounting()

    # geometry mismatch is a ValueError (not a refusal): pages only move
    # between twins
    other = make_paged(params, page_size=8)
    with pytest.raises(ValueError, match="geometry mismatch"):
        other.import_pages(42, payload)
    other.assert_page_accounting()
    src.assert_page_accounting()


def test_export_rejects_unknown_and_mid_prefill(params):
    cb = make_paged(params)
    with pytest.raises(KeyError):
        cb.export_pages(123)
    # a long prompt chunk-prefills one page per iteration: after one
    # step the admission is mid-prefill — nothing committed to move
    long_prompt = np.arange(1, 13, dtype=np.int32)
    cb.submit(3, long_prompt, 8)
    cb.serve_step()
    s = next(s for s in cb._seqs if s.seq_id == 3)
    assert s.prefilling
    with pytest.raises(ValueError, match="mid-prefill"):
        cb.export_pages(3)
    drain(cb)
    cb.assert_page_accounting()


# ---------------------------------------------------------------------------
# tensor parallelism: shard-local transfers
# ---------------------------------------------------------------------------

def test_tp2_migration_identity(params):
    ref = make_paged(params).run([PROMPT], [14])[0]
    src = make_paged(params, tp=2)
    dst = make_paged(params, tp=2)
    src.submit(1, PROMPT, 14)
    drive_until(src, 1, 5)
    payload = src.export_pages(1)
    assert payload["geometry"]["tp"] == 2
    src.cancel(1)
    dst.import_pages(10, payload)
    out = drain(dst)
    assert out[10] == ref
    # both ends balanced INCLUDING the sharded-layout leg (the import
    # scatter must leave the pool resting head-sharded)
    src.assert_page_accounting()
    dst.assert_page_accounting()


def test_tp2_to_tp1_migration(params):
    """The payload is layout-agnostic host bytes: a TP=2 export imports
    into an unsharded twin and stays token-identical."""
    ref = make_paged(params).run([PROMPT], [12])[0]
    src = make_paged(params, tp=2)
    dst = make_paged(params)
    src.submit(1, PROMPT, 12)
    drive_until(src, 1, 6)
    payload = src.export_pages(1)
    src.cancel(1)
    dst.import_pages(10, payload)
    assert drain(dst)[10] == ref
    src.assert_page_accounting()
    dst.assert_page_accounting()


# ---------------------------------------------------------------------------
# registry: probe backoff (fake clock) + DRAINING
# ---------------------------------------------------------------------------

def _registry_stack(probe, clock):
    from kubegpu_tpu.gateway import ReplicaRegistry
    from kubegpu_tpu.testing.fake_serving import build_fake_serving_stack

    stack = build_fake_serving_stack(1)
    return ReplicaRegistry(stack.api, probe=probe, clock=clock)


def test_probe_backoff_exponential_with_jitter_and_reset():
    clock = type("C", (), {"t": 0.0, "__call__": lambda s: s.t})()
    calls = []
    state = {"ok": False}

    def probe(info):
        calls.append(clock.t)
        return (True, "") if state["ok"] else (False, "down")

    reg = _registry_stack(probe, clock)
    reg.refresh()
    assert len(calls) == 1
    (key,) = [r.key for r in reg.all()]
    assert not reg.live_keys()
    assert "data plane: down" in reg.get(key).reason

    # inside the backoff window: refreshes do NOT re-probe, and the
    # cached failure (annotated as backing off) stands
    reg.refresh()
    reg.refresh()
    assert len(calls) == 1
    assert "backing off" in reg.get(key).reason

    # walk the windows: each expiry probes exactly once more, and the
    # delays grow exponentially within the jitter envelope
    delays = []
    for _ in range(4):
        window = reg._probe_backoff[key]["next"] - clock.t
        delays.append(window)
        clock.t = reg._probe_backoff[key]["next"] + 1e-6
        n = len(calls)
        reg.refresh()
        assert len(calls) == n + 1
    for i, d in enumerate(delays):
        ideal = min(30.0, 0.5 * 2 ** i)
        assert 0.5 * ideal <= d < 1.5 * ideal, (i, d, ideal)
    assert delays[2] > delays[0]

    # success resets: the replica goes live and the next failure backs
    # off from the BASE again
    state["ok"] = True
    clock.t = reg._probe_backoff[key]["next"] + 1e-6
    reg.refresh()
    assert reg.live_keys() == frozenset({key})
    assert key not in reg._probe_backoff
    state["ok"] = False
    reg.refresh()
    fresh = reg._probe_backoff[key]["next"] - clock.t
    assert fresh < 0.5 * 1.5, fresh


def test_draining_leaves_routable_not_live():
    from kubegpu_tpu.gateway import ReplicaRegistry
    from kubegpu_tpu.testing.fake_serving import build_fake_serving_stack

    stack = build_fake_serving_stack(2)
    reg = ReplicaRegistry(stack.api)
    fired = []
    reg.subscribe(lambda live: fired.append(set(live)))
    reg.refresh()
    keys = sorted(r.key for r in reg.live())
    assert len(keys) == 2
    n_fired = len(fired)
    reg.set_draining(keys[0])
    # draining is NOT a live-set change: the data plane must keep its
    # connections (an observer firing would abort in-flight streams)
    assert len(fired) == n_fired
    assert sorted(r.key for r in reg.live()) == keys
    assert [r.key for r in reg.routable()] == [keys[1]]
    assert reg.get(keys[0]).draining
    reg.set_draining(keys[0], False)
    assert sorted(r.key for r in reg.routable()) == keys


# ---------------------------------------------------------------------------
# gateway lifecycle: drain + sealed restore after death
# ---------------------------------------------------------------------------

def _gateway_stack(n_replicas, batcher_factory, router=None, **gw_kw):
    from kubegpu_tpu.gateway import (
        AdmissionQueue, FailoverPolicy, Gateway, InMemoryReplicaClient,
    )
    from kubegpu_tpu.testing.fake_serving import build_fake_serving_stack
    from kubegpu_tpu.utils.metrics import Metrics

    stack = build_fake_serving_stack(n_replicas, metrics=Metrics())
    client = InMemoryReplicaClient(
        batcher_factory=batcher_factory, step_delay_s=0.002,
    )
    stack.registry.subscribe(client.sync_live)
    gw = Gateway(
        stack.registry, client, router=router,
        queue=AdmissionQueue(capacity=64),
        policy=FailoverPolicy(
            deadline_s=60.0, max_attempts=8,
            retry_budget_ratio=1.0, budget_floor=100,
        ),
        metrics=Metrics(), dispatchers=4, **gw_kw,
    )
    stack.registry.refresh()
    gw.start()
    return stack, client, gw


def test_drain_migrates_inflight_and_stops_admissions():
    from kubegpu_tpu.gateway import GatewayRequest, SimBatcher

    stack, client, gw = _gateway_stack(
        3, lambda key: SimBatcher(slots=8, vocab=101)
    )
    try:
        slow = gw.submit(GatewayRequest(
            prompt=[1, 2, 3], max_new_tokens=120, request_id="slow",
        ))
        # find where it landed
        home = None
        deadline = time.monotonic() + 10
        while home is None and time.monotonic() < deadline:
            for rep in stack.registry.live():
                if any(
                    not a.done for a in client.inflight_on(rep.key)
                ):
                    home = rep.key
            time.sleep(0.005)
        assert home is not None
        stats = gw.drain_replica(home)
        assert stats["migrated"] == 1, stats
        assert [r.key for r in stack.registry.routable()] == sorted(
            r.key for r in stack.registry.live() if r.key != home
        )
        # new admissions avoid the draining replica entirely
        quick = [
            gw.submit(GatewayRequest(
                prompt=[5], max_new_tokens=3, request_id=f"q{i}",
            ))
            for i in range(12)
        ]
        for p in quick:
            assert p.wait(30) and p.result().status == "ok"
        assert home not in gw.completed_by_replica
        assert slow.wait(60) and slow.result().status == "ok"
        tokens = slow.result().tokens
        assert len(tokens) == 120
        # stream CONTINUITY across the migration: one seed explains the
        # whole stream (token i == (seed*31 + i) % vocab) — a restart
        # would show a seam where the arithmetic re-anchors
        seed31 = (tokens[0] - 0) % 101
        assert all(
            tokens[i] == (seed31 + i) % 101 for i in range(len(tokens))
        ), "migrated stream is not one mill's arithmetic"
        assert gw.metrics.get("gateway_replica_drains_total") == 1
    finally:
        gw.stop()
        client.stop()


def test_sealed_restore_after_replica_death(params):
    """The acceptance flow: turn 1 pins a session to a paged replica
    (which seals and is eagerly captured); the replica DIES; turn 2
    re-pins, the dispatcher imports the captured export, and the new
    replica serves it from warm decode pages — token-identical to an
    undisturbed session."""
    from kubegpu_tpu.gateway import GatewayRequest, SessionAffinityRouter

    def factory(key):
        return make_paged(params, prompt_pad=24)

    stack, client, gw = _gateway_stack(
        2, factory, router=SessionAffinityRouter(),
    )
    try:
        p1 = [int(t) for t in PROMPT]
        r1 = gw.submit(GatewayRequest(
            prompt=p1, max_new_tokens=9, request_id="t1", session="s",
        ))
        assert r1.wait(120) and r1.result().status == "ok", r1.result()
        home = r1.result().replica
        stream = p1 + r1.result().tokens
        # the insurance was captured while the replica lived (the
        # capture writes through asynchronously — flush it)
        assert gw.session_store.flush_captures(30.0)
        entry = gw.session_store.entry("s")
        assert entry["payload"] is not None
        assert entry["replica"] == home

        # never-migrated reference for turn 2 (fresh twin batcher)
        ref_cb = make_paged(params, prompt_pad=24)
        ref_cb.run([np.asarray(p1, np.int32)], [9])
        p2 = stream + [13]
        ref = ref_cb.run([np.asarray(p2, np.int32)], [6])[0]

        # the pinned replica dies: process + chips, same advertise cycle
        client.fail_replica(home)
        rep = stack.registry.get(home)
        for coords in rep.coords:
            stack.slices[rep.slice_id].kill_chip(coords)
        for adv in stack.advs.values():
            adv.advertise_once()
        stack.registry.refresh()
        assert home not in stack.registry.live_keys()

        r2 = gw.submit(GatewayRequest(
            prompt=p2, max_new_tokens=6, request_id="t2", session="s",
        ))
        assert r2.wait(120) and r2.result().status == "ok", r2.result()
        assert r2.result().replica != home
        assert r2.result().tokens == ref
        assert gw.metrics.get("gateway_session_restores_total") == 1
        # the survivor actually served from warm pages
        with client._lock:
            survivor = client._workers[r2.result().replica].batcher
        assert survivor.stats["prefix_hit_tokens_decode"] > 0
        survivor.assert_page_accounting()
    finally:
        gw.stop()
        client.stop()


# ---------------------------------------------------------------------------
# SimBatcher migration contract (no jax)
# ---------------------------------------------------------------------------

def test_simbatcher_migration_contract():
    from kubegpu_tpu.gateway import SimBatcher

    a, b = SimBatcher(slots=2, vocab=97), SimBatcher(slots=1, vocab=97)
    a.submit(5, [1, 2], 10)
    for _ in range(4):
        a.serve_step()
    payload = a.export_pages(5)
    assert payload["sim"] and payload["seed"] == 5
    with pytest.raises(KeyError):
        a.export_pages(99)
    a.cancel(5)
    b.import_pages(0, payload, trace=None)
    out = {}
    while b.has_work():
        out.update(b.serve_step())
    assert out[0] == [(5 * 31 + i) % 97 for i in range(10)]
    # refusal: no free slot
    b.submit(7, [1], 5)
    b.serve_step()
    with pytest.raises(RuntimeError):
        b.import_pages(8, payload)


# ---------------------------------------------------------------------------
# soak: the kill-mid-migration schedules
# ---------------------------------------------------------------------------

def test_gateway_soak_migration_inmemory():
    from kubegpu_tpu.testing.soak import GatewaySoak

    GatewaySoak(seed=101, n_replicas=4, migration=True).run(70)


def test_gateway_soak_migration_http():
    from kubegpu_tpu.testing.soak import GatewaySoak

    GatewaySoak(seed=202, n_replicas=3, migration=True, http=True).run(45)


@pytest.mark.slow
def test_gateway_soak_migration_paged_kill_schedule(params):
    """The acceptance schedule, in-memory lane: paged fp32 replicas
    with sealing + multiturn traffic under drains, migrations,
    kill-mid-migration and importer refusals — ``check()`` holds I5,
    the trace oracles, and ``assert_page_accounting`` on every
    surviving batcher (both ends of every transfer) at quiescence."""
    from kubegpu_tpu.testing.soak import GatewaySoak

    def factory(key):
        return make_paged(params, slots=8, prompt_pad=16, pool_pages=64)

    GatewaySoak(
        seed=303, n_replicas=3, batcher_factory=factory,
        multiturn=True, migration=True,
    ).run(24)


@pytest.mark.slow
def test_gateway_soak_migration_paged_http_kill_schedule(params):
    """The same schedule ACROSS THE WIRE: every export/import is a real
    /v1/export / /v1/import round-trip, kills are server deaths, and
    the page-accounting claim holds through sockets."""
    from kubegpu_tpu.testing.soak import GatewaySoak

    def factory(key):
        return make_paged(params, slots=8, prompt_pad=16, pool_pages=64)

    GatewaySoak(
        seed=404, n_replicas=3, batcher_factory=factory,
        multiturn=True, migration=True, http=True,
    ).run(20)
