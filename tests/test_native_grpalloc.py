"""Parity tests: native allocator core vs the defining Python loop.

The C++ scan (native/grpalloc_core.cpp) must reproduce the Python
enumeration+scoring+sort EXACTLY — same candidate sets, bit-identical
scores (both are IEEE doubles applying the same operations in the same
order), same tie-broken order — across mesh ranks, wrap configurations,
and random free masks (holes from used/unhealthy chips).
"""

import itertools
import os
import random
import subprocess

import pytest

from kubegpu_tpu.grpalloc import native_core
from kubegpu_tpu.grpalloc.scoring import placement_score
from kubegpu_tpu.types.topology import enumerate_rectangles

NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native"
)


@pytest.fixture(scope="module", autouse=True)
def built_lib():
    if not os.path.exists(os.path.join(NATIVE_DIR, "libgrpalloc_core.so")):
        try:
            subprocess.run(["make", "-C", NATIVE_DIR], check=True, capture_output=True)
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", b"") or b""
            pytest.skip(
                "native core not buildable here: "
                f"{e} [{detail[-300:].decode(errors='replace')}]"
            )
    if native_core.load() is None:
        pytest.skip("libgrpalloc_core.so not loadable")


def python_candidates(n, mesh_shape, wrap, free):
    out = []
    for rect in enumerate_rectangles(n, mesh_shape, wrap):
        coords = rect.coords(mesh_shape, wrap)
        if not coords <= free:
            continue
        s = placement_score(coords, free, mesh_shape, wrap)
        out.append((s, sorted(coords), coords))
    out.sort(key=lambda t: (-t[0], t[1]))
    return out


def assert_parity(n, mesh_shape, wrap, free):
    native = native_core.candidate_rectangles(n, mesh_shape, wrap, free)
    assert native is not None
    expected = python_candidates(n, mesh_shape, wrap, free)
    assert len(native) == len(expected), (n, mesh_shape, wrap)
    for (ns, ncoords, nset), (ps, pcoords, pset) in zip(native, expected):
        assert ns == ps, f"score diverges: {ns} != {ps} for {pcoords}"
        assert ncoords == pcoords
        assert nset == pset


MESHES = [
    ((4, 4), (False, False)),
    ((4, 4), (True, True)),
    ((8, 4), (True, False)),
    ((16,), (True,)),
    ((4, 4, 4), (False, False, True)),
]


@pytest.mark.parametrize("mesh_shape,wrap", MESHES)
def test_parity_full_mesh(mesh_shape, wrap):
    full = frozenset(itertools.product(*(range(s) for s in mesh_shape)))
    for n in (1, 2, 4, 8):
        assert_parity(n, mesh_shape, wrap, full)


@pytest.mark.parametrize("mesh_shape,wrap", MESHES)
def test_parity_random_holes(mesh_shape, wrap):
    cells = sorted(itertools.product(*(range(s) for s in mesh_shape)))
    rng = random.Random(hash(mesh_shape) & 0xFFFF)
    for trial in range(5):
        free = frozenset(c for c in cells if rng.random() < 0.7)
        for n in (2, 4):
            assert_parity(n, mesh_shape, wrap, free)


def test_parity_no_free_space():
    assert_parity(4, (4, 4), (False, False), frozenset())


def test_score_entry_matches_python():
    """grpalloc_score (arbitrary coord sets, incl. non-contiguous)."""
    import ctypes

    lib = native_core.load()
    mesh_shape, wrap = (4, 4), (False, True)
    cells = sorted(itertools.product(range(4), range(4)))
    rng = random.Random(7)
    for _ in range(20):
        free = frozenset(c for c in cells if rng.random() < 0.8)
        pick = rng.sample(sorted(free), min(4, len(free))) if free else []
        if not pick:
            continue
        volume = 16
        mask = (ctypes.c_uint8 * volume)()
        for c in free:
            mask[c[0] * 4 + c[1]] = 1
        flat = (ctypes.c_int * len(pick))(*[c[0] * 4 + c[1] for c in pick])
        got = lib.grpalloc_score(
            (ctypes.c_int * 2)(*mesh_shape),
            (ctypes.c_uint8 * 2)(0, 1),
            2,
            mask,
            flat,
            len(pick),
        )
        want = placement_score(frozenset(pick), free, mesh_shape, wrap)
        assert got == want, (pick, got, want)


def test_fit_gang_native_vs_python_identical():
    """End-to-end: fit_gang with the native path vs KUBEGPU_NO_NATIVE must
    produce the same placements."""
    from kubegpu_tpu.grpalloc.allocator import _candidate_rectangles
    from kubegpu_tpu.grpalloc.view import SliceView

    view = SliceView(slice_id="s", mesh_shape=(4, 4), wrap=(False, False))
    free = frozenset((x, y) for x in range(4) for y in range(4) if (x, y) != (1, 2))
    got = _candidate_rectangles(4, view, free)
    os.environ["KUBEGPU_NO_NATIVE"] = "1"
    try:
        want = _candidate_rectangles(4, view, free)
    finally:
        del os.environ["KUBEGPU_NO_NATIVE"]
    assert [(s, c) for s, c, _ in got] == [(s, c) for s, c, _ in want]


def test_native_speedup_logged():
    """Not a hard perf gate (CI noise) — but record the ratio so regressions
    are visible in test output; the native scan should not be slower."""
    import time

    mesh_shape, wrap = (16, 16), (True, True)
    full = frozenset(itertools.product(range(16), range(16)))
    native_core.candidate_rectangles(16, mesh_shape, wrap, full)  # warm
    t0 = time.perf_counter()
    native_core.candidate_rectangles(16, mesh_shape, wrap, full)
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    python_candidates(16, mesh_shape, wrap, full)
    t_python = time.perf_counter() - t0
    print(f"\nnative {t_native*1e3:.1f}ms vs python {t_python*1e3:.1f}ms "
          f"({t_python/max(t_native,1e-9):.0f}x)")
    assert t_native < t_python