"""Real multi-process rendezvous e2e: two OS processes bring up
jax.distributed from EXACTLY the env the CRI shim injects
(crishim/inject.py::worker_env) and train together — the closest this
harness gets to a real multi-host gang (SURVEY.md §3.4), with the CPU
backend standing in for per-host TPU runtimes."""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow  # JAX compile-heavy; run with -m slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def spawn(script: str, env_extra: dict) -> subprocess.Popen:
    env = {k: v for k, v in os.environ.items() if k not in (
        "JAX_PLATFORMS", "XLA_FLAGS", "PYTHONPATH",
    )}
    env.update(
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO,
        **env_extra,
    )
    return subprocess.Popen(
        [sys.executable, "-c", script], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )


def run_gang(script: str, n: int = 2, timeout: float = 180.0):
    port = free_port()
    names = [f"w{i}" for i in range(n)]
    procs = []
    for i in range(n):
        env = {
            # the injected contract, verbatim (inject.py::worker_env)
            "TPU_WORKER_ID": str(i),
            "TPU_WORKER_HOSTNAMES": ",".join(names),
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "JAX_NUM_PROCESSES": str(n),
            "JAX_PROCESS_ID": str(i),
        }
        procs.append(spawn(script, env))
    outs = []
    try:
        for p in procs:
            try:
                out, err = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                pytest.fail("gang member hung at rendezvous")
            assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
            outs.append(out)
    finally:
        # a failed assert must not orphan siblings blocked at rendezvous
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    return outs


def test_two_process_rendezvous_and_collective():
    outs = run_gang(textwrap.dedent("""
        from kubegpu_tpu.parallel import device_mesh, distributed_init_from_env
        assert distributed_init_from_env() is True
        import jax, jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        assert jax.process_count() == 2
        assert jax.device_count() == 2 and jax.local_device_count() == 1
        mesh = device_mesh({"data": 2})
        # one global array from per-process rows, then a global reduction
        rows = jnp.full((1, 4), float(jax.process_index() + 1))
        g = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("data")), rows)
        total = float(jax.jit(lambda x: x.sum())(g))
        assert total == (1 + 2) * 4, total
        print(f"OK process={jax.process_index()} total={total}")
    """))
    assert all("OK" in o for o in outs)


@pytest.mark.exhaustive
def test_two_process_worker_trains_data_parallel():
    # the REAL worker entrypoint across two processes: rendezvous, disjoint
    # per-process data, global-batch DP steps, both report the first step
    outs = run_gang(textwrap.dedent("""
        from kubegpu_tpu.models import worker
        rc = worker.main([
            "--model", "resnet-tiny", "--steps", "3", "--batch-per-chip", "2",
        ])
        assert rc == 0
    """), timeout=300.0)
    for o in outs:
        assert "FIRST_STEP_DONE" in o


@pytest.mark.exhaustive
def test_four_process_worker_gang_north_star_shape():
    """The north-star config's REAL process shape (VERDICT r1 weak #7): four
    OS processes rendezvous from the injected env and train DP together —
    not just the 2-process ceiling."""
    outs = run_gang(textwrap.dedent("""
        from kubegpu_tpu.models import worker
        import jax
        rc = worker.main([
            "--model", "resnet-tiny", "--steps", "2", "--batch-per-chip", "2",
        ])
        assert rc == 0
        assert jax.process_count() == 4 and jax.device_count() == 4
    """), n=4, timeout=420.0)
    assert len(outs) == 4
    for o in outs:
        assert "FIRST_STEP_DONE" in o


LM_ARGS = [
    "--model", "lm", "--tp", "2", "--steps", "2", "--batch-per-chip", "2",
    "--vocab", "64", "--layers", "1", "--heads", "2", "--hidden", "16",
    "--seq", "32", "--data-pool", "1",
]


@pytest.mark.exhaustive
def test_two_process_tp_lm_matches_single_process_loss():
    """TP gang data integrity: with dp=1 the token batch is REPLICATED
    across the two single-device processes, so both must feed byte-identical
    rows into make_array_from_process_local_data — divergent streams would
    silently stitch different 'replicas' and the TP psum would mix
    activations from different inputs.  The discriminator: the gang's first
    -step loss must equal a single-process run of the same config."""
    import re

    script = textwrap.dedent("""
        from kubegpu_tpu.models import worker
        rc = worker.main(%r)
        assert rc == 0
    """ % (LM_ARGS,))
    gang = run_gang(script, timeout=300.0)
    solo = spawn(script, {"XLA_FLAGS": "--xla_force_host_platform_device_count=2"})
    out, err = solo.communicate(timeout=300.0)
    assert solo.returncode == 0, err[-2000:]

    def first_loss(text):
        m = re.search(r"FIRST_STEP_DONE seconds=\S+ loss=(\S+)", text)
        assert m, text
        return float(m.group(1))

    ref = first_loss(out)
    for o in gang:
        assert abs(first_loss(o) - ref) < 1e-4, (first_loss(o), ref)


@pytest.mark.exhaustive
def test_multislice_gang_process_shaped_rendezvous():
    """VERDICT r2 next #4: the megascale env contract, PROCESS-shaped.

    Schedule a 4-pod multislice gang (2 slices x 2 members) through the
    real extender, compute every member's env through the REAL injection
    path (ShimDaemon.decide -> crishim/inject.py), assert the contract —
    slice-local TPU_WORKER_ID/TPU_WORKER_HOSTNAMES tables, gang-global
    JAX process table, megascale coordinator on slice 0 — then LAUNCH all
    four as OS processes with exactly that env and prove they rendezvous
    (jax.distributed.initialize) and complete a cross-process collective."""
    from kubegpu_tpu.crishim import ShimDaemon
    from kubegpu_tpu.plugins import Advertiser, FakeSlice
    from kubegpu_tpu.scheduler import Scheduler
    from kubegpu_tpu.types import annotations as ann
    from kubegpu_tpu.utils import InMemoryApiServer
    from kubegpu_tpu.utils.metrics import Metrics

    api = InMemoryApiServer()
    fss = {}
    for sid in ("sl-a", "sl-b"):
        fs = FakeSlice(slice_id=sid, mesh_shape=(2, 4), host_block=(2, 2))
        fss[sid] = fs
        for host, prov in fs.providers().items():
            Advertiser(prov, api).advertise_once()
    sched = Scheduler(api, metrics=Metrics())
    sched.cache.refresh()

    pods = [
        {
            "metadata": {
                "name": f"ms{i}", "namespace": "default",
                "annotations": {
                    ann.POD_GROUP: "msgang",
                    ann.POD_GROUP_SIZE: "4",
                    ann.POD_MULTISLICE: "true",
                },
            },
            "spec": {
                "subdomain": "ms-svc",
                "containers": [
                    {"name": "main",
                     "resources": {"limits": {"google.com/tpu": "4"}}}
                ],
            },
        }
        for i in range(4)
    ]
    nodes = sorted(n["metadata"]["name"] for n in api.list_nodes())
    for obj in pods:
        api.create_pod(obj)
    for obj in pods:
        name = obj["metadata"]["name"]
        r = sched.filter(obj, nodes)
        assert r.nodes, r.failed
        assert sched.bind("default", name, r.nodes[0]) is None

    # the real injection path, per member, on its own node's provider
    injections, by_slice = {}, {}
    for i in range(4):
        name = f"ms{i}"
        stored = api.get_pod("default", name)
        a = ann.assignment_from_pod(stored)
        daemon = ShimDaemon(api, fss[a.slice_id].provider_for(a.node))
        inj = daemon.decide(
            "default", name, "main", stored["metadata"]["annotations"], name
        )
        assert inj is not None and inj.env.get("TPU_VISIBLE_CHIPS")
        injections[name] = inj.env
        by_slice.setdefault(a.slice_id, []).append(name)

    # --- contract: 2 slices x 2 members, slice-local libtpu tables -------
    assert sorted(len(v) for v in by_slice.values()) == [2, 2]
    ordered = sorted(by_slice)
    for sid, members in by_slice.items():
        local = sorted(members)
        for name in members:
            env = injections[name]
            assert env["TPU_WORKER_ID"] == str(local.index(name)), (name, env)
            assert env["TPU_WORKER_HOSTNAMES"].split(",") == [
                f"{m}.ms-svc.default.svc" for m in local
            ]
            assert env["JAX_NUM_PROCESSES"] == "4"
            assert env["MEGASCALE_NUM_SLICES"] == "2"
            assert env["MEGASCALE_SLICE_ID"] == str(ordered.index(sid))
    # gang-global process table is a permutation of 0..3, coordinator shared
    ids = sorted(int(injections[f"ms{i}"]["JAX_PROCESS_ID"]) for i in range(4))
    assert ids == [0, 1, 2, 3]
    coords = {e["JAX_COORDINATOR_ADDRESS"] for e in injections.values()}
    assert len(coords) == 1
    # megascale coordinator: first member ON the first slice
    ms_coord = injections["ms0"]["MEGASCALE_COORDINATOR_ADDRESS"]
    assert ms_coord.rsplit(":", 1)[0] == (
        f"{sorted(by_slice[ordered[0]])[0]}.ms-svc.default.svc"
    )

    # --- launch: 4 OS processes with exactly the injected env ------------
    # (pod DNS names don't resolve on this harness: only the coordinator
    # HOST is rewritten to loopback, after being asserted correct above)
    port = free_port()
    script = textwrap.dedent("""
        import os, jax, jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from kubegpu_tpu.parallel import device_mesh, distributed_init_from_env
        assert distributed_init_from_env() is True
        assert jax.process_count() == 4
        wid = int(os.environ["TPU_WORKER_ID"])          # slice-local
        assert wid in (0, 1)
        assert len(os.environ["TPU_WORKER_HOSTNAMES"].split(",")) == 2
        mesh = device_mesh({"data": 4})
        rows = jnp.full((1, 2), float(jax.process_index() + 1))
        g = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("data")), rows)
        total = float(jax.jit(lambda x: x.sum())(g))
        assert total == (1 + 2 + 3 + 4) * 2, total
        print(f"OK pid={jax.process_index()} "
              f"slice={os.environ['MEGASCALE_SLICE_ID']} total={total}")
    """)
    procs = []
    for i in range(4):
        env = dict(injections[f"ms{i}"])
        env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        procs.append(spawn(script, env))
    outs = []
    try:
        for p in procs:
            try:
                out, err = p.communicate(timeout=420.0)
            except subprocess.TimeoutExpired:
                pytest.fail("multislice gang member hung at rendezvous")
            assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    assert len(outs) == 4
    slices_seen = set()
    for o in outs:
        assert "OK pid=" in o
        slices_seen.add(o.split("slice=")[1].split()[0])
    assert slices_seen == {"0", "1"}
