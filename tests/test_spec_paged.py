"""Speculative decoding on the paged serving path (ISSUE 4).

The contract under test: with a draft model attached
(``speculate_k=k``), ``PagedContinuousBatcher`` emits EXACTLY the tokens
the non-speculative paged batcher emits (which the station tests pin to
the dense batcher and the per-sequence greedy oracle) — for ANY draft,
across speculation depths, station widths, token budgets, prefix-cache
hits, EOS early-exit, and slot churn.  The draft only moves how many
verify programs the stream costs.  fp32 everywhere: losslessness is
guaranteed per numerics class (see models/spec_serving.py — at bf16 the
(b, k+1) verify GEMMs may round ~1 ULP apart from the (b, 1) step's,
which is a tie-flip class, not a bookkeeping bug; these tests hold the
HOST algorithm to token-exactness where the class guarantees it).

Also here: the dense ``SpeculativeContinuousBatcher`` fp32 regression on
the exact slot-churn traffic that exposed the r5
``spec_serving_match_dense: false`` artifact, the GatewaySoak kill
schedule with speculation on (no page leaked by rejected drafts), the
compile-stability bound for the three speculative programs, and the
``serve_spec_*`` metrics in the shared exposition format.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubegpu_tpu.models import TransformerLM, greedy_generate
from kubegpu_tpu.models.paging import PagedContinuousBatcher
from kubegpu_tpu.models.serving import ContinuousBatcher
from kubegpu_tpu.utils.metrics import Metrics

pytestmark = pytest.mark.slow

CFG = dict(vocab_size=61, num_layers=2, num_heads=4, hidden=32, max_seq=32)
DRAFT = dict(draft_num_layers=1, draft_num_heads=2, draft_hidden=16)


def trained_params():
    model = TransformerLM(dtype=jnp.float32, **CFG)
    return model.init(jax.random.PRNGKey(0), jnp.ones((2, 8), jnp.int32))[
        "params"
    ]


def draft_params():
    # an independent random init: a HOPELESS draft (the all-reject path);
    # perfect-draft coverage reuses the target's own params
    model = TransformerLM(
        vocab_size=CFG["vocab_size"], max_seq=CFG["max_seq"],
        num_layers=DRAFT["draft_num_layers"],
        num_heads=DRAFT["draft_num_heads"], hidden=DRAFT["draft_hidden"],
        dtype=jnp.float32,
    )
    return model.init(jax.random.PRNGKey(7), jnp.ones((2, 8), jnp.int32))[
        "params"
    ]


def oracle(params, prompt, n):
    out = greedy_generate(
        params, jnp.asarray(prompt)[None, :], n, dtype=jnp.float32, **CFG
    )
    return list(np.asarray(out)[0, len(prompt):])


def make_paged(params, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("prompt_pad", 16)
    kw.setdefault("page_size", 4)
    kw.setdefault("pool_pages", 40)
    return PagedContinuousBatcher(params, dtype=jnp.float32, **CFG, **kw)


def make_spec_paged(params, dparams, k, **kw):
    return make_paged(
        params, draft_params=dparams, speculate_k=k, **DRAFT, **kw
    )


# ---------------------------------------------------------------------------
# Property: spec-paged ≡ paged ≡ dense oracle across the grid
# ---------------------------------------------------------------------------

def test_spec_paged_token_identical_across_k_and_stations():
    """Greedy, fixed seed, slot churn (10 sequences through 4 slots),
    prompt lengths straddling page boundaries, a duplicate prompt (an
    in-burst prefix-cache hit), mixed budgets — the speculative batcher
    must emit the per-sequence oracle's exact tokens for k ∈ {1, 2, 4}
    with both a hopeless and a perfect draft, across station widths and
    under a token budget (where a speculative slot bills k+1 rows)."""
    params = trained_params()
    dparams = draft_params()
    rng = np.random.RandomState(0)
    lengths = (1, 3, 4, 5, 7, 8, 9, 12, 13)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=n), np.int32)
        for n in lengths
    ]
    prompts.append(prompts[6].copy())  # duplicate: prefix-cache hit
    budgets = [5, 4, 6, 3, 5, 4, 6, 5, 4, 5]
    expected = {
        i: oracle(params, p, n)
        for i, (p, n) in enumerate(zip(prompts, budgets))
    }
    plain = make_paged(params)
    assert plain.run(prompts, budgets) == expected
    plain.assert_page_accounting()
    for kw in (
        dict(k=1),
        dict(k=2, station_slots=2),
        dict(k=4, station_slots=4),
        dict(k=2, token_budget=9),
        dict(k=4, station_slots=2, token_budget=12),
    ):
        k = kw.pop("k")
        cb = make_spec_paged(params, dparams, k, **kw)
        got = cb.run(prompts, budgets)
        assert got == expected, (k, kw, {
            i: (got[i], expected[i])
            for i in expected if got[i] != expected[i]
        })
        cb.assert_page_accounting()
        assert cb.stats["spec_steps"] > 0
        # the duplicate prompt still hits its twin's registered pages:
        # speculation must not break prefix sharing (windows write only
        # private pages — sharable pages end below the first decode row)
        assert cb.stats["prefix_hit_tokens"] >= 8, (k, kw)
    # perfect draft (the target itself): the all-accept path — same
    # tokens, strictly fewer verify programs than the hopeless draft
    hopeless = make_spec_paged(params, dparams, 4)
    assert hopeless.run(prompts, budgets) == expected
    perfect = make_paged(
        params, draft_params=params, speculate_k=4,
        draft_num_layers=CFG["num_layers"],
        draft_num_heads=CFG["num_heads"], draft_hidden=CFG["hidden"],
    )
    assert perfect.run(prompts, budgets) == expected
    assert perfect.stats["spec_steps"] < hopeless.stats["spec_steps"]
    # ...and the hopeless draft still advances ≥1 token per verify
    assert hopeless.stats["spec_tokens"] >= hopeless.stats["spec_steps"]


def test_spec_paged_eos_early_exit_and_budget_cap():
    """A window may carry tokens past EOS or past the slot's remaining
    budget: the surplus must be dropped exactly like the non-speculative
    batcher drops it (stream truncated at EOS; remaining never goes
    negative), and the pages of retired sequences must balance."""
    params = trained_params()
    dparams = draft_params()
    rng = np.random.RandomState(1)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=n), np.int32)
        for n in (3, 5, 7, 4)
    ]
    budgets = [6, 9, 4, 8]
    for eos in (None, 7, 0):
        plain = make_paged(params, eos_id=eos)
        expected = plain.run(prompts, budgets)
        plain.assert_page_accounting()
        for k in (1, 3):
            cb = make_spec_paged(params, dparams, k, eos_id=eos)
            got = cb.run(prompts, budgets)
            assert got == expected, (eos, k)
            cb.assert_page_accounting()
            for i, toks in got.items():
                assert len(toks) <= budgets[i]
                if eos is not None and eos in toks:
                    assert toks.index(eos) == len(toks) - 1


def test_spec_paged_incremental_api_with_cancel():
    """submit/serve_step/cancel churn: cancelling a mid-decode
    speculative sequence frees its pages (junk window writes on the dead
    slot touch only pages the sequence owned), and the survivors' tokens
    stay oracle-exact."""
    params = trained_params()
    dparams = draft_params()
    rng = np.random.RandomState(2)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=n), np.int32)
        for n in (4, 6, 9, 5)
    ]
    cb = make_spec_paged(params, dparams, 2)
    for i, p in enumerate(prompts):
        cb.submit(i, p, 8)
    # let prefill/first windows run, then kill seq 1 mid-flight
    done = {}
    for _ in range(3):
        done.update(cb.serve_step())
    assert cb.cancel(1)
    while cb.has_work():
        done.update(cb.serve_step())
    assert 1 not in done
    for i in (0, 2, 3):
        assert done[i] == oracle(params, prompts[i], 8), i
    cb.assert_page_accounting()


# ---------------------------------------------------------------------------
# Draft ring: the dense slots x max_seq draft cache became a ring
# ---------------------------------------------------------------------------

def test_draft_ring_window_token_identical_across_wraps():
    """A draft ring barely above the validation floor wraps repeatedly
    on long generations (the draft restarts its context at row 0); the
    emitted stream must stay oracle-exact anyway — greedy verification
    is lossless for ANY draft — and the ring only moves how many verify
    programs the stream costs."""
    params = trained_params()
    dparams = draft_params()
    rng = np.random.RandomState(7)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=n), np.int32)
        for n in (3, 7, 12, 5)
    ]
    budgets = [14, 10, 12, 16]
    expected = {
        i: oracle(params, p, n)
        for i, (p, n) in enumerate(zip(prompts, budgets))
    }
    for k, window in ((2, 19), (4, 21), (2, 32)):
        cb = make_spec_paged(params, dparams, k, draft_window=window)
        assert cb.draft_window == window
        got = cb.run(prompts, budgets)
        assert got == expected, (k, window, {
            i: (got[i], expected[i])
            for i in expected if got[i] != expected[i]
        })
        cb.assert_page_accounting()
        if window < 32:  # streams reach 19+ rows: the tight rings wrap
            assert cb.stats["draft_wraps"] > 0, (k, window)
    # perfect draft through a wrapping ring: still token-exact (the
    # wrap only dents the accept rate while context rebuilds)
    perfect = make_paged(
        params, draft_params=params, speculate_k=2, draft_window=19,
        draft_num_layers=CFG["num_layers"],
        draft_num_heads=CFG["num_heads"], draft_hidden=CFG["hidden"],
    )
    assert perfect.run(prompts, budgets) == expected
    assert perfect.stats["draft_wraps"] > 0


def test_draft_ring_validation_and_default():
    params = trained_params()
    dparams = draft_params()
    # floor: prompt_pad + k + 1 (admit prefill + one verify window)
    with pytest.raises(ValueError, match="draft_window"):
        make_spec_paged(params, dparams, 2, draft_window=18)
    with pytest.raises(ValueError, match="draft_window"):
        make_spec_paged(params, dparams, 2, draft_window=64)  # > max_seq
    # default: min(max_seq, prompt_pad + 16*(k+1)) — here max_seq wins
    cb = make_spec_paged(params, dparams, 2)
    assert cb.draft_window == CFG["max_seq"]
    # the ring IS the draft cache's row count
    assert cb.d_caches[0][0].shape[1] == cb.draft_window
    tight = make_spec_paged(params, dparams, 2, draft_window=20)
    assert tight.d_caches[0][0].shape[1] == 20


def test_draft_ring_gauge_and_compile_stability():
    """The ring exposes its memory shape as ``serve_draft_cache_rows``
    (slots x draft_window), and wrap resets never mint new programs —
    the write head is a traced argument like pos."""
    params = trained_params()
    dparams = draft_params()
    m = Metrics()
    cb = make_spec_paged(params, dparams, 2, draft_window=19, metrics=m)
    rng = np.random.RandomState(8)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=n), np.int32)
        for n in (4, 9)
    ]
    cb.run(prompts, [14, 12])
    assert cb.stats["draft_wraps"] > 0
    assert m.gauge("serve_draft_cache_rows") == 4 * 19.0
    assert "# TYPE serve_draft_cache_rows gauge" in m.render()
    cb.assert_page_accounting()
    for name in ("_spec_draft", "_spec_verify", "_draft_admit"):
        assert getattr(cb, name)._cache_size() == 1, (
            f"{name}: {getattr(cb, name)._cache_size()} compiled entries"
        )


# ---------------------------------------------------------------------------
# Guards: construction and submission contracts
# ---------------------------------------------------------------------------

def test_spec_paged_guards():
    params = trained_params()
    dparams = draft_params()
    with pytest.raises(ValueError, match="speculate_k"):
        make_spec_paged(params, dparams, 0)
    with pytest.raises(ValueError, match="draft model"):
        make_paged(params, speculate_k=2)
    cb = make_spec_paged(params, dparams, 2)
    # greedy-only: lossless speculative SAMPLING is a different program
    with pytest.raises(ValueError, match="greedy-only"):
        cb.submit(0, np.array([1, 2], np.int32), 4, temperature=0.7)
    # k rows of cache headroom beyond the dense bound (max_seq 32)
    with pytest.raises(ValueError, match="headroom"):
        cb.submit(1, np.array([1, 2, 3], np.int32), 28)
    # the same request is fine without speculation
    make_paged(params).submit(1, np.array([1, 2, 3], np.int32), 28)


# ---------------------------------------------------------------------------
# Dense spec batcher: the r5 divergence traffic, fp32 regression
# ---------------------------------------------------------------------------

def test_dense_spec_batcher_matches_dense_batcher_under_churn():
    """The EXACT traffic shape that exposed ``spec_serving_match_dense:
    false`` (16 mixed-budget prompts through 8 slots, multi-hundred-token
    budgets, slot churn), held to token-identity at fp32 — where the
    numerics class guarantees the host algorithm shows through.  Guards
    the retire/admit/budget bookkeeping against regressions; the bf16
    tie-flip class is bench-instrumented (margins), not tested here."""
    from kubegpu_tpu.models.spec_serving import SpeculativeContinuousBatcher

    cfg = dict(
        vocab_size=128, num_layers=2, num_heads=2, hidden=32, max_seq=128
    )
    params = TransformerLM(dtype=jnp.float32, **cfg).init(
        jax.random.PRNGKey(3), jnp.ones((1, 8), jnp.int32)
    )["params"]
    dp = TransformerLM(
        vocab_size=128, num_layers=1, num_heads=2, hidden=16, max_seq=128,
        dtype=jnp.float32,
    ).init(jax.random.PRNGKey(9), jnp.ones((1, 8), jnp.int32))["params"]
    rs = np.random.RandomState(1)
    budgets = [(8, 16, 24, 40)[i % 4] for i in range(16)]
    prompts = [
        np.asarray(rs.randint(0, 128, size=rs.randint(4, 16)), np.int32)
        for _ in range(16)
    ]
    dense = ContinuousBatcher(
        params, slots=8, prompt_pad=16, dtype=jnp.float32, **cfg
    ).run(prompts, budgets)
    spec = SpeculativeContinuousBatcher(
        params, dp, k=4, slots=8, prompt_pad=16,
        draft_num_layers=1, draft_num_heads=2, draft_hidden=16,
        dtype=jnp.float32, **cfg,
    ).run(prompts, budgets)
    assert spec == dense, {
        i: (dense[i][:6], spec[i][:6])
        for i in dense if spec.get(i) != dense[i]
    }


# ---------------------------------------------------------------------------
# Soak: kill schedule with speculation on — no page leaked by drafts
# ---------------------------------------------------------------------------

def test_gateway_soak_kill_schedule_with_speculation():
    """GatewaySoak's kill/revive/hedge schedule over SPECULATIVE paged
    batchers: invariant I5 (served exactly once or explicitly rejected)
    plus assert_page_accounting on every surviving replica — rejected
    draft tails must never leak pool pages (rollback is don't-commit;
    the junk rows live in pages the sequence already owns)."""
    from kubegpu_tpu.testing.soak import GatewaySoak

    tiny = dict(vocab_size=61, num_layers=1, num_heads=2, hidden=16,
                max_seq=24)
    params = TransformerLM(dtype=jnp.float32, **tiny).init(
        jax.random.PRNGKey(1), jnp.ones((1, 4), jnp.int32)
    )["params"]
    soak = GatewaySoak(
        # workload prompts must fit the replicas' prompt_pad below
        seed=17, n_replicas=2, follow_prompt_cap=4,
        batcher_factory=lambda key: PagedContinuousBatcher(
            params, slots=4, prompt_pad=4, page_size=4, pool_pages=24,
            station_slots=2, token_budget=8, dtype=jnp.float32,
            draft_params=params, speculate_k=2,
            draft_num_layers=tiny["num_layers"],
            draft_num_heads=tiny["num_heads"],
            draft_hidden=tiny["hidden"], **tiny,
        ),
    )
    soak.run(steps=18)


# ---------------------------------------------------------------------------
# Compile stability: speculation mints exactly three programs, once each
# ---------------------------------------------------------------------------

def test_spec_compile_stability_fixed_jit_cache():
    """A varied schedule — mixed lengths, cache hits, cancels, zero-
    budget admits, EOS retirements, partial station occupancy — leaves
    exactly ONE compiled entry for each speculative program
    (draft-admit, draft scan, verify) and for the station programs; the
    plain step program is never traced while speculation is on."""
    params = trained_params()
    dparams = draft_params()
    rng = np.random.RandomState(5)
    cb = make_spec_paged(params, dparams, 2, station_slots=2,
                         token_budget=11, eos_id=3)
    seq = 0
    live = []
    for _ in range(40):
        roll = rng.rand()
        if roll < 0.5:
            n = int(rng.randint(1, 13))
            max_new = int(rng.randint(0, 5))
            prompt = (
                np.arange(n, dtype=np.int32) % 7 if roll < 0.1
                else np.array(
                    rng.randint(0, CFG["vocab_size"], size=n), np.int32
                )
            )
            cb.submit(seq, prompt, max_new)
            live.append(seq)
            seq += 1
        elif roll < 0.6 and live:
            cb.cancel(live.pop(rng.randint(len(live))))
        else:
            for s in cb.serve_step():
                live.remove(s)
    while cb.has_work():
        for s in cb.serve_step():
            live.remove(s)
    cb.assert_page_accounting()
    for name in ("_spec_draft", "_spec_verify", "_draft_admit", "_chunk"):
        assert getattr(cb, name)._cache_size() == 1, (
            f"{name}: {getattr(cb, name)._cache_size()} compiled entries"
        )
    # bucketed multi-page programs: one compiled entry per padded width
    assert cb._write_pages, "no multi-page scatter ran"
    for w, fn in cb._write_pages.items():
        assert fn._cache_size() == 1, f"scatter width {w} recompiled"
    for w, fn in cb._gather_pages.items():
        assert fn._cache_size() == 1, f"gather width {w} recompiled"
    assert cb._step._cache_size() == 0, "plain step traced under speculation"


# ---------------------------------------------------------------------------
# Metrics: serve_spec_* in the shared exposition format
# ---------------------------------------------------------------------------

def test_spec_metrics_exposition():
    """The speculative batcher observes accept-rate, tokens-per-step and
    the draft/verify phase timers into the SHARED registry, and they
    render in the Prometheus text format next to the serving histograms."""
    params = trained_params()
    dparams = draft_params()
    m = Metrics()
    cb = make_spec_paged(params, dparams, 2, metrics=m)
    rng = np.random.RandomState(6)
    prompts = [
        np.array(rng.randint(0, CFG["vocab_size"], size=n), np.int32)
        for n in (4, 7)
    ]
    out = cb.run(prompts, [6, 5])
    assert sum(len(v) for v in out.values()) == 11
    assert m.histogram_count("serve_spec_accept_rate", mode="greedy") > 0
    assert m.histogram_count("serve_spec_draft_seconds") > 0
    assert m.histogram_count("serve_spec_verify_seconds") > 0
    assert m.get("serve_spec_tokens_per_step") == 11.0
    assert m.get("serve_spec_steps_total") == cb.stats["spec_steps"]
    # accept rate is a fraction of k: every sample within [0, 1]
    assert 0.0 <= m.histogram_sum(
        "serve_spec_accept_rate", mode="greedy"
    ) <= m.histogram_count("serve_spec_accept_rate", mode="greedy")
    text = m.render()
    for name in ("serve_spec_accept_rate", "serve_spec_draft_seconds",
                 "serve_spec_verify_seconds"):
        assert f"{name}_count" in text, name
    assert "serve_spec_tokens_per_step 11" in text
    # the non-speculative emit path still feeds TTFT/ITL
    assert m.histogram_count("serve_ttft_seconds") == 2
