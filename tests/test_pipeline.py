"""Pipeline parallelism tests (parallel/pipeline.py + models/pipeline_lm.py).

The oracle is sequential_lm_logits — identical math, no pipelining — so the
GPipe schedule (microbatch streaming, bubble masking, ppermute hops, psum
broadcast) must reproduce it exactly in fp32 on the 8-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from kubegpu_tpu.models.pipeline_lm import (
    init_pipeline_lm,
    make_pipeline_lm_train_step,
    pipeline_lm_logits,
    place_pipeline_lm,
    sequential_lm_logits,
)
from kubegpu_tpu.parallel import device_mesh
from kubegpu_tpu.parallel.pipeline import pipeline_apply

pytestmark = pytest.mark.slow  # JAX compile-heavy; run with -m slow


def _mesh(n):
    return device_mesh({"pipe": n}, devices=jax.devices()[:n])


def test_pipeline_apply_matches_sequential_stage_chain():
    """Generic engine: y = f_{S-1}(...f_0(x)) for a toy affine stage."""
    S, M = 4, 3
    mesh = _mesh(S)
    w = jax.random.normal(jax.random.PRNGKey(0), (S, 8, 8)) * 0.3

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    stream = jax.random.normal(jax.random.PRNGKey(1), (M, 2, 8))
    out = pipeline_apply(stage_fn, mesh)({"w": w}, stream)

    expected = stream
    for s in range(S):
        expected = jnp.tanh(expected @ w[s])
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("stages,layers_per_stage,micro", [(4, 2, 4), (8, 1, 2)])
def test_pipeline_lm_matches_sequential(stages, layers_per_stage, micro):
    mesh = _mesh(stages)
    params = init_pipeline_lm(
        jax.random.PRNGKey(0), vocab_size=64, num_stages=stages,
        layers_per_stage=layers_per_stage, hidden=16, max_seq=32,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)

    got = pipeline_lm_logits(params, tokens, mesh, num_heads=2,
                             num_microbatches=micro)
    want = sequential_lm_logits(params, tokens, num_heads=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_pipeline_lm_rejects_indivisible_microbatching():
    mesh = _mesh(2)
    params = init_pipeline_lm(
        jax.random.PRNGKey(0), vocab_size=16, num_stages=2,
        layers_per_stage=1, hidden=8, max_seq=16,
    )
    tokens = jnp.ones((3, 8), jnp.int32)
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_lm_logits(params, tokens, mesh, num_heads=2,
                           num_microbatches=2)


@pytest.mark.exhaustive
def test_pipeline_grads_match_sequential():
    """The GPipe backward schedule must produce the SAME gradients as the
    unpipelined model — including for stage 0 (gradient crosses every
    ppermute transpose)."""
    mesh = _mesh(4)
    params = init_pipeline_lm(
        jax.random.PRNGKey(0), vocab_size=32, num_stages=4,
        layers_per_stage=1, hidden=8, max_seq=16,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 9), 0, 32)

    def xent(logits, tgt):
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, tgt[..., None], axis=-1))

    g_pipe = jax.grad(
        lambda p: xent(
            pipeline_lm_logits(p, tokens[:, :-1], mesh, num_heads=2,
                               num_microbatches=2),
            tokens[:, 1:],
        )
    )(params)
    g_seq = jax.grad(
        lambda p: xent(
            sequential_lm_logits(p, tokens[:, :-1], num_heads=2),
            tokens[:, 1:],
        )
    )(params)
    flat_p, _ = jax.tree_util.tree_flatten_with_path(g_pipe)
    flat_s = dict(jax.tree_util.tree_flatten_with_path(g_seq)[0])
    assert flat_p and len(flat_p) == len(flat_s)
    for path, leaf in flat_p:
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_s[tuple(path)]),
            rtol=2e-4, atol=1e-5, err_msg=str(path),
        )


def test_pipeline_train_step_learns():
    mesh = _mesh(4)
    params = init_pipeline_lm(
        jax.random.PRNGKey(0), vocab_size=32, num_stages=4,
        layers_per_stage=1, hidden=16, max_seq=16,
    )
    tx = optax.sgd(0.3)
    opt_state = tx.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 9), 0, 32)
    params, opt_state, tokens = place_pipeline_lm(params, opt_state, tokens, mesh)

    # placement: every blocks leaf (and its moments) sharded over pipe
    assert all(
        "pipe" in leaf.sharding.spec
        for leaf in jax.tree_util.tree_leaves(params["blocks"])
    )

    step = make_pipeline_lm_train_step(mesh, tx, num_heads=2, num_microbatches=2)
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


# -- circular / interleaved schedule (VERDICT r1 #8) ------------------------

def test_circular_apply_matches_sequential_stage_chain():
    """V=2 rounds over P=4 devices: 8 global stages; the wrap edge and slot
    buffer must chain them in stage order v*P + p."""
    P_, V, M = 4, 2, 4
    mesh = _mesh(P_)
    w = jax.random.normal(jax.random.PRNGKey(0), (V, P_, 8, 8)) * 0.3

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    stream = jax.random.normal(jax.random.PRNGKey(1), (M, 2, 8))
    out = pipeline_apply(stage_fn, mesh, num_rounds=V)({"w": w}, stream)

    expected = stream
    for v in range(V):
        for p in range(P_):
            expected = jnp.tanh(expected @ w[v, p])
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=1e-5, atol=1e-6)


def test_circular_rejects_fewer_microbatches_than_devices():
    mesh = _mesh(4)
    w = jnp.zeros((2, 4, 8, 8))
    stream = jnp.zeros((3, 2, 8))  # 3 microbatches < 4 devices

    def stage_fn(p, x):
        return x @ p["w"]

    with pytest.raises(ValueError, match="microbatches >= devices"):
        pipeline_apply(stage_fn, mesh, num_rounds=2)({"w": w}, stream)


@pytest.mark.parametrize(
    "micro", [4, pytest.param(6, marks=pytest.mark.exhaustive)]
)
def test_circular_lm_matches_sequential(micro):
    from kubegpu_tpu.models.pipeline_lm import to_circular_layout

    P_, V = 4, 2
    mesh = _mesh(P_)
    params = init_pipeline_lm(
        jax.random.PRNGKey(0), vocab_size=64, num_stages=P_ * V,
        layers_per_stage=1, hidden=16, max_seq=64,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(1), (micro * 2, 24), 0, 64)
    ref = sequential_lm_logits(params, tokens, num_heads=2)
    circ = to_circular_layout(params, P_)
    out = pipeline_lm_logits(
        circ, tokens, mesh, num_heads=2, num_microbatches=micro, num_rounds=V
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.exhaustive
def test_circular_grads_match_sequential():
    from kubegpu_tpu.models.pipeline_lm import to_circular_layout

    P_, V = 4, 2
    mesh = _mesh(P_)
    params = init_pipeline_lm(
        jax.random.PRNGKey(0), vocab_size=32, num_stages=P_ * V,
        layers_per_stage=1, hidden=16, max_seq=32,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0, 32)
    circ = to_circular_layout(params, P_)

    def loss_p(p):
        out = pipeline_lm_logits(
            p, tokens, mesh, num_heads=2, num_microbatches=4, num_rounds=V
        )
        return jnp.mean(out ** 2)

    def loss_s(p):
        return jnp.mean(sequential_lm_logits(p, tokens, num_heads=2) ** 2)

    gp = jax.grad(loss_p)(circ)
    gs = jax.grad(loss_s)(params)
    # compare in the flat stage-order layout
    gp_flat = jax.tree.map(
        lambda a: a.reshape((P_ * V,) + a.shape[2:]), gp["blocks"]
    )
    for k in gs["blocks"]:
        np.testing.assert_allclose(
            np.asarray(gp_flat[k]), np.asarray(gs["blocks"][k]),
            rtol=5e-4, atol=5e-4,
        )


def test_circular_train_step_runs_and_bubble_shrinks():
    from kubegpu_tpu.models.pipeline_lm import to_circular_layout
    from kubegpu_tpu.parallel.pipeline import bubble_fraction

    P_, V, M = 4, 2, 4
    mesh = _mesh(P_)
    params = init_pipeline_lm(
        jax.random.PRNGKey(0), vocab_size=64, num_stages=P_ * V,
        layers_per_stage=1, hidden=16, max_seq=64,
    )
    circ = to_circular_layout(params, P_)
    tx = optax.sgd(0.1)
    opt = tx.init(circ)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0, 64)
    circ, opt, tokens = place_pipeline_lm(circ, opt, tokens, mesh, num_rounds=V)
    step = make_pipeline_lm_train_step(
        mesh, tx, num_heads=2, num_microbatches=M, num_rounds=V
    )
    circ, opt, loss = step(circ, opt, tokens)
    assert np.isfinite(float(loss))

    # the schedule's whole point, reported: same stage count at V=2 halves
    # (nearly) the idle fraction vs GPipe over P_*V devices
    b_gpipe = bubble_fraction(M, P_ * V, 1)
    b_circ = bubble_fraction(M, P_, V)
    assert b_circ < b_gpipe
    print(f"bubble: gpipe(P={P_*V})={b_gpipe:.3f} circular(P={P_},V={V})={b_circ:.3f}")


# -- PP x TP composition ----------------------------------------------------

@pytest.mark.exhaustive
def test_pp_tp_matches_sequential():
    """GPipe over "pipe" x Megatron TP over "model" on a (4, 2) mesh: each
    stage's kernels are column/row-parallel with in-stage psums; logits
    must match the unsharded sequential oracle."""
    mesh = device_mesh({"pipe": 4, "model": 2})
    params = init_pipeline_lm(
        jax.random.PRNGKey(0), vocab_size=64, num_stages=4,
        layers_per_stage=2, hidden=16, max_seq=64,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 24), 0, 64)
    ref = sequential_lm_logits(params, tokens, num_heads=2)
    out = pipeline_lm_logits(
        params, tokens, mesh, num_heads=2, num_microbatches=4,
        model_axis="model",
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)

    # gradients too: a missing psum on the TP transpose path would keep
    # the forward exact and only corrupt the backward
    def loss_p(p):
        return jnp.mean(pipeline_lm_logits(
            p, tokens, mesh, num_heads=2, num_microbatches=4,
            model_axis="model",
        ) ** 2)

    def loss_s(p):
        return jnp.mean(sequential_lm_logits(p, tokens, num_heads=2) ** 2)

    gp = jax.grad(loss_p)(params)
    gs = jax.grad(loss_s)(params)
    # the FULL tree, embed/pos/head included: their cotangents cross the
    # shard_map replication boundary, exactly where a TP-degree scaling
    # bug would hide while blocks grads stay exact
    for (kp, a), (_, b) in zip(
        jax.tree_util.tree_leaves_with_path(gp),
        jax.tree_util.tree_leaves_with_path(gs),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4,
            err_msg=jax.tree_util.keystr(kp),
        )


def test_pp_tp_train_step_runs_with_placed_state():
    mesh = device_mesh({"pipe": 4, "model": 2})
    params = init_pipeline_lm(
        jax.random.PRNGKey(0), vocab_size=64, num_stages=4,
        layers_per_stage=1, hidden=16, max_seq=64,
    )
    tx = optax.sgd(0.1)
    opt = tx.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0, 64)
    params, opt, tokens = place_pipeline_lm(
        params, opt, tokens, mesh, model_axis="model"
    )
    step = make_pipeline_lm_train_step(
        mesh, tx, num_heads=2, num_microbatches=4, model_axis="model"
    )
    params, opt, loss = step(params, opt, tokens)
    assert np.isfinite(float(loss))
    # TP sharding actually landed: a column kernel's last dim is split
    wq_shard = params["blocks"]["wq"].sharding.spec
    assert wq_shard == ("pipe", None, None, "model")


def test_pp_tp_rejects_circular():
    mesh = device_mesh({"pipe": 4, "model": 2})
    params = init_pipeline_lm(
        jax.random.PRNGKey(0), vocab_size=64, num_stages=8,
        layers_per_stage=1, hidden=16, max_seq=64,
    )
    from kubegpu_tpu.models.pipeline_lm import to_circular_layout

    circ = to_circular_layout(params, 4)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 24), 0, 64)
    with pytest.raises(ValueError, match="GPipe schedule only"):
        pipeline_lm_logits(
            circ, tokens, mesh, num_heads=2, num_microbatches=4,
            num_rounds=2, model_axis="model",
        )
