"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding/mesh tests run
on 8 virtual CPU devices (the driver separately dry-run-compiles the
multi-chip path via __graft_entry__.dryrun_multichip).

Note: this environment's sitecustomize may import jax at interpreter start
with a TPU platform pinned, so setting env vars alone is not enough —
jax.config.update('jax_platforms', ...) before first backend use is the
reliable switch (backends initialize lazily on first device query)."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:  # pure control-plane environments without jax
    pass
