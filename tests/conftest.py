"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding/mesh tests run on
8 virtual CPU devices (the driver separately dry-run-compiles the multi-chip
path via __graft_entry__.dryrun_multichip)."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
