"""DeviceScheduler plugin registry (SURVEY.md §2 #5, §3.5 plugin loading).

A second, non-TPU device type rides the whole control-plane loop: generic
grouped-capacity advertisement -> treefit-backed filter/prioritize ->
bind with grouped bindings in the assignment annotation -> cache bookkeeping
-> restart replay -> release on delete.  The TPU path stays the built-in
first-priority plugin.
"""

import sys
import types

import pytest

from kubegpu_tpu.scheduler import Scheduler
from kubegpu_tpu.scheduler.plugins import (
    DeviceSchedulerPlugin,
    GroupedResourceScheduler,
    PluginRegistry,
    TpuDeviceScheduler,
    default_registry,
)
from kubegpu_tpu.types import annotations
from kubegpu_tpu.types.info import PodInfo
from kubegpu_tpu.types.resource import RES_TPU, ResourcePath, ResourceTree
from kubegpu_tpu.utils.apiserver import InMemoryApiServer

RES_NPU = "example.com/npu"
NPU_TEMPLATE = "npugrp/*/npu/*/dev"


def npu_plugin() -> GroupedResourceScheduler:
    return GroupedResourceScheduler("npu", RES_NPU, NPU_TEMPLATE)


def npu_capacity(groups: int = 2, per_group: int = 2) -> ResourceTree:
    t = ResourceTree()
    for g in range(groups):
        for d in range(per_group):
            t.add(ResourcePath.parse(f"npugrp/{g}/npu/{d}/dev"), 1)
    return t


def npu_node(api: InMemoryApiServer, name: str = "npu-node-0", **kw) -> None:
    api.add_node({"metadata": {"name": name, "annotations": {}}})
    api.patch_node_annotations(
        name,
        {
            annotations.NODE_GROUPED_CAPACITY: annotations.encode_grouped_capacity(
                npu_capacity(**kw)
            )
        },
    )


def npu_pod(name: str, want: int) -> dict:
    return {
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "containers": [
                {"name": "main", "resources": {"limits": {RES_NPU: str(want)}}}
            ]
        },
    }


# ---------------------------------------------------------------------------
# registry mechanics
# ---------------------------------------------------------------------------

def test_default_registry_owns_tpu_pods_only():
    reg = default_registry()
    tpu_pod = annotations.pod_from_k8s(
        {
            "metadata": {"name": "t"},
            "spec": {
                "containers": [
                    {"name": "m", "resources": {"limits": {RES_TPU: "2"}}}
                ]
            },
        }
    )
    cpu_pod = annotations.pod_from_k8s(
        {"metadata": {"name": "c"}, "spec": {"containers": [{"name": "m"}]}}
    )
    assert reg.plugin_for(tpu_pod).name == "tpu"
    assert reg.plugin_for(cpu_pod) is None


def test_registration_order_is_precedence():
    reg = default_registry()
    reg.register(npu_plugin())
    both = annotations.pod_from_k8s(
        {
            "metadata": {"name": "b"},
            "spec": {
                "containers": [
                    {
                        "name": "m",
                        "resources": {"limits": {RES_TPU: "1", RES_NPU: "1"}},
                    }
                ]
            },
        }
    )
    assert reg.plugin_for(both).name == "tpu"  # tpu registered first


def test_duplicate_name_rejected():
    reg = default_registry()
    with pytest.raises(ValueError):
        reg.register(TpuDeviceScheduler())


def test_dynamic_load_via_entry_symbol():
    mod = types.ModuleType("fake_device_plugin")
    mod.create_device_scheduler_plugin = npu_plugin
    sys.modules["fake_device_plugin"] = mod
    try:
        reg = default_registry()
        p = reg.load("fake_device_plugin")
        assert p.name == "npu" and reg.names() == ["tpu", "npu"]
    finally:
        del sys.modules["fake_device_plugin"]


def test_dynamic_load_rejects_non_plugin():
    mod = types.ModuleType("bad_device_plugin")
    mod.create_device_scheduler_plugin = lambda: object()
    sys.modules["bad_device_plugin"] = mod
    try:
        with pytest.raises(TypeError):
            PluginRegistry().load("bad_device_plugin")
    finally:
        del sys.modules["bad_device_plugin"]


# ---------------------------------------------------------------------------
# generic device type end-to-end through the scheduler verbs
# ---------------------------------------------------------------------------

def make_sched(api: InMemoryApiServer) -> Scheduler:
    reg = default_registry()
    reg.register(npu_plugin())
    s = Scheduler(api, plugins=reg)
    s.cache.refresh()
    return s


def test_generic_filter_prioritize_bind_and_bookkeeping():
    api = InMemoryApiServer()
    npu_node(api)  # 2 groups x 2 devs = 4 NPUs
    api.add_node({"metadata": {"name": "plain-node", "annotations": {}}})
    sched = make_sched(api)

    api.create_pod(npu_pod("p1", 2))
    r = sched.filter(api.get_pod("default", "p1"), ["npu-node-0", "plain-node"])
    assert r.nodes == ["npu-node-0"]
    assert "plain-node" in r.failed

    scores = dict(sched.prioritize(api.get_pod("default", "p1"), ["npu-node-0"]))
    assert scores["npu-node-0"] > 0

    assert sched.bind("default", "p1", "npu-node-0") is None
    a = annotations.assignment_from_pod(api.get_pod("default", "p1"))
    assert a is not None and a.node == "npu-node-0" and not a.all_chips()
    assert sum(a.grouped_totals().values()) == 2

    node = sched.cache.node("npu-node-0")
    assert node.used.total("dev") == 2

    # only 2 NPUs left: a 3-NPU pod must not fit
    api.create_pod(npu_pod("p2", 3))
    r2 = sched.filter(api.get_pod("default", "p2"), ["npu-node-0"])
    assert not r2.nodes
    # ...but a 2-NPU pod still does
    api.create_pod(npu_pod("p3", 2))
    r3 = sched.filter(api.get_pod("default", "p3"), ["npu-node-0"])
    assert r3.nodes == ["npu-node-0"]
    assert sched.bind("default", "p3", "npu-node-0") is None
    assert sched.cache.node("npu-node-0").used.total("dev") == 4


def test_generic_release_on_delete():
    api = InMemoryApiServer()
    npu_node(api)
    sched = make_sched(api)
    api.create_pod(npu_pod("p1", 4))
    assert sched.filter(api.get_pod("default", "p1"), ["npu-node-0"]).nodes
    assert sched.bind("default", "p1", "npu-node-0") is None
    assert sched.cache.node("npu-node-0").used.total("dev") == 4

    obj = api.get_pod("default", "p1")
    api.delete_pod("default", "p1")
    sched.on_pod_deleted(obj)
    assert sched.cache.node("npu-node-0").used.total("dev") == 0


def test_generic_assignment_survives_restart_replay():
    api = InMemoryApiServer()
    npu_node(api)
    sched = make_sched(api)
    api.create_pod(npu_pod("p1", 3))
    assert sched.filter(api.get_pod("default", "p1"), ["npu-node-0"]).nodes
    assert sched.bind("default", "p1", "npu-node-0") is None

    fresh = make_sched(api)  # new scheduler, same API server
    assert fresh.cache.node("npu-node-0").used.total("dev") == 3
    # remaining capacity is exactly 1
    api.create_pod(npu_pod("p2", 1))
    assert fresh.filter(api.get_pod("default", "p2"), ["npu-node-0"]).nodes
    api.create_pod(npu_pod("p3", 2))
    assert not fresh.filter(api.get_pod("default", "p3"), ["npu-node-0"]).nodes


def test_generic_bind_race_detected():
    """Two schedulers over one API server: the loser's bind must fail
    cleanly (take validates before mutating)."""
    api = InMemoryApiServer()
    npu_node(api)  # 4 NPUs
    s1 = make_sched(api)
    s2 = make_sched(api)
    api.create_pod(npu_pod("p1", 3))
    api.create_pod(npu_pod("p2", 3))
    assert s1.filter(api.get_pod("default", "p1"), ["npu-node-0"]).nodes
    assert s2.filter(api.get_pod("default", "p2"), ["npu-node-0"]).nodes
    assert s1.bind("default", "p1", "npu-node-0") is None
    # s2's stale cache still thinks 4 are free; refresh inside bind path
    # is NOT automatic — the annotation replay on refresh() is
    s2.cache.refresh()
    err = s2.bind("default", "p2", "npu-node-0")
    assert err is not None


def test_multi_container_generic_pod_binds_distinct_units():
    api = InMemoryApiServer()
    npu_node(api)  # 4 NPUs
    sched = make_sched(api)
    api.create_pod(
        {
            "metadata": {"name": "mc", "namespace": "default"},
            "spec": {
                "containers": [
                    {"name": "a", "resources": {"limits": {RES_NPU: "2"}}},
                    {"name": "b", "resources": {"limits": {RES_NPU: "2"}}},
                ]
            },
        }
    )
    assert sched.filter(api.get_pod("default", "mc"), ["npu-node-0"]).nodes
    assert sched.bind("default", "mc", "npu-node-0") is None
    a = annotations.assignment_from_pod(api.get_pod("default", "mc"))
    # each container got 2, and no unit is double-bound across containers
    assert sorted(a.grouped) == ["a", "b"]
    seen = {}
    for c, pairs in a.grouped.items():
        for path, qty in pairs:
            seen[path] = seen.get(path, 0) + qty
    assert sum(seen.values()) == 4
    assert all(q == 1 for q in seen.values())  # 4 distinct single-unit devs


def test_mixed_device_type_pod_rejected_not_overcommitted():
    """A pod mixing device types must be rejected outright: fitting only
    the first type would silently over-commit the second."""
    api = InMemoryApiServer()
    npu_node(api)
    sched = make_sched(api)
    api.create_pod(
        {
            "metadata": {"name": "mix", "namespace": "default"},
            "spec": {
                "containers": [
                    {
                        "name": "m",
                        "resources": {"limits": {RES_TPU: "1", RES_NPU: "2"}},
                    }
                ]
            },
        }
    )
    r = sched.filter(api.get_pod("default", "mix"), ["npu-node-0"])
    assert not r.nodes
    assert "multiple device types" in r.failed["npu-node-0"]
    err = sched.bind("default", "mix", "npu-node-0")
    assert err is not None and "multiple device types" in err
    # nothing was committed anywhere
    assert sched.cache.node("npu-node-0").used.total("dev") == 0


def test_malformed_grouped_capacity_keeps_tpu_topology():
    """A broken generic-capacity annotation must not drop the node's TPU
    topology from the cache."""
    from kubegpu_tpu.plugins import Advertiser, FakeSlice

    api = InMemoryApiServer()
    fs = FakeSlice(slice_id="s0", mesh_shape=(2, 2), host_block=(2, 2))
    for host, prov in fs.providers().items():
        Advertiser(prov, api).advertise_once()
    host = fs.hosts()[0]
    api.patch_node_annotations(
        host, {annotations.NODE_GROUPED_CAPACITY: "{not json"}
    )
    sched = make_sched(api)
    node = sched.cache.node(host)
    assert node is not None and node.is_tpu_node  # TPU tree survived


def test_tpu_path_unchanged_with_extra_plugins_registered():
    from kubegpu_tpu.plugins import Advertiser, FakeSlice

    api = InMemoryApiServer()
    fs = FakeSlice(slice_id="s0", mesh_shape=(4, 4), host_block=(2, 2))
    for host, prov in fs.providers().items():
        Advertiser(prov, api).advertise_once()
    sched = make_sched(api)
    api.create_pod(
        {
            "metadata": {"name": "t1", "namespace": "default"},
            "spec": {
                "containers": [
                    {"name": "m", "resources": {"limits": {RES_TPU: "4"}}}
                ]
            },
        }
    )
    nodes = sorted(n["metadata"]["name"] for n in api.list_nodes())
    r = sched.filter(api.get_pod("default", "t1"), nodes)
    assert r.nodes
    assert sched.bind("default", "t1", r.nodes[0]) is None
    a = annotations.assignment_from_pod(api.get_pod("default", "t1"))
    assert len(a.all_chips()) == 4 and not a.grouped
