"""Compilation-cache prewarm for the north-star path.

Run once per node/image rollout (init container or DaemonSet post-start
hook) with ``JAX_COMPILATION_CACHE_DIR`` pointed at a host-path volume:
compiles the flagship programs (ScanResNet-50 init + train step at the
sample's per-worker shapes) into the persistent XLA cache, so the FIRST
real job on the node takes the warm schedule→first-step path (~22 s
measured) instead of the cold one (~36 s).  bench.py's warm probe measures
exactly this configuration.

XLA cache keys include the device topology, and workers run with
TPU_VISIBLE_CHIPS restricted to their allocation — so prewarm must
compile under the SAME visibility a worker will have.  Pass
``--chips-per-worker`` (e.g. 1 for the north-star sample's 1-chip pods)
to restrict this process before backend init; run once per chip-count
shape your pods use.

    JAX_COMPILATION_CACHE_DIR=/var/cache/kubegpu-tpu-xla \
        python -m deploy.prewarm --batch 32 --chips-per-worker 1
"""

from __future__ import annotations

import argparse
import os
import time


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=32, help="per-worker batch")
    ap.add_argument("--classes", type=int, default=1000)
    ap.add_argument(
        "--chips-per-worker",
        type=int,
        default=0,
        help="restrict TPU_VISIBLE_CHIPS to this many chips so the cache "
        "key matches a worker pod's restricted visibility (0 = all chips)",
    )
    args = ap.parse_args(argv)

    if args.chips_per_worker > 0:
        # explicit flag OVERRIDES ambient env: an image/pod that already
        # exports full-host TPU_VISIBLE_CHIPS would otherwise silently
        # compile under the wrong visibility and never match a worker's
        # cache key — the exact failure this flag exists to prevent
        os.environ["TPU_VISIBLE_CHIPS"] = ",".join(
            str(i) for i in range(args.chips_per_worker)
        )

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)

    from kubegpu_tpu.models import ScanResNet50, create_train_state
    from kubegpu_tpu.models.train import make_resnet_train_step, place_resnet
    from kubegpu_tpu.parallel import device_mesh

    mesh = device_mesh({"data": jax.local_device_count()})
    model = ScanResNet50(num_classes=args.classes)
    rng = jax.random.PRNGKey(0)
    images = jnp.ones((args.batch, 224, 224, 3), jnp.float32)
    labels = jnp.zeros((args.batch,), jnp.int32)

    t0 = time.perf_counter()
    # EXACTLY the two programs a real job's first step needs, built the
    # same way (b1 init, b{batch} step) — and EXECUTED, not just
    # .compile()d: this backend defers real compilation to the first
    # execute, so only an executed step is guaranteed into the cache
    state = create_train_state(model, rng, images[:1])
    jax.block_until_ready(state.params)
    state, images, labels = place_resnet(state, (images, labels), mesh)
    step = make_resnet_train_step(mesh)
    state, loss = step(state, images, labels)
    float(loss)
    print(f"prewarm done in {time.perf_counter() - t0:.1f} s "
          f"(init + train step b{args.batch} compiled, executed, cached)")


if __name__ == "__main__":
    main()
