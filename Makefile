# Developer entry points.  Everything runs without TPUs (fake provider +
# 8-device virtual CPU mesh) except `bench`, which uses the real accelerator.

PY ?= python

.PHONY: test test-slow test-all native bench dryrun image clean

# fast half: control plane + wire protocols, seconds (default pytest run)
test: native
	$(PY) -m pytest tests/ -x -q

# slow half: JAX compile-heavy workload tests on the 8-dev CPU mesh (~15 min)
test-slow:
	$(PY) -m pytest tests/ -x -q -m slow

test-all: test test-slow

native:
	$(MAKE) -C native

bench:
	$(PY) bench.py

dryrun:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
	  $(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

image:
	docker build -f deploy/Dockerfile -t kubegpu-tpu:latest .

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
