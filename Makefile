# Developer entry points.  Everything runs without TPUs (fake provider +
# 8-device virtual CPU mesh) except `bench`, which uses the real accelerator.

PY ?= python

.PHONY: test test-mid test-slow test-all native bench dryrun image clean

# fast half: control plane + wire protocols, ~1 min (default pytest run)
test: native
	$(PY) -m pytest tests/ -x -q

# mid tier: the workload stack minus the multi-minute process-spawning /
# compile-exhaustive tests — the "re-verify models+parallelism" loop
test-mid:
	$(PY) -m pytest tests/ -x -q -m "slow and not exhaustive"

# everything marked slow, including the exhaustive tier (~25-30 min)
test-slow:
	$(PY) -m pytest tests/ -x -q -m slow

test-all: test test-slow

native:
	$(MAKE) -C native

bench:
	$(PY) bench.py

dryrun:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
	  $(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

image:
	docker build -f deploy/Dockerfile -t kubegpu-tpu:latest .

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
