# Developer entry points.  Everything runs without TPUs (fake provider +
# 8-device virtual CPU mesh) except `bench`, which uses the real accelerator.

PY ?= python
SHELL := /bin/bash

.PHONY: test tier1 test-mid test-slow test-all native bench bench-smoke multichip-smoke dryrun image clean

# fast half: control plane + wire protocols, ~1 min (default pytest run)
test: native
	$(PY) -m pytest tests/ -x -q

# the EXACT tier-1 verify command from ROADMAP.md (the driver's gate):
# unlike `test`, no -x (full run) and collection errors don't stop it
tier1:
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 870 env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q \
	  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
	  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; \
	rc=$${PIPESTATUS[0]}; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); \
	exit $$rc

# mid tier: the workload stack minus the multi-minute process-spawning /
# compile-exhaustive tests — the "re-verify models+parallelism" loop
test-mid:
	$(PY) -m pytest tests/ -x -q -m "slow and not exhaustive"

# everything marked slow, including the exhaustive tier (~25-30 min)
test-slow:
	$(PY) -m pytest tests/ -x -q -m slow

test-all: test test-slow

native:
	$(MAKE) -C native

bench:
	$(PY) bench.py

# CPU-only serving-path micro-bench (~2 min): TTFT/ITL p95 with chunked
# vs monolithic prefill, prefix-cache hit rate, burst TTFT p95
# batched-station vs serial, speculative vs plain paged decode tok/s,
# pipelined device-resident decode vs the synchronous host-driven
# baseline (same warm batcher, min-of-N interleaved, ledger
# host_ms/device_ms as the host-gap measurement), multi-turn session
# KV reuse (turn-2 TTFT decode-page cache vs prompt-only, <60 s on its
# own), request tracing (per-request phase spans must SUM to the
# measured TTFT within tolerance on the burst, and tracing overhead
# must stay within 5% tok/s of untraced on the same run), and the HTTP
# data plane (the same warm batcher served through the in-memory client
# vs the replica HTTP endpoint over loopback — token-identical, HTTP
# tok/s within a fixed 0.5x tolerance), and KV migration (a session's
# sealed chain exported/imported between warm batchers: restored
# re-pin TTFT strictly below the cold-restart re-pin, fp32
# token-identical, pages/s + wire bytes reported) on tiny shapes;
# exits non-zero
# if chunked ITL regresses >10% past monolithic (compute-bound tie on a
# 1-core box; the strict gate flaked at seed), hits vanish, the batched
# station's burst TTFT is not strictly below serial, spec decode is not
# strictly above plain, pipelined decode is not strictly above the sync
# baseline, turn-2 TTFT with decode-page caching is not strictly below
# prompt-only, tokens diverge on any of them (the HTTP lane included),
# the TTFT phase decomposition breaks, tracing overhead blows the 5%
# gate, the HTTP path falls past its tolerance, or the restored re-pin
# fails to beat (or match tokens with) the cold restart.  Also the
# gateway tier (serving_gateway_scaleout): 2 loopback gateways must
# clear 1.5x aggregate tok/s over 1 on the shared-workload mixed
# replay with fp32 token identity, and hedged-streaming p99 TTFT must
# be strictly below unhedged under an injected straggler.  Also the
# external session store (serving_store_failover): restored turn-2
# TTFT through the external store within 1.2x of the in-process
# backend on the same warm replicas, store-DOWN degradation bounded
# (cold + one fast breaker trip, never a deadline-length stall), fp32
# token identity across all three lanes.  Also the quantized page pool
# (serving_quantized_pool): at EQUAL pool byte budget the int8 pool
# must serve the same warm traffic strictly faster than the bf16 pool
# with >= 1.8x the effective rows, deterministic int8 streams, a
# token-identical export->import round trip at well under the bf16
# wire bytes, the fp32 full-width lane token-identical to the dense
# oracle, and an int8-pool soak kill schedule holding page accounting;
# token agreement / divergence margins / ppl delta are REPORTED
bench-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py --serve-smoke

# tensor-parallel paged serving on the 8-device CPU sim (~2 min):
# fp32 token identity TP=8 vs TP=1 (burst + speculation + multi-turn
# through sealed decode pages), pool-rows-per-replica scaling >= 4x at
# equal per-device memory budget, per-step collective bytes reported,
# and a GatewaySoak kill schedule over TP batchers holding page
# accounting at quiescence; exits non-zero on any gate
multichip-smoke:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
	  $(PY) bench.py --tp-smoke

# gateway smoke runs FIRST: it has no JAX-device dependency, so it still
# exercises the serving path in environments where the multichip dry run
# cannot (e.g. a jax build without the APIs the parallel stack needs).
# dryrun_tracing: serve a few traced requests, dump/reload the JSONL,
# assert one complete span tree each (the observability smoke).
# dryrun_http_serving: spawn a REAL replica subprocess (worker
# --serve-http), stream/cancel over loopback sockets, then SIGKILL it
# mid-stream — the distributed-data-plane smoke
# dryrun_sampled_spec_http: a --serving paged --speculate
# --sample-temperature worker subprocess; one seed-pinned SAMPLED
# stream rides rejection-verified speculation (wire-visible
# spec_steps), replays byte-identical on the same seed
# dryrun_kv_migration: TWO real replica subprocesses; a request streams
# on A, migrates mid-stream to B over the export/import verbs, A is
# SIGKILLed after the handoff — the stream must finish on B
# token-identical to a never-migrated reference
# dryrun_quantized_serving: TWO real replica subprocesses serving with
# --kv-dtype int8 — deterministic int8 streams, /v1/state advertising
# the per-dtype page-byte economy, and a mid-flight migration over the
# quantized (int8 pages + scales) wire schema, token-identical
# dryrun_gateway_tier: TWO gateways over one registry; a greedy stream's
# home gateway is KILLED mid-stream and the client retries on the
# survivor with the resume watermark — the stream completes via the
# survivor, token-identical, each token delivered exactly once
# dryrun_gateway_pods: the MULTI-PROCESS deployment — one external
# session-store subprocess + two real gateway subprocesses + one paged
# worker; the home gateway is SIGKILLed mid-stream (sibling completes
# exactly-once via the resume watermark), the worker cold-restarts and
# the session's next turn restores sealed KV from the EXTERNAL store
# (decode-page hits > 0, token-identical), and SIGTERM drains a gateway
# gracefully (readyz 503, live stream finishes, exit 0)
# dryrun_prefix_tier: the fleet-wide prefix tier over REAL processes —
# one store, two workers, two gateways each fronting ONE worker;
# replica A prefills an agent scaffold once, the sealed chain lands in
# the store under its content hash, and the COLD replica B imports it
# pre-prefill (decode-page hit tokens > 0, token-identical to the
# warm-local reference)
# dryrun_disaggregation: prefill/decode role split over REAL worker
# subprocesses — one spawned --role prefill, one --role decode; a
# RAG-length prompt seals and PARKS on the prefill replica (zero tokens
# streamed), hands off over the wire verbs to the decode replica, and
# the same attempt streams to the end token-identical to a co-located
# reference (prefill worker flipped to flex over POST /v1/role)
# dryrun_controller: the self-reshaping fleet over a REAL subprocess
# worker fleet — a surge's reconcile tick gang-schedules a second
# serving pod by preempting a batch pod (checkpoint-and-requeue), the
# launcher hook spawns its worker process, surge streams stay
# token-identical across the reshape; the drought drains + releases it,
# reaps the subprocess, and the freed chip re-binds the victim
dryrun:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
	  $(PY) -c "import __graft_entry__ as g; g.dryrun_gateway(); \
	  g.dryrun_gateway_tier(); \
	  g.dryrun_spec_serving(); g.dryrun_tracing(); \
	  g.dryrun_http_serving(); g.dryrun_sampled_spec_http(); \
	  g.dryrun_kv_migration(); \
	  g.dryrun_quantized_serving(); \
	  g.dryrun_gateway_pods(); g.dryrun_prefix_tier(); \
	  g.dryrun_disaggregation(); \
	  g.dryrun_controller(); \
	  g.dryrun_multichip(8)"

image:
	docker build -f deploy/Dockerfile -t kubegpu-tpu:latest .

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
